"""Compiled-artifact verification: assert the invariants ON the lowered
programs, not just the source.

The AST lint proves the source doesn't *write* a host sync; this pass
proves the artifact doesn't *contain* one — the two fail independently
(a dependency could lower a callback; a refactor could drop donation
without touching any linted line). Checks, all on the tiny test config
so they run in CI on CPU in seconds:

  * zero host callbacks (`pure_callback` / `io_callback` /
    `debug_callback` custom calls) in the solo AND constrained decode
    StableHLO — the zero-Python-per-token contract;
  * the decode loop really is compiled (a `stablehlo.while` is present —
    an unrolled or host-driven loop would be a silent regression);
  * donation aliasing is ACTUALLY present for the KV cache (the
    `tf.aliasing_output` attr on the donated inputs — `donate_argnames`
    that XLA rejects degrades to a copy with only a warning);
  * a two-invocation recompile guard: calling decode again with
    different *traced* values (limit, start_pos) must not grow the jit
    cache — a shape or weak-type drift here means compile-per-step in
    production;
  * on a pp mesh (gated on `jax.shard_map`, like every pp test): the
    decode program contains the ring `collective_permute` and no
    callbacks;
  * the `wire-dtype` family (EngineConfig.pp_wire_quant): with the int8
    wire ON, every full-rank `collective_permute` operand is si8 (fp32
    allowed only for the rank-(n-1) scale companions) — the byte claim
    machine-checked on the artifact — plus callbacks/donation/
    recompile-guard legs for the quantized program; with the knob OFF,
    no int8 ships at all (the bit-identity contract);
  * the `adapter-mixed` family (engine/adapters.py paged runtime LoRA):
    the adapter-conditioned mixed launch — per-slot page ids as a
    traced device gather — keeps zero callbacks, pool donation,
    IDENTICAL StableHLO across adapter mixes, and a no-recompile
    execution guard: one compiled program serves any adapter mix.

Reused by tests/test_analysis.py and tests/test_constrained_decode.py —
one implementation of the artifact assertions.
"""

from __future__ import annotations

import functools

_CALLBACK_MARKERS = ("callback",)  # pure/io/debug callback custom calls


def check_no_host_callbacks(text: str) -> list:
    """Problems if the lowered text contains any host-callback custom
    call. `text`: StableHLO (`lowered.as_text()`)."""
    low = text.lower()
    out = []
    for marker in _CALLBACK_MARKERS:
        if marker in low:
            n = low.count(marker)
            out.append(
                f"lowered program contains {n} {marker!r} occurrence(s) — "
                f"the decode hot path must run zero host callbacks"
            )
    return out


def check_while_compiled(text: str) -> list:
    if "stablehlo.while" not in text and "while" not in text.lower():
        return ["no while op in the lowered decode — the loop is not "
                "compiled (unrolled or host-driven?)"]
    return []


def check_donation(text: str, min_aliased: int = 1) -> list:
    """Donation must survive lowering: each donated input carries a
    `tf.aliasing_output` attr in the StableHLO. min_aliased: the number
    of cache leaves expected to alias (a {k, v} cache has 2)."""
    n = text.count("tf.aliasing_output")
    if n < min_aliased:
        return [
            f"only {n} aliased input(s) in the lowered program, expected "
            f">= {min_aliased} — cache donation was dropped (XLA will "
            f"copy the cache every step)"
        ]
    return []


def count_cache_leaves(cache) -> int:
    import jax

    return len(jax.tree.leaves(cache))


@functools.lru_cache(maxsize=1)
def tiny_engine():
    """The shared tiny solo engine (test-llama-tiny: vocab 256, dim 64 —
    compiles in seconds on CPU)."""
    from ..config import EngineConfig
    from ..engine.engine import InferenceEngine
    from ..models.registry import get_model_config

    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )


def _decode_args(engine, constraint=None, limit=8, start_pos=4):
    import jax
    import jax.numpy as jnp

    from ..engine import generate as G

    cfg = engine.cfg
    cache = engine.backend.init_cache(1, cfg.max_seq_len)
    return (
        cfg, engine.backend.params, jnp.zeros((1,), jnp.int32), cache,
        jnp.int32(start_pos), jnp.int32(limit), jax.random.PRNGKey(0),
        G.default_sampling(greedy=True), None, None, None, None, constraint,
    )


def lower_solo_decode(engine=None, constrained: bool = False,
                      max_steps: int = 16) -> str:
    """StableHLO text of the REAL solo decode program (G.decode with its
    declared donation — not a re-wrap, which would silently drop
    donate_argnames and void the aliasing check)."""
    from ..engine import generate as G

    engine = engine or tiny_engine()
    constraint = None
    if constrained:
        art = engine._compile_constraint({"regex": "[ab]{1,8}"})
        cm, ct = art.device_tables()
        import jax.numpy as jnp

        constraint = (jnp.zeros((1,), jnp.int32), cm, ct)
    lowered = G.decode.lower(
        *_decode_args(engine, constraint), max_steps=max_steps
    )
    return lowered.as_text()


def check_no_recompile(engine=None) -> list:
    """Run the decode program twice with different TRACED values; the jit
    cache must not grow (a second entry means some 'traced' input is
    actually specializing the program — compile-per-request in prod)."""
    import jax
    import jax.numpy as jnp

    from ..engine import generate as G

    engine = engine or tiny_engine()
    cfg = engine.cfg
    sampling = G.default_sampling(greedy=True)

    def run(limit, start_pos, seed):
        cache = engine.backend.init_cache(1, cfg.max_seq_len)
        return G.decode(
            cfg, engine.backend.params, jnp.zeros((1,), jnp.int32), cache,
            jnp.int32(start_pos), jnp.int32(limit), jax.random.PRNGKey(seed),
            sampling, None, None, None, None, None, max_steps=16,
        )

    out = run(4, 2, 0)
    jax.block_until_ready(out[0])
    size_after_first = G.decode._cache_size()
    out = run(9, 5, 3)
    jax.block_until_ready(out[0])
    size_after_second = G.decode._cache_size()
    if size_after_second > size_after_first:
        return [
            f"decode recompiled across invocations with different traced "
            f"values (jit cache grew {size_after_first} -> "
            f"{size_after_second}) — limit/start_pos/key must stay traced"
        ]
    return []


def _ragged_args(engine, tail: int, width: int = 32):
    """Operand tuple for the ragged paged prefill program
    (engine/paged.prefill_ragged_paged) on the tiny config with
    attn_impl="pallas", a fresh pool (donated per run) and a `tail`-token
    prompt padded to the fixed launch `width`."""
    import jax
    import jax.numpy as jnp

    from ..engine import generate as G
    from ..engine import paged as EP

    cfg = engine.cfg.replace(attn_impl="pallas")
    bs, MB = 16, 8
    pool = EP.init_pool(cfg, MB + 2, bs)
    table = jnp.asarray([list(range(1, MB + 1))], jnp.int32)
    meta, tok_row, tok_pos, _, _ = EP.build_ragged_meta(
        [(0, 0, tail, EP.RAGGED_PREFILL)], width=width, tile=8
    )
    toks = jnp.asarray([1] * tail + [0] * (width - tail), jnp.int32)
    return (
        cfg, engine.backend.params, toks, jnp.asarray(tok_row),
        jnp.asarray(tok_pos), jnp.asarray(meta), pool, table,
        jnp.int32(tail - 1), jax.random.PRNGKey(0),
        G.default_sampling(greedy=True),
    )


def lower_ragged_prefill(engine=None, tail: int = 20, width: int = 32) -> str:
    """StableHLO of the REAL ragged paged prefill launch (the program the
    paged admission path dispatches when engine_cfg.ragged_prefill is on)
    — declared donation intact, ragged kernel selected."""
    from ..engine import paged as EP

    engine = engine or tiny_engine()
    return EP.prefill_ragged_paged.lower(
        *_ragged_args(engine, tail, width)
    ).as_text()


def check_ragged_shape_stability(engine=None) -> list:
    """Two DIFFERENT tail lengths must lower to the IDENTICAL program:
    the tail only moves traced values (token contents, metadata, the
    sample position), never shapes. Identical StableHLO text is the
    artifact-level proof that one compiled launch serves any prompt tail
    — the property that deletes the prefill-bucket ladder."""
    engine = engine or tiny_engine()
    a = lower_ragged_prefill(engine, tail=20)
    b = lower_ragged_prefill(engine, tail=27)
    if a != b:
        return [
            "ragged prefill lowered DIFFERENT programs for tails 20 and "
            "27 — some per-tail value became shape-specializing "
            "(compile-per-prompt-length in production)"
        ]
    return []


def check_ragged_no_recompile(engine=None) -> list:
    """Execute the ragged prefill with two different tail lengths; the
    jit cache must not grow (a second entry means a 'traced' operand is
    specializing the program — the bucket ladder reborn as recompiles)."""
    import jax

    from ..engine import paged as EP

    engine = engine or tiny_engine()
    out = EP.prefill_ragged_paged(*_ragged_args(engine, 20))
    jax.block_until_ready(out[0])
    size_after_first = EP.prefill_ragged_paged._cache_size()
    out = EP.prefill_ragged_paged(*_ragged_args(engine, 27))
    jax.block_until_ready(out[0])
    size_after_second = EP.prefill_ragged_paged._cache_size()
    if size_after_second > size_after_first:
        return [
            f"ragged prefill recompiled across tail lengths (jit cache "
            f"grew {size_after_first} -> {size_after_second}) — the "
            f"launch width must be the only shape"
        ]
    return []


def _mixed_args(engine, n_decode: int, chunk: int, width: int = 32):
    """Operand tuple for the mixed scheduler step program
    (engine/paged.mixed_step_ragged) on the tiny config: `n_decode`
    decode rows + one `chunk`-token prefill chunk on a 2-slot fleet with
    attn_impl="pallas" — the launch the chunked-prefill scheduler
    dispatches every step."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engine import generate as G
    from ..engine import paged as EP

    cfg = engine.cfg.replace(attn_impl="pallas")
    bs, MB, B = 16, 4, 2
    pool = EP.init_pool(cfg, 2 * MB + 2, bs)
    table = jnp.asarray(
        [list(range(1, MB + 1)), list(range(MB + 1, 2 * MB + 1))], jnp.int32
    )
    entries = [
        (b, 4 + b, 1, EP.RAGGED_DECODE) for b in range(n_decode)
    ] + [(1, 0, chunk, EP.RAGGED_PREFILL)]
    meta, tok_row, tok_pos, offsets, _ = EP.build_ragged_meta(
        entries, width=width, tile=8,
    )
    toks = np.zeros((width,), np.int32)
    dec_flag = np.zeros((width,), bool)
    dec_idx = np.zeros((B,), np.int32)
    for b in range(n_decode):
        dec_flag[offsets[b]] = True
        dec_idx[b] = offsets[b]
    off = offsets[n_decode]
    toks[off : off + chunk] = 1
    state, sparams = G.init_slots(B, cfg.vocab_size)
    arm = EP.idle_mixed_arm(B, cfg.vocab_size)._replace(
        on=jnp.asarray([False, True]),
        idx=jnp.asarray([0, off + chunk - 1], jnp.int32),
        prompt_len=jnp.asarray([0, chunk], jnp.int32),
        max_tokens=jnp.asarray([0, 4], jnp.int32),
    )
    return (
        cfg, engine.backend.params, jnp.asarray(toks), jnp.asarray(tok_row),
        jnp.asarray(tok_pos), jnp.asarray(dec_flag), jnp.asarray(meta),
        pool, table, state, sparams, jax.random.PRNGKey(0),
        jnp.asarray(dec_idx), arm,
    )


def lower_mixed_step(engine=None, n_decode: int = 1, chunk: int = 9) -> str:
    """StableHLO of the REAL mixed scheduler launch (decode rows +
    prefill chunks in one program) — declared pool donation intact."""
    from ..engine import paged as EP

    engine = engine or tiny_engine()
    return EP.mixed_step_ragged.lower(
        *_mixed_args(engine, n_decode, chunk)
    ).as_text()


def check_mixed_shape_stability(engine=None) -> list:
    """Two DIFFERENT launch compositions (decode-row count, chunk length)
    must lower to the IDENTICAL program: the scheduler re-plans the mix
    every step, so any composition-dependent shape would recompile
    per step — the chunked-prefill equivalent of the bucket ladder."""
    engine = engine or tiny_engine()
    a = lower_mixed_step(engine, n_decode=1, chunk=9)
    b = lower_mixed_step(engine, n_decode=2, chunk=14)
    if a != b:
        return [
            "mixed scheduler step lowered DIFFERENT programs for two "
            "launch compositions — some per-step plan value became "
            "shape-specializing (compile-per-step in production)"
        ]
    return []


def check_mixed_no_recompile(engine=None) -> list:
    """Execute the mixed step with two different compositions; the jit
    cache must not grow."""
    import jax

    from ..engine import paged as EP

    engine = engine or tiny_engine()
    out = EP.mixed_step_ragged(*_mixed_args(engine, 1, 9))
    jax.block_until_ready(out[0])
    size_after_first = EP.mixed_step_ragged._cache_size()
    out = EP.mixed_step_ragged(*_mixed_args(engine, 2, 14))
    jax.block_until_ready(out[0])
    size_after_second = EP.mixed_step_ragged._cache_size()
    if size_after_second > size_after_first:
        return [
            f"mixed scheduler step recompiled across launch compositions "
            f"(jit cache grew {size_after_first} -> {size_after_second}) — "
            f"the launch width must be the only shape"
        ]
    return []


def _spec_mixed_args(engine, n_spec: int, n_draft: int, chunk: int,
                     width: int = 32, k_max: int = 4,
                     device_meta: bool = False):
    """Operand tuple for the SPECULATIVE mixed scheduler step: the
    _mixed_args fleet plus `n_spec` verify rows of `n_draft` drafts each
    (n-gram mode — the drafts ride the host token plan). The accept
    pattern is pure DATA (token contents vs the model's argmax), so
    every composition must share one compiled program. With
    device_meta=True the decode/verify rows' positions are marked for
    on-device substitution (engine/paged.DeviceMeta) — the derivation
    pattern and the adaptive per-slot K are plan data too, so every
    (accept pattern, K) pair must share the one device-meta program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engine import generate as G
    from ..engine import paged as EP

    cfg = engine.cfg.replace(attn_impl="pallas")
    bs, MB, B = 16, 4, 2
    pool = EP.init_pool(cfg, 2 * MB + 2, bs)
    table = jnp.asarray(
        [list(range(1, MB + 1)), list(range(MB + 1, 2 * MB + 1))], jnp.int32
    )
    K1 = k_max + 1
    entries = [
        (b, 4 + b, (1 + n_draft) if b < n_spec else 1,
         EP.RAGGED_PREFILL if b < n_spec else EP.RAGGED_DECODE)
        for b in range(B)
    ] + [(1, 0, chunk, EP.RAGGED_PREFILL)]
    meta, tok_row, tok_pos, offsets, _ = EP.build_ragged_meta(
        entries, width=width, tile=8,
    )
    toks = np.zeros((width,), np.int32)
    dec_flag = np.zeros((width,), bool)
    dec_idx = np.zeros((B,), np.int32)
    dec_on = np.zeros((B,), bool)
    sp_on = np.zeros((B,), bool)
    sp_idx = np.zeros((B, K1), np.int32)
    sp_nd = np.zeros((B,), np.int32)
    for b in range(B):
        off = offsets[b]
        dec_flag[off] = True
        if b < n_spec:
            sp_on[b] = True
            sp_nd[b] = n_draft
            idxs = off + np.arange(K1, dtype=np.int32)
            idxs[n_draft + 1:] = off + n_draft
            sp_idx[b] = idxs
            toks[off + 1 : off + 1 + n_draft] = 1 + np.arange(n_draft)
        else:
            dec_on[b] = True
            dec_idx[b] = off
    off = offsets[B]
    toks[off : off + chunk] = 1
    state, sparams = G.init_slots(B, cfg.vocab_size)
    state = state._replace(
        active=jnp.ones((B,), bool), remaining=jnp.full((B,), 6, jnp.int32),
        pos=jnp.asarray([4, 5], jnp.int32),
    )
    arm = EP.idle_mixed_arm(B, cfg.vocab_size)
    spec = EP.SpecPlan(
        jnp.asarray(dec_on), jnp.asarray(sp_on), jnp.asarray(sp_idx),
        jnp.asarray(sp_nd),
    )
    base = (
        cfg, engine.backend.params, jnp.asarray(toks), jnp.asarray(tok_row),
        jnp.asarray(tok_pos), jnp.asarray(dec_flag), jnp.asarray(meta),
        pool, table, state, sparams, jax.random.PRNGKey(0),
        jnp.asarray(dec_idx), arm, spec,
    )
    if not device_meta:
        return base
    t_on, t_off, k_on, k_off = EP.build_device_meta(
        entries, offsets, B, width=width, tile=8,
    )
    dev = EP.DeviceMeta(
        jnp.asarray(t_on), jnp.asarray(t_off),
        jnp.asarray(k_on), jnp.asarray(k_off),
    )
    return base + (None, dev)  # spec_toks=None, dev


def lower_spec_mixed_step(engine=None, n_spec: int = 1, n_draft: int = 3,
                          chunk: int = 9) -> str:
    """StableHLO of the REAL speculative mixed launch (verify rows +
    decode rows + prefill chunks in one program) — declared pool
    donation intact, traced accept/reject inside."""
    from ..engine import paged as EP

    engine = engine or tiny_engine()
    return EP.mixed_step_ragged.lower(
        *_spec_mixed_args(engine, n_spec, n_draft, chunk)
    ).as_text()


def check_spec_mixed_shape_stability(engine=None) -> list:
    """Two DIFFERENT speculative compositions (verify-row count, draft
    length, chunk length) must lower to the IDENTICAL program: accept
    patterns and per-slot draft lengths are plan DATA — any
    composition-dependent shape would recompile per accept pattern."""
    engine = engine or tiny_engine()
    a = lower_spec_mixed_step(engine, n_spec=1, n_draft=3, chunk=9)
    b = lower_spec_mixed_step(engine, n_spec=2, n_draft=2, chunk=14)
    if a != b:
        return [
            "speculative mixed step lowered DIFFERENT programs for two "
            "verify-row compositions — some per-step spec plan value "
            "became shape-specializing (compile-per-accept-pattern in "
            "production)"
        ]
    return []


def check_spec_mixed_no_recompile(engine=None) -> list:
    """Execute the speculative mixed step with two different verify
    compositions; the jit cache must not grow (one compiled program for
    every accept pattern — the machine check ISSUE 13 names)."""
    import jax

    from ..engine import paged as EP

    engine = engine or tiny_engine()
    out = EP.mixed_step_ragged(*_spec_mixed_args(engine, 1, 3, 9))
    jax.block_until_ready(out[0])
    size_after_first = EP.mixed_step_ragged._cache_size()
    out = EP.mixed_step_ragged(*_spec_mixed_args(engine, 2, 2, 14))
    jax.block_until_ready(out[0])
    size_after_second = EP.mixed_step_ragged._cache_size()
    if size_after_second > size_after_first:
        return [
            f"speculative mixed step recompiled across verify "
            f"compositions (jit cache grew {size_after_first} -> "
            f"{size_after_second}) — accept patterns must stay traced "
            f"data"
        ]
    return []


def lower_spec_devmeta_step(engine=None, n_spec: int = 1, n_draft: int = 3,
                            chunk: int = 9) -> str:
    """StableHLO of the DEVICE-META speculative mixed launch (ISSUE 15:
    decode/verify positions substituted on device from slot state, the
    program the unfrozen back-to-back serving path dispatches)."""
    from ..engine import paged as EP

    engine = engine or tiny_engine()
    return EP.mixed_step_ragged.lower(
        *_spec_mixed_args(engine, n_spec, n_draft, chunk, device_meta=True)
    ).as_text()


def check_spec_devmeta_shape_stability(engine=None) -> list:
    """Two DIFFERENT device-meta compositions — verify-row count AND
    draft length (the adaptive-K throttle's output) — must lower to the
    IDENTICAL program: derivation masks and per-slot K are plan data,
    so a composition-dependent shape would recompile per accept pattern
    or per adaptive-K change."""
    engine = engine or tiny_engine()
    a = lower_spec_devmeta_step(engine, n_spec=1, n_draft=3, chunk=9)
    b = lower_spec_devmeta_step(engine, n_spec=2, n_draft=2, chunk=14)
    if a != b:
        return [
            "device-meta speculative step lowered DIFFERENT programs for "
            "two verify/K compositions — some derivation or adaptive-K "
            "value became shape-specializing (compile-per-accept-pattern "
            "/ compile-per-K in production)"
        ]
    return []


def check_spec_devmeta_no_recompile(engine=None) -> list:
    """Execute the device-meta speculative step with two different
    verify compositions AND adaptive-K values; the jit cache must not
    grow — one compiled program across accept patterns and K values,
    the ISSUE 15 machine check."""
    import jax

    from ..engine import paged as EP

    engine = engine or tiny_engine()
    out = EP.mixed_step_ragged(
        *_spec_mixed_args(engine, 1, 3, 9, device_meta=True)
    )
    jax.block_until_ready(out[0])
    size_after_first = EP.mixed_step_ragged._cache_size()
    out = EP.mixed_step_ragged(
        *_spec_mixed_args(engine, 2, 2, 14, device_meta=True)
    )
    jax.block_until_ready(out[0])
    size_after_second = EP.mixed_step_ragged._cache_size()
    if size_after_second > size_after_first:
        return [
            f"device-meta speculative step recompiled across verify/K "
            f"compositions (jit cache grew {size_after_first} -> "
            f"{size_after_second}) — derivation masks and draft lengths "
            f"must stay traced data"
        ]
    return []


@functools.lru_cache(maxsize=1)
def tiny_adapter_engine():
    """tiny_engine plus the paged runtime-LoRA leaves (slots=4, rank=4)
    and an attached AdapterPool — the engine the adapter-mixed legs
    lower against. Separate from tiny_engine: the extra leaves change
    the params pytree, so sharing would shadow its cached programs."""
    from ..config import EngineConfig
    from ..engine.adapters import attach_adapter_pool
    from ..engine.engine import InferenceEngine
    from ..models.registry import get_model_config

    cfg = get_model_config("test-llama-tiny")
    engine = InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )
    attach_adapter_pool(engine, slots=4, rank=4)
    return engine


def lower_adapter_mixed_step(engine=None, pages=(0, 1), n_decode: int = 1,
                             chunk: int = 9) -> str:
    """StableHLO of the ADAPTER-conditioned mixed scheduler launch: the
    ordinary mixed step plus the per-slot adapter page ids as a traced
    operand (engine/adapters.py; page 0 = the base page)."""
    import jax.numpy as jnp

    from ..engine import paged as EP

    engine = engine or tiny_adapter_engine()
    return EP.mixed_step_ragged.lower(
        *_mixed_args(engine, n_decode, chunk),
        pages=jnp.asarray(pages, jnp.int32),
    ).as_text()


def check_adapter_mixed_shape_stability(engine=None) -> list:
    """Two DIFFERENT adapter mixes (per-slot page assignments) on two
    DIFFERENT launch compositions must lower to the IDENTICAL program:
    page ids are traced DATA riding a device gather, so any mix-
    dependent shape would recompile per adapter mix — the multi-tenant
    equivalent of the bucket ladder."""
    engine = engine or tiny_adapter_engine()
    a = lower_adapter_mixed_step(engine, pages=(0, 1), n_decode=1, chunk=9)
    b = lower_adapter_mixed_step(engine, pages=(3, 2), n_decode=2, chunk=14)
    if a != b:
        return [
            "adapter mixed step lowered DIFFERENT programs for two "
            "adapter mixes — some page assignment became shape-"
            "specializing (compile-per-adapter-mix in production)"
        ]
    return []


def check_adapter_mixed_no_recompile(engine=None) -> list:
    """Execute the adapter mixed step with two different adapter mixes
    AND launch compositions; the jit cache must not grow — ONE compiled
    program serves any adapter mix, the acceptance invariant."""
    import jax
    import jax.numpy as jnp

    from ..engine import paged as EP

    engine = engine or tiny_adapter_engine()
    out = EP.mixed_step_ragged(
        *_mixed_args(engine, 1, 9), pages=jnp.asarray([0, 1], jnp.int32)
    )
    jax.block_until_ready(out[0])
    size_after_first = EP.mixed_step_ragged._cache_size()
    out = EP.mixed_step_ragged(
        *_mixed_args(engine, 2, 14), pages=jnp.asarray([3, 2], jnp.int32)
    )
    jax.block_until_ready(out[0])
    size_after_second = EP.mixed_step_ragged._cache_size()
    if size_after_second > size_after_first:
        return [
            f"adapter mixed step recompiled across adapter mixes (jit "
            f"cache grew {size_after_first} -> {size_after_second}) — "
            f"page ids must stay traced data"
        ]
    return []


def pp_available() -> bool:
    import jax

    return hasattr(jax, "shard_map") and len(jax.devices()) >= 2


@functools.lru_cache(maxsize=2)
def _pp_engine(wire_quant=None):
    """Cached 2-stage pp engine on the tiny config (one per wire mode —
    the wire-dtype family lowers the SAME decode with the knob on and
    off). Caller must gate on pp_available()."""
    from ..config import EngineConfig, MeshConfig
    from ..runtime import create_engine

    return create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32,), pp_wire_quant=wire_quant
        ),
    )


def lower_pp_decode(max_steps: int = 4, wire_quant=None) -> str:
    """StableHLO of the pp-ring decode step (2 stages, tiny config).
    Caller must gate on pp_available()."""
    import jax
    import jax.numpy as jnp

    from ..engine import generate as G

    engine = _pp_engine(wire_quant)
    backend = engine.backend
    cache = backend.init_cache(1, engine.cfg.max_seq_len)
    fn = backend._build_decode(max_steps)
    lowered = fn.lower(
        backend.shared, backend.layers, jnp.zeros((1,), jnp.int32), cache,
        jnp.int32(4), jnp.int32(max_steps), jax.random.PRNGKey(0),
        G.default_sampling(greedy=True),
    )
    return lowered.as_text()


def _collective_operands(text: str, opname: str) -> list:
    """(rank, dtype, line) of every `opname` collective operand in the
    lowered text — the function-type clause `: (tensor<...>) -> ...`.
    (The attribute dict's `replica_groups ... : tensor<...>` has no
    paren wrapper, so the regex cannot mistake it for an operand.)"""
    import re

    ops = []
    for line in text.splitlines():
        if opname not in line:
            continue
        m = re.search(r":\s*\(tensor<([^>]+)>\)", line)
        if not m:
            continue
        parts = m.group(1).split("x")
        ops.append((len(parts) - 1, parts[-1], line.strip()[:110]))
    return ops


def _collective_permute_operands(text: str) -> list:
    return _collective_operands(text, "collective_permute")


def check_wire_dtype(text: str) -> list:
    """With pp_wire_quant="int8", every collective_permute on the pp axis
    must ship si8 DATA: the full-rank ([B, T, D]) operands are i8, and
    any non-i8 operand is a rank-(n-1) scale companion (one fp32 per
    token row). This is the machine check that the wire really carries
    int8 — the byte claim, proven on the artifact."""
    ops = _collective_permute_operands(text)
    if not ops:
        return ["no collective_permute in the wire-quantized pp decode "
                "program — the ring hand-off is missing"]
    data_rank = max(r for r, _, _ in ops)
    problems = []
    if not any(d == "i8" for r, d, _ in ops if r == data_rank):
        problems.append(
            "no si8 activation collective_permute — the pp wire is not "
            "int8 despite pp_wire_quant"
        )
    for r, d, line in ops:
        if r == data_rank and d != "i8":
            problems.append(
                f"full-rank collective_permute ships {d}, not si8: {line}"
            )
    return problems


def check_wire_off_exact(text: str) -> list:
    """With the knob OFF (the default), NO collective_permute may carry
    i8 — the off path must be the bit-identical unquantized wire."""
    bad = [
        line for r, d, line in _collective_permute_operands(text) if d == "i8"
    ]
    return [
        f"pp_wire_quant=None program ships int8 on the wire (the off "
        f"path must be bit-identical): {line}" for line in bad
    ]


def check_wire_no_recompile() -> list:
    """Run the wire-quantized pp decode twice with different TRACED
    values; neither the variant memo nor the jit cache may grow — the
    quantized programs obey the same one-program-per-topology contract
    as the plain wire."""
    import jax
    import jax.numpy as jnp

    from ..engine import generate as G

    engine = _pp_engine("int8")
    backend = engine.backend
    sampling = G.default_sampling(greedy=True)

    def run(limit, start_pos, seed):
        cache = backend.init_cache(1, engine.cfg.max_seq_len)
        return backend.decode(
            jnp.zeros((1,), jnp.int32), cache, jnp.int32(start_pos),
            jnp.int32(limit), jax.random.PRNGKey(seed), sampling,
            max_steps=8,
        )

    out = run(4, 2, 0)
    jax.block_until_ready(out[0])
    variants = len(backend._decode_cache)
    size_first = next(iter(backend._decode_cache.values()))._cache_size()
    out = run(6, 3, 1)
    jax.block_until_ready(out[0])
    size_second = next(iter(backend._decode_cache.values()))._cache_size()
    if len(backend._decode_cache) > variants or size_second > size_first:
        return [
            f"wire-quantized pp decode recompiled across invocations "
            f"(programs {variants} -> {len(backend._decode_cache)}, jit "
            f"cache {size_first} -> {size_second}) — quantize/dequantize "
            f"must stay inside the one compiled program"
        ]
    return []


def check_gather_dtype(text: str) -> list:
    """The pp decode program's all_gather is the vocab logits gather
    (the FAT_INVENTORY edge): its operand must be fp32 in BOTH wire
    modes — the wire knob quantizes the ring hand-off, never the
    logits path (sampling parity depends on exact fp32 logits)."""
    ops = _collective_operands(text, "all_gather")
    if not ops:
        return ["no all_gather in the pp decode program — the vocab-"
                "sharded logits gather (parallel/vocab.unembed_sharded) "
                "is missing"]
    return [
        f"all_gather ships {d}, not f32 — the logits gather must stay "
        f"full precision (quantizing it is the tracked FAT_INVENTORY "
        f"worklist, not a silent wire side effect): {line}"
        for r, d, line in ops if d != "f32"
    ]


def check_a2a_dtype(text: str, *, wire: bool) -> list:
    """Operand dtypes of the ulysses all_to_all exchanges (parallel/
    ring.ulysses_attend). With `wire` on, the K and V head-scatter a2a
    ship si8 data (their fp32 scale companions ride rank-(n-1) a2a);
    off, nothing on the sp wire may be int8 — the same bit-identity
    contract as the pp ring, proven per-primitive on the artifact."""
    ops = _collective_operands(text, "all_to_all")
    if not ops:
        return ["no all_to_all in the sp attend program — the ulysses "
                "head<->sequence exchange is missing"]
    data_rank = max(r for r, _, _ in ops)
    si8 = [line for r, d, line in ops if r == data_rank and d == "i8"]
    if wire and len(si8) < 2:
        return [
            f"wire-quantized ulysses attend ships {len(si8)} si8 "
            f"full-rank all_to_all (expected >= 2: K and V) — the sp "
            f"wire is not int8 despite the knob"
        ]
    if not wire and any(d == "i8" for _, d, _ in ops):
        return [
            f"wire=off ulysses attend ships int8 on the sp wire (the "
            f"off path must be bit-identical): {next(l for _, d, l in ops if d == 'i8')}"
        ]
    return []


def check_comms_graph(text: str, topology: str) -> list:
    """Cross-validate the lowered program against the statically derived
    edge set (analysis/comms.HLO_PREDICTED): every predicted StableHLO
    collective kind appears, and nothing unpredicted appears. This is
    the twin that keeps the static comms model honest — a new collective
    in the source shows up here before it ships unaccounted."""
    from .comms import STABLEHLO_COLLECTIVES, predicted_hlo_ops

    found = {k for k in STABLEHLO_COLLECTIVES if k in text}
    want = predicted_hlo_ops(topology)
    problems = []
    for k in sorted(want - found):
        problems.append(
            f"{topology}: predicted collective {k} absent from the "
            f"lowered program — the static graph "
            f"(analysis/comms.HLO_PREDICTED) is stale"
        )
    for k in sorted(found - want):
        problems.append(
            f"{topology}: lowered program contains unpredicted "
            f"collective {k} — add the edge to analysis/comms."
            f"HLO_PREDICTED (and the link table, if it moves "
            f"activation bytes)"
        )
    return problems


def lower_sp_attend(wire: bool = False) -> str:
    """StableHLO of one ulysses attention body shard_mapped over a
    2-device sp mesh (tiny head counts: H=4, KV=2 scatter over sp=2).
    Caller must gate on pp_available() — same capability set."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel.mesh import AXIS_SP
    from ..parallel.ring import ulysses_attend

    mesh = Mesh(np.array(jax.devices()[:2]), (AXIS_SP,))
    B, T, H, KV, Dh = 1, 8, 4, 2, 16
    q = jnp.zeros((B, T, H, Dh), jnp.float32)
    k = jnp.zeros((B, T, KV, Dh), jnp.float32)
    v = jnp.zeros((B, T, KV, Dh), jnp.float32)

    def body(q, k, v):
        return ulysses_attend(q, k, v, AXIS_SP, wire=wire)

    shmapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, AXIS_SP), P(None, AXIS_SP), P(None, AXIS_SP)),
        out_specs=P(None, AXIS_SP),
        check_vma=False,
    )
    return jax.jit(shmapped).lower(q, k, v).as_text()


def check_pp_ring(text: str, max_per_step: int = 2) -> list:
    """The pp decode program must hand activations around the ring: at
    least one collective_permute (the lax.ppermute microstep hop), and a
    small rolled count — an unrolled ring would multiply it per
    microstep."""
    n = text.count("collective_permute")
    if n < 1:
        return ["no collective_permute in the pp decode program — the "
                "ring hand-off is missing (activations moving over host?)"]
    if n > max_per_step:
        return [
            f"{n} collective_permute ops in the pp decode program "
            f"(expected <= {max_per_step}) — the microstep ring appears "
            f"unrolled (compile time and program size scale with steps)"
        ]
    return []


def run_hlo_checks() -> dict:
    """The full artifact suite; {check_name: [problems]} (empty list ==
    pass). The CLI and the CI gate consume this."""
    results = {}
    engine = tiny_engine()

    solo = lower_solo_decode(engine)
    results["solo-decode-callbacks"] = check_no_host_callbacks(solo)
    results["solo-decode-while"] = check_while_compiled(solo)
    cache = engine.backend.init_cache(1, engine.cfg.max_seq_len)
    results["solo-decode-donation"] = check_donation(
        solo, min_aliased=count_cache_leaves(cache)
    )

    constrained = lower_solo_decode(engine, constrained=True)
    results["constrained-decode-callbacks"] = check_no_host_callbacks(
        constrained
    )
    results["constrained-decode-donation"] = check_donation(
        constrained, min_aliased=count_cache_leaves(cache)
    )

    results["recompile-guard"] = check_no_recompile(engine)

    # ragged paged ingest (engine/paged.py + the ragged kernel): the
    # admission path must stay ONE host-sync-free launch per chunk with
    # no per-tail-shape recompile — the properties that replaced the
    # prefill-bucket ladder
    ragged = lower_ragged_prefill(engine)
    results["ragged-prefill-callbacks"] = check_no_host_callbacks(ragged)
    results["ragged-shape-stability"] = check_ragged_shape_stability(engine)
    results["ragged-recompile-guard"] = check_ragged_no_recompile(engine)

    # mixed scheduler step (engine/scheduler.py + engine/paged.
    # mixed_step_ragged): the chunked-prefill launch must stay ONE
    # host-sync-free program across every per-step launch composition —
    # the scheduler re-plans the decode/prefill mix every step, so a
    # composition-dependent shape would compile per step
    mixed = lower_mixed_step(engine)
    results["sched-mixed-callbacks"] = check_no_host_callbacks(mixed)
    results["sched-mixed-donation"] = check_donation(mixed, min_aliased=2)
    results["sched-mixed-shape-stability"] = check_mixed_shape_stability(
        engine
    )
    results["sched-mixed-recompile-guard"] = check_mixed_no_recompile(engine)

    # speculative mixed step (ISSUE 13: draft-then-verify inside the
    # mixed launch): the verify rows' accept/reject must stay fully
    # traced — zero host callbacks, pool donation intact, and ONE
    # compiled program across every accept pattern / verify composition
    spec_mixed = lower_spec_mixed_step(engine)
    results["spec-mixed-callbacks"] = check_no_host_callbacks(spec_mixed)
    results["spec-mixed-donation"] = check_donation(spec_mixed, min_aliased=2)
    results["spec-mixed-shape-stability"] = check_spec_mixed_shape_stability(
        engine
    )
    results["spec-mixed-recompile-guard"] = check_spec_mixed_no_recompile(
        engine
    )

    # device-meta speculative step (ISSUE 15: decode/verify q_start and
    # positions derived on device from slot state — the unfrozen
    # back-to-back launch path): zero host callbacks, pool donation, and
    # ONE compiled program across accept patterns AND adaptive-K values
    spec_dev = lower_spec_devmeta_step(engine)
    results["spec-devmeta-callbacks"] = check_no_host_callbacks(spec_dev)
    results["spec-devmeta-donation"] = check_donation(spec_dev, min_aliased=2)
    results["spec-devmeta-shape-stability"] = (
        check_spec_devmeta_shape_stability(engine)
    )
    results["spec-devmeta-recompile-guard"] = check_spec_devmeta_no_recompile(
        engine
    )

    # adapter-conditioned mixed step (engine/adapters.py: paged runtime
    # LoRA): the per-slot page ids are traced data riding a device
    # gather, so the multi-tenant launch must stay ONE host-sync-free
    # donated program across every adapter mix — the acceptance
    # invariant of the adapter subsystem, proven on the artifact
    adapter_engine = tiny_adapter_engine()
    adapter_mixed = lower_adapter_mixed_step(adapter_engine)
    results["adapter-mixed-callbacks"] = check_no_host_callbacks(
        adapter_mixed
    )
    results["adapter-mixed-donation"] = check_donation(
        adapter_mixed, min_aliased=2
    )
    results["adapter-mixed-shape-stability"] = (
        check_adapter_mixed_shape_stability(adapter_engine)
    )
    results["adapter-mixed-recompile-guard"] = (
        check_adapter_mixed_no_recompile(adapter_engine)
    )

    if pp_available():
        pp = lower_pp_decode()
        results["pp-decode-callbacks"] = check_no_host_callbacks(pp)
        results["pp-decode-ring"] = check_pp_ring(pp)
        # wire-dtype family (EngineConfig.pp_wire_quant, ops/
        # wire_quant.py): knob OFF must ship NO int8 on the ring (the
        # bit-identity contract, checked on the artifact); knob ON must
        # ship si8 data on every full-rank collective_permute (fp32 only
        # for the rank-(n-1) scale companions), with the usual
        # callbacks / donation / recompile-guard legs on the quantized
        # program
        results["wire-dtype-off"] = check_wire_off_exact(pp)
        wired = lower_pp_decode(wire_quant="int8")
        results["wire-dtype"] = check_wire_dtype(wired)
        # data + scale = two rolled collective_permutes per microstep hop
        results["wire-ring"] = check_pp_ring(wired, max_per_step=4)
        results["wire-callbacks"] = check_no_host_callbacks(wired)
        # donation is covered by the donate-cache AST rule for the pp
        # builders — tf.aliasing_output does not survive shard_map
        # lowering text, so the artifact leg would be vacuous here (the
        # plain pp-decode checks skip it for the same reason)
        results["wire-recompile-guard"] = check_wire_no_recompile()
        # comms-graph twin (analysis/comms.HLO_PREDICTED): the statically
        # derived edge set must match the lowered program exactly, in
        # BOTH wire modes — every predicted collective kind appears and
        # nothing unpredicted appears; plus the logits all_gather dtype
        # proof (fp32 both modes — the knob never touches the logits)
        results["comms-graph-pp"] = (
            check_comms_graph(pp, "pp-decode")
            + check_comms_graph(wired, "pp-decode")
        )
        results["gather-dtype"] = (
            check_gather_dtype(pp) + check_gather_dtype(wired)
        )
        # sp twin: the ulysses attention body lowers to all_to_all
        # exchanges only, and the a2a operand dtypes prove the sp wire
        # (int8 K/V data + fp32 scales with `wire` on; zero int8 off)
        sp_off = lower_sp_attend(False)
        sp_on = lower_sp_attend(True)
        results["comms-graph-sp"] = (
            check_comms_graph(sp_off, "sp-attend")
            + check_comms_graph(sp_on, "sp-attend")
        )
        results["a2a-dtype"] = (
            check_a2a_dtype(sp_on, wire=True)
            + check_a2a_dtype(sp_off, wire=False)
        )
    else:
        results["pp-decode (skipped: no jax.shard_map / < 2 devices)"] = []
        results["wire-dtype (skipped: no jax.shard_map / < 2 devices)"] = []
        results["comms-graph (skipped: no jax.shard_map / < 2 devices)"] = []
        results["a2a-dtype (skipped: no jax.shard_map / < 2 devices)"] = []
    return results
