"""Static model of the package's collective graph (the comms contract).

The paper's subject is inter-device activation hand-off, and the next
levers on the ROADMAP (fp8 wire everywhere, multi-host MPMD pipeline)
both need to know exactly which arrays cross which mesh axes at what
dtype and size. This module makes that knowledge machine-checked, the
way callgraph.py did traced reachability:

  * `WIRE_LINKS` — the ONE symbolic bytes-per-launch model of every
    accounted wire link. The backends route `dli_pp_wire_bytes_total`
    accounting through `link_bytes` (parallel/pipeline.py
    `_account_link`), so the counters, the bench `comms_report` leg,
    and the `--comms` CLI report all derive from the same table; a
    hand-maintained per-call-seam copy cannot drift because it no
    longer exists.
  * `wire_link_bytes` — the canonical per-hop formula
    (ops/wire_quant.wire_bytes delegates here).
  * `collect_sites` — an AST walk over every `lax.{ppermute, psum,
    all_gather, all_to_all, psum_scatter, pmax, pmin}` call site plus
    the `wire_ppermute`/`masked_psum` wrappers, with resolved axis
    names and an operand-role taxonomy. The four comms-* rules
    (analysis/rules/comms_*.py) and the report are consumers.
  * `FAT_INVENTORY` — the standing machine-tracked list of collectives
    whose symbolic bytes exceed `FAT_THRESHOLD` with no quantized path
    (the ROADMAP "quantized logits all_gather" worklist as data, not
    prose). comms-fat-collective enforces both directions: a raw wide
    collective must be inventoried or suppressed, and a stale entry
    whose site disappeared is itself a violation.
  * `HLO_PREDICTED` — the per-topology set of StableHLO collective op
    kinds the model predicts; analysis/hlo.py cross-validates lowered
    programs against it (every derived edge appears, nothing
    unpredicted appears).

Import discipline: this module is jax-free (stdlib ast/dataclasses/math
only) so the CLI lint half stays cheap and ops/wire_quant can delegate
its formula here without a cycle. It deliberately does NOT import
config.py (which pulls in jax.numpy): configs are duck-typed through
`params_from_config`.

Role taxonomy (ARCHITECTURE.md "Comms contract"):
  wrapper-internal  raw lax call inside ops/wire_quant itself — the one
                    sanctioned home of raw transfer collectives
  transfer          a wire_ppermute/masked_psum wrapper call (covered)
  axis-size         `psum(1, axis)` — bookkeeping, constant-folded,
                    produces no HLO collective
  weight-reduce     tp/ep partial-sum psums in models/ — classified,
                    not flagged (weights stay resident; not a transfer)
  merge             pmax/pmin control/merge reductions (scalar-class)
  raw               anything else — a lint error on a parallel/
                    transfer path unless suppressed with a reason
"""

from __future__ import annotations

import ast
import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from .callgraph import (
    PackageIndex, build_index, dotted, traced_reachable, _walk_own_body,
)

__all__ = [
    "wire_link_bytes", "LinkSpec", "WIRE_LINKS", "params_from_config",
    "link_bytes", "CollectiveSite", "collect_sites", "declared_axes",
    "FatEntry", "FAT_INVENTORY", "FAT_THRESHOLD", "REFERENCE_PARAMS",
    "HLO_PREDICTED", "STABLEHLO_COLLECTIVES", "predicted_hlo_ops",
    "link_call_sites", "build_report",
]


# -- canonical wire-bytes formula --------------------------------------------

def wire_link_bytes(shape, itemsize: int, hops: int, *, quant: bool) -> int:
    """Bytes one activation of `shape` costs crossing `hops` hand-offs.

    Quantized, a [..., D] tensor ships D int8 + one fp32 scale per
    leading row (the WireQuant pytree: si8 data + f32 scales). This is
    the ONE implementation — ops/wire_quant.wire_bytes delegates here,
    the link table below evaluates through it, and the HLO wire-dtype
    rules prove the lowered programs really ship what it counts."""
    n = math.prod(shape)
    rows = n // shape[-1]
    per_hop = n + 4 * rows if quant else n * itemsize
    return per_hop * hops


# -- the wire-link table ------------------------------------------------------

@dataclass(frozen=True)
class LinkSpec:
    """One accounted wire link: a family of identical hops whose bytes
    are a closed-form function of ModelConfig dims + launch params."""

    name: str          # link id, the `_account_link` key
    path: str          # dli_pp_wire_bytes_total `path` label it feeds
    axis: str          # mesh axis the bytes cross
    transport: str     # wrapper that ships it (wire_ppermute/masked_psum)
    symbolic: str      # human-readable shape x hops formula
    shape: Callable    # params dict -> activation shape tuple
    hops: Callable     # params dict -> hop count


def _links(*specs):
    return {s.name: s for s in specs}


# Launch params (beyond the cfg dims): rows (batch rows), t (tokens per
# row in the shipped window), steps (sample events), draft (speculative
# draft length), bh (broadcast hops), b_m (per-microbatch rows), t_chunk
# (sp sequence chunk), plus topology dp/pp/sp/mb.
WIRE_LINKS = _links(
    LinkSpec(
        "pp-microstep-decode", "microstep", "pp", "wire_ppermute",
        "(max(1, rows/dp), 1, dim) x steps*pp hops",
        lambda p: (max(1, p["rows"] // p["dp"]), 1, p["dim"]),
        lambda p: p["steps"] * p["pp"],
    ),
    LinkSpec(
        "pp-broadcast-decode", "broadcast", "pp", "masked_psum",
        "(max(1, rows/dp), 1, dim) x steps hops",
        lambda p: (max(1, p["rows"] // p["dp"]), 1, p["dim"]),
        lambda p: p["steps"],
    ),
    LinkSpec(
        "pp-microstep-prefill", "microstep", "pp", "wire_ppermute",
        "(rows, t, dim) x pp hops",
        lambda p: (p["rows"], p["t"], p["dim"]),
        lambda p: p["pp"],
    ),
    LinkSpec(
        "pp-broadcast-prefill", "broadcast", "pp", "masked_psum",
        "(rows, 1, dim) x bh hops",
        lambda p: (p["rows"], 1, p["dim"]),
        lambda p: p.get("bh", 1),
    ),
    LinkSpec(
        "pp-microstep-slots", "microstep", "pp", "wire_ppermute",
        "(rows, 1, dim) x steps*pp hops",
        lambda p: (p["rows"], 1, p["dim"]),
        lambda p: p["steps"] * p["pp"],
    ),
    LinkSpec(
        "pp-broadcast-slots", "broadcast", "pp", "masked_psum",
        "(rows, 1, dim) x steps hops",
        lambda p: (p["rows"], 1, p["dim"]),
        lambda p: p["steps"],
    ),
    LinkSpec(
        "pp-broadcast-score", "broadcast", "pp", "masked_psum",
        "(rows, t, dim) x 1 hop",
        lambda p: (p["rows"], p["t"], p["dim"]),
        lambda p: 1,
    ),
    LinkSpec(
        "pp-microstep-spec", "microstep", "pp", "wire_ppermute",
        "(rows, 1+draft, dim) x steps*pp hops",
        lambda p: (p["rows"], 1 + p["draft"], p["dim"]),
        lambda p: p["steps"] * p["pp"],
    ),
    LinkSpec(
        "pp-broadcast-spec", "broadcast", "pp", "masked_psum",
        "(rows, 1+draft, dim) x steps hops",
        lambda p: (p["rows"], 1 + p["draft"], p["dim"]),
        lambda p: p["steps"],
    ),
    LinkSpec(
        "fleet-1f1b-decode", "1f1b", "pp", "wire_ppermute",
        "(b_m, 1, dim) x (pp-1 + steps*mb) hops",
        lambda p: (p["b_m"], 1, p["dim"]),
        lambda p: p["pp"] - 1 + p["steps"] * p["mb"],
    ),
    LinkSpec(
        "fleet-broadcast-decode", "broadcast", "pp", "masked_psum",
        "(b_m, 1, dim) x steps*mb hops",
        lambda p: (p["b_m"], 1, p["dim"]),
        lambda p: p["steps"] * p["mb"],
    ),
    LinkSpec(
        "fleet-1f1b-prefill", "1f1b", "pp", "wire_ppermute",
        "(b_m, t, dim) x (mb + pp - 1) hops",
        lambda p: (p["b_m"], p["t"], p["dim"]),
        lambda p: p["mb"] + p["pp"] - 1,
    ),
    LinkSpec(
        "fleet-broadcast-prefill", "broadcast", "pp", "masked_psum",
        "(b_m, 1, dim) x mb hops",
        lambda p: (p["b_m"], 1, p["dim"]),
        lambda p: p["mb"],
    ),
    LinkSpec(
        "sp-kv-ring", "sp", "sp", "ppermute (operands pre-quantized)",
        "(rows, t_chunk, n_kv_heads, head_dim) x 2*n_layers*(sp-1) hops",
        lambda p: (p["rows"], p["t_chunk"], p["n_kv_heads"], p["head_dim"]),
        lambda p: 2 * p["n_layers"] * (p["sp"] - 1),
    ),
    LinkSpec(
        "sp-broadcast-prefill", "broadcast", "sp", "masked_psum",
        "(rows, 1, dim) x 1 hop",
        lambda p: (p["rows"], 1, p["dim"]),
        lambda p: 1,
    ),
    # The KV fabric's replica-to-replica chain transfer (GET/POST /kv,
    # serving/kv_fabric.py) — DCN, not ICI: it rides plain HTTP between
    # hosts, so its bytes never appear in any HLO collective. The shape
    # is one full chain of kv_blocks cache blocks: K and V planes
    # (2*n_layers) x block tokens x GQA kv heads x head dim. One "hop"
    # = one verified chain moved (pull or push); runtime bytes land on
    # dli_kv_fabric_bytes_total{tier=...} via the same _account_link
    # seam the ICI links use.
    LinkSpec(
        "kv-fabric-dcn", "kv", "dcn", "HTTP /kv (npz chain, streamed)",
        "(kv_blocks, 2*n_layers, kv_block, n_kv_heads, head_dim) x 1 hop",
        lambda p: (p["kv_blocks"], 2 * p["n_layers"], p["kv_block"],
                   p["n_kv_heads"], p["head_dim"]),
        lambda p: 1,
    ),
    # The MPMD stage transport's inter-PROCESS activation hand-off
    # (POST /stage/step, serving/stage_runtime.py) — like kv-fabric-dcn
    # this is DCN/HTTP, invisible to HLO. One hop = one stage boundary
    # crossed by one step's hidden states [rows, t, dim]; with
    # pp_wire_quant="int8" the body ships int8 rows + fp32 scales, so
    # the same wire_link_bytes quant formula applies to the cross-
    # process wire. Runtime bytes land on
    # dli_pp_wire_bytes_total{path="stage"}.
    LinkSpec(
        "stage-activation-dcn", "stage", "dcn",
        "HTTP /stage/step (npz hidden, int8-quantizable)",
        "(rows, t, dim) x 1 hop",
        lambda p: (p["rows"], p["t"], p["dim"]),
        lambda p: 1,
    ),
    # The last stage's reply when it closes the ring: sampled token ids
    # [rows] int32 back to the controller (never quantized — ids, not
    # activations; accounted at fp32 itemsize as 1 id per row).
    LinkSpec(
        "stage-result-dcn", "stage", "dcn",
        "HTTP /stage/step reply (sampled ids)",
        "(rows, 1, 1) x 1 hop",
        lambda p: (p["rows"], 1, 1),
        lambda p: 1,
    ),
)

# ModelConfig attrs the link formulas and fat inventory may read.
_CFG_DIMS = ("dim", "n_layers", "n_heads", "n_kv_heads", "head_dim",
             "vocab_size")


def params_from_config(cfg, **launch) -> dict:
    """Flatten a (duck-typed) ModelConfig + launch params into the flat
    dict the link formulas evaluate over. Keeps this module jax-free:
    cfg is only read through getattr, never imported."""
    p = {k: int(getattr(cfg, k)) for k in _CFG_DIMS}
    p.update(launch)
    return p


def link_bytes(name: str, params: dict, *, itemsize: int,
               quant: bool) -> int:
    """Derived wire bytes for one launch of link `name`."""
    spec = WIRE_LINKS[name]
    return wire_link_bytes(
        spec.shape(params), itemsize, spec.hops(params), quant=quant
    )


# -- static collective-site scan ----------------------------------------------

# the transfer-class lax primitives the wire-coverage contract covers
TRANSFER_PRIMS = frozenset(
    {"ppermute", "psum", "all_gather", "all_to_all", "psum_scatter"}
)
# recorded for graph completeness; exempt from wire coverage (scalar /
# control-class reductions)
_EXTRA_PRIMS = frozenset({"pmax", "pmin"})
_LAX_PRIMS = TRANSFER_PRIMS | _EXTRA_PRIMS
WRAPPERS = frozenset({"wire_ppermute", "masked_psum"})
# positional index of the axis-name argument per callable
_AXIS_ARGPOS = dict(
    {p: 1 for p in _LAX_PRIMS}, wire_ppermute=1, masked_psum=2,
)


@dataclass(frozen=True)
class CollectiveSite:
    """One collective call site in the package source."""

    module: str        # dotted module ("parallel.ring")
    path: str          # package-relative file path
    line: int
    primitive: str     # lax primitive or wrapper name
    func: str          # enclosing function qualname
    axes: tuple        # resolved axis-name strings (unresolved dropped)
    axis_sources: tuple  # provenance per axis expr (incl. unresolved)
    role: str          # taxonomy in the module docstring
    traced: bool       # enclosing function is traced-reachable
    call: ast.Call = field(compare=False, repr=False, hash=False)


def _module_str_consts(mod) -> dict:
    """Module-level `NAME = "str"` bindings, tuple-unpack included
    (parallel/mesh.py declares all five axes in one statement)."""
    out = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    out[target.id] = node.value.value
            elif isinstance(target, ast.Tuple) and isinstance(
                node.value, ast.Tuple
            ) and len(target.elts) == len(node.value.elts):
                for t, v in zip(target.elts, node.value.elts):
                    if isinstance(t, ast.Name) and isinstance(
                        v, ast.Constant
                    ) and isinstance(v.value, str):
                        out[t.id] = v.value
    return out


def declared_axes(index: PackageIndex) -> frozenset:
    """Axis names the package declares: the values of every module-level
    `AXIS_* = "..."` binding (parallel/mesh.py is the real declaration
    site; fixtures declare their own)."""
    axes = set()
    for mod in index.modules.values():
        for name, value in _module_str_consts(mod).items():
            if name.startswith("AXIS_"):
                axes.add(value)
    return frozenset(axes)


def _resolve_axis_name(name: str, mod, index: PackageIndex):
    """A Name used as an axis argument -> its string value, or None."""
    consts = _module_str_consts(mod)
    if name in consts:
        return consts[name]
    imp = mod.imports.get(name)
    if imp and imp[0] == "obj":
        src = index.modules.get(imp[1])
        if src is not None:
            return _module_str_consts(src).get(imp[2])
    return None


def _resolve_axes(expr, mod, index: PackageIndex):
    """Axis expression -> (resolved names, per-element provenance).

    Handles string literals, tuples of axes (context.py broadcasts over
    (AXIS_SP, AXIS_PP)), and names resolving to module-level string
    constants here or in the imported module. Function parameters and
    attribute chains are honestly unresolved — reported, never flagged."""
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    axes, sources = [], []
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            axes.append(e.value)
            sources.append(f"literal:{e.value}")
        elif isinstance(e, ast.Name):
            val = _resolve_axis_name(e.id, mod, index)
            if val is not None:
                axes.append(val)
                sources.append(f"name:{e.id}={val}")
            else:
                sources.append(f"param:{e.id}")
        else:
            d = dotted(e)
            sources.append(f"expr:{d or type(e).__name__}")
    return tuple(axes), tuple(sources)


def _primitive_of(call: ast.Call) -> Optional[str]:
    """`jax.lax.ppermute(...)` / `lax.psum(...)` -> primitive name;
    `wire_ppermute(...)` / `wq.masked_psum(...)` -> wrapper name."""
    d = dotted(call.func)
    if d is None:
        return None
    parts = d.split(".")
    leaf = parts[-1]
    if leaf in _LAX_PRIMS and len(parts) >= 2 and parts[-2] == "lax":
        return leaf
    if leaf in WRAPPERS:
        return leaf
    return None


def _axis_expr(call: ast.Call, primitive: str):
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    pos = _AXIS_ARGPOS[primitive]
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _is_wrapper_module(module: str) -> bool:
    return module == "ops.wire_quant" or module.endswith(".wire_quant") \
        or module == "wire_quant"


def in_parallel(module: str) -> bool:
    """True for modules under a parallel/ package — the transfer plane
    the wire-coverage contract governs."""
    return "parallel" in module.split(".")


def _role_of(module: str, primitive: str, call: ast.Call) -> str:
    if primitive in WRAPPERS:
        return "transfer"
    if _is_wrapper_module(module):
        return "wrapper-internal"
    if primitive in _EXTRA_PRIMS:
        return "merge"
    if primitive == "psum" and call.args and isinstance(
        call.args[0], ast.Constant
    ) and call.args[0].value == 1:
        # `sp = lax.psum(1, axis)` — the axis-size idiom; constant-folded,
        # no wire bytes, no HLO collective
        return "axis-size"
    if primitive == "psum" and module.split(".")[0] == "models":
        return "weight-reduce"
    return "raw"


def collect_sites(index: PackageIndex,
                  traced: Optional[set] = None) -> list:
    """Every collective call site in the package, with resolved axes,
    role, and traced-reachability (resolved through the same callgraph
    the host/decode rules use)."""
    if traced is None:
        traced = traced_reachable(index)
    sites = []
    for mod in index.modules.values():
        for fn in mod.functions.values():
            for node in _walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                prim = _primitive_of(node)
                if prim is None:
                    continue
                expr = _axis_expr(node, prim)
                if expr is None:
                    axes, sources = (), ("missing",)
                else:
                    axes, sources = _resolve_axes(expr, mod, index)
                sites.append(CollectiveSite(
                    module=mod.name,
                    path=mod.path,
                    line=node.lineno,
                    primitive=prim,
                    func=fn.qualname,
                    axes=axes,
                    axis_sources=sources,
                    role=_role_of(mod.name, prim, node),
                    traced=fn.key in traced,
                    call=node,
                ))
    return sites


# -- fat-collective inventory -------------------------------------------------

# Reference dims for symbolic-bytes evaluation in the report: a
# llama-8B-class serving shape (dim 4096, 32 layers, GQA 8 kv heads,
# 128k vocab) on a dp=1, pp=8, sp=8 mesh, an 8-row fleet decoding one
# token over a 4096-token context. Chosen for the report's headline
# numbers only — unit tests evaluate the same formulas at the
# test-llama-tiny dims they can check by hand.
REFERENCE_PARAMS = dict(
    dim=4096, n_layers=32, n_heads=32, n_kv_heads=8, head_dim=128,
    vocab_size=128256,
    dp=1, pp=8, sp=8, mb=8,
    rows=8, t=4096, t_chunk=512, steps=1, draft=4, bh=1, b_m=1,
    # KV-fabric chain transfer: a 4096-token prefix at kv_block=32
    # tokens per cache block = 256 blocks shipped per handoff
    kv_blocks=256, kv_block=32,
)

# A collective is "fat" when its symbolic bytes at the reference dims
# exceed this and no quantized path exists. 1 MiB: an order of magnitude
# above the largest quantized activation hop, an order below the logits
# gathers it exists to track.
FAT_THRESHOLD = 1 << 20


@dataclass(frozen=True)
class FatEntry:
    """One standing fat collective: a machine-tracked worklist item for
    the ROADMAP low-precision-everywhere lever."""

    module: str      # dotted module suffix ("parallel.vocab")
    func: str        # enclosing-qualname substring ("unembed_sharded")
    primitive: str
    axis: str
    dtype: str
    symbolic: str    # closed-form bytes/invocation
    bytes_fn: Callable  # params dict -> bytes/invocation
    note: str
    operand: str = ""  # operand Name at the call site, "" = any — keeps
    #                    an entry from claiming a sibling control gather


def _vocab_pad(p):
    return -(-p["vocab_size"] // p["pp"]) * p["pp"]


FAT_INVENTORY = (
    FatEntry(
        module="parallel.vocab",
        func="unembed_sharded",
        primitive="all_gather",
        axis="pp",
        dtype="float32",
        symbolic="4 * rows * t * (V_pad/pp) * (pp-1)  [V_pad = "
                 "pp*ceil(V/pp)]",
        bytes_fn=lambda p: 4 * p["rows"] * p["t"]
        * (_vocab_pad(p) // p["pp"]) * (p["pp"] - 1),
        note="the vocab-shard logits gather — the one remaining fat "
             "collective (ROADMAP: quantized logits all_gather; needs "
             "an error-tolerant top-k story before int8/fp8 ships)",
        operand="lg",
    ),
    FatEntry(
        module="parallel.context",
        func="_build_score",
        primitive="all_gather",
        axis="sp",
        dtype="float32",
        symbolic="4 * rows * (t/sp) * V * (sp-1)",
        bytes_fn=lambda p: 4 * p["rows"] * p["t_chunk"]
        * p["vocab_size"] * (p["sp"] - 1),
        note="sp scoring gathers every chunk's full-vocab logits to "
             "reassemble [B, T, V] — same quantization story as the "
             "vocab gather, lower duty cycle (score calls only)",
        operand="logits_local",
    ),
)


def fat_entry_for(site: CollectiveSite) -> Optional[FatEntry]:
    """The inventory entry covering `site`, if any."""
    for entry in FAT_INVENTORY:
        if (site.module == entry.module
                or site.module.endswith("." + entry.module)) \
                and entry.func in site.func \
                and site.primitive == entry.primitive:
            if entry.operand:
                arg = site.call.args[0] if site.call.args else None
                if not (isinstance(arg, ast.Name)
                        and arg.id == entry.operand):
                    continue
            return entry
    return None


# -- HLO twin predictions -----------------------------------------------------

# every StableHLO collective kind the scanner in analysis/hlo.py greps
# for when cross-validating a lowered program against the model
STABLEHLO_COLLECTIVES = frozenset({
    "collective_permute", "all_reduce", "all_gather", "all_to_all",
    "reduce_scatter", "collective_broadcast",
})

# Derived per-topology edge sets: the StableHLO collective kinds the
# static graph predicts for each lowered program family. pp decode =
# the wire_ppermute ring (collective_permute), the embed-shard merge +
# masked-psum broadcast (all_reduce), and the vocab logits gather
# (all_gather — the FAT_INVENTORY edge). The sp ulysses attention body
# is all_to_all head<->sequence exchanges only (its `psum(1, axis)`
# axis-size probe constant-folds away).
HLO_PREDICTED = {
    "pp-decode": frozenset({"collective_permute", "all_reduce",
                            "all_gather"}),
    "sp-attend": frozenset({"all_to_all"}),
}


def predicted_hlo_ops(topology: str) -> frozenset:
    return HLO_PREDICTED[topology]


# -- report -------------------------------------------------------------------

def link_call_sites(index: PackageIndex) -> dict:
    """{link name: [(path, line), ...]} — every `self._account_link(
    "<name>", ...)` call site in the package. The provenance half of the
    --comms report, and the proof that each table row is actually wired
    to the runtime accounting."""
    out: dict = {name: [] for name in WIRE_LINKS}
    unknown: list = []
    for mod in index.modules.values():
        for fn in mod.functions.values():
            for node in _walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None or d.split(".")[-1] != "_account_link":
                    continue
                if not node.args or not isinstance(
                    node.args[0], ast.Constant
                ):
                    unknown.append(
                        (mod.path, node.lineno, "<non-literal link name>")
                    )
                    continue
                name = node.args[0].value
                if name in out:
                    out[name].append((mod.path, node.lineno))
                else:
                    unknown.append((mod.path, node.lineno, name))
    out["__unknown__"] = unknown
    return out


def build_report(index: Optional[PackageIndex] = None,
                 root: Optional[str] = None) -> dict:
    """The --comms report: per-link symbolic + reference bytes with
    accounting provenance, the collective-site census, and the fat
    inventory. `problems` is non-empty when the table and the package
    disagree (unknown link name at a call site, or a table row no call
    site uses) — the CLI exits nonzero on it."""
    if index is None:
        index = build_index(root)
    sites = collect_sites(index)
    call_sites = link_call_sites(index)
    problems = [
        f"{path}:{line}: _account_link names unknown link {name!r}"
        for path, line, name in call_sites.pop("__unknown__")
    ]
    links = []
    for name, spec in sorted(WIRE_LINKS.items()):
        where = call_sites.get(name, [])
        if not where:
            problems.append(
                f"link {name!r} has no _account_link call site — dead "
                "table row (delete it) or unrouted accounting"
            )
        links.append({
            "name": name,
            "path": spec.path,
            "axis": spec.axis,
            "transport": spec.transport,
            "symbolic": spec.symbolic,
            "reference_shape": list(spec.shape(REFERENCE_PARAMS)),
            "reference_hops": spec.hops(REFERENCE_PARAMS),
            "reference_bytes_raw": wire_link_bytes(
                spec.shape(REFERENCE_PARAMS), 2,
                spec.hops(REFERENCE_PARAMS), quant=False,
            ),
            "reference_bytes_quant": wire_link_bytes(
                spec.shape(REFERENCE_PARAMS), 2,
                spec.hops(REFERENCE_PARAMS), quant=True,
            ),
            "accounted_at": [f"{p}:{ln}" for p, ln in where],
        })
    site_rows = [
        {
            "file": s.path,
            "line": s.line,
            "primitive": s.primitive,
            "func": s.func,
            "axes": list(s.axes),
            "axis_sources": list(s.axis_sources),
            "role": s.role,
            "traced": s.traced,
        }
        for s in sorted(sites, key=lambda s: (s.path, s.line))
    ]
    fat_rows = []
    for entry in FAT_INVENTORY:
        matched = [
            f"{s.path}:{s.line}" for s in sites
            if fat_entry_for(s) is entry
        ]
        fat_rows.append({
            "module": entry.module,
            "func": entry.func,
            "primitive": entry.primitive,
            "axis": entry.axis,
            "dtype": entry.dtype,
            "symbolic": entry.symbolic,
            "reference_bytes": entry.bytes_fn(REFERENCE_PARAMS),
            "sites": matched,
            "note": entry.note,
        })
    return {
        "reference_params": dict(REFERENCE_PARAMS),
        "links": links,
        "sites": site_rows,
        "fat_inventory": fat_rows,
        "problems": problems,
    }


def format_report(report: dict) -> str:
    """Human rendering of build_report (the non-JSON CLI output)."""
    out = []
    out.append("wire links (bytes/launch at reference dims, itemsize=2):")
    for row in report["links"]:
        out.append(
            f"  {row['name']:<24} axis={row['axis']:<3} "
            f"path={row['path']:<10} raw={row['reference_bytes_raw']:>12,} "
            f"int8={row['reference_bytes_quant']:>12,}  {row['symbolic']}"
        )
        for where in row["accounted_at"]:
            out.append(f"      accounted at {where}")
    out.append("")
    out.append("fat-collective inventory (unquantized, above threshold):")
    for row in report["fat_inventory"]:
        sites = ", ".join(row["sites"]) or "<no matching site!>"
        out.append(
            f"  {row['module']}.{row['func']} {row['primitive']}@"
            f"{row['axis']} [{row['dtype']}] "
            f"ref={row['reference_bytes']:,} B  ({sites})"
        )
        out.append(f"      {row['symbolic']}")
        out.append(f"      {row['note']}")
    out.append("")
    by_role: dict = {}
    for s in report["sites"]:
        by_role.setdefault(s["role"], []).append(s)
    out.append("collective sites by role:")
    for role in sorted(by_role):
        out.append(f"  {role} ({len(by_role[role])}):")
        for s in by_role[role]:
            axes = ",".join(s["axes"]) or ",".join(s["axis_sources"])
            out.append(
                f"    {s['file']}:{s['line']}: {s['primitive']}@{axes} "
                f"in {s['func']}"
            )
    for p in report["problems"]:
        out.append(f"PROBLEM: {p}")
    return "\n".join(out)
