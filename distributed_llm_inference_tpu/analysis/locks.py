"""Lock model for the host-control-plane rules (lock-order,
blocking-under-lock, guarded-by, join-hygiene).

The control plane grew from one worker loop into a thread mesh —
supervisor loop, shadow copier, router prober/rolling-restart, HTTP
handler threads — and its bug classes are lock bugs: ordering
inversions, blocking calls under the admission lock, guarded state
written lock-free. This module builds, once per PackageIndex, the facts
those rules need:

  * LOCK IDENTITIES: every `self.X = threading.Lock()/RLock()/
    Condition(...)` assignment declares a lock (module, class, attr).
    `Condition(self.Y)` ALIASES X to Y (one underlying lock — engine/
    shadow.py's `_cv`/`_lock` pair). A lock attr declared by several
    classes of one module resolves by name to the conflated id
    (module, "*", attr) when the owning instance cannot be typed; the
    rules never draw self-edges, so conflation can widen the graph but
    not invent a cycle on its own.
  * INSTANCE TYPING: `self.X = ClassName(...)` in a class body binds
    X's type, so `self._shadow.flush()` resolves to ShadowStore.flush —
    the cross-object call edges lock ordering is about.
  * HELD-REGION FACTS per function: every lock acquisition (`with
    self.X:`) with the locks already held at that point, every resolved
    call with its held set, every potentially BLOCKING call (time.sleep,
    HTTP fetch, bare `.join()`, `.put(block=True)`, device syncs,
    `.wait()` on anything but an already-held condition) with its held
    set, and every `self.ATTR` write with its held set.
  * GUARDED-BY DECLARATIONS: `# guarded-by: <lock>` on an attribute's
    initializing assignment declares the attr's lock; on a `def` line it
    declares the method's precondition ("caller must hold <lock>" — the
    `_locked`-suffix convention, machine-checked).

Everything here is syntactic and intra-package: a resolution miss makes
a rule MISS a fact, never invent one, so the rules stay near-zero-noise
on real code while catching the fixture shapes (and the PR-4/PR-9
history shapes) exactly.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from .callgraph import (
    FuncInfo, ModuleInfo, PackageIndex, _class_scope, _local_scope, dotted,
)

_LOCK_CTORS = {
    "threading.Lock", "Lock", "threading.RLock", "RLock",
    "threading.Condition", "Condition",
    "threading.Semaphore", "Semaphore",
    "threading.BoundedSemaphore", "BoundedSemaphore",
}
_CONDITION_CTORS = {"threading.Condition", "Condition"}

_GUARDED_RE = re.compile(r"#.*\bguarded-by:\s*([A-Za-z_][\w]*)")

# blocking primitives (the direct facts; may_block() closes them over
# the call graph)
_HTTP_PREFIXES = (
    "urllib.request.urlopen", "urlopen", "requests.", "http.client.",
)
_SLEEP_CALLS = {"time.sleep"}
_DEVICE_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_DEVICE_SYNC_CALLS = {"jax.device_get"}


@dataclass(frozen=True)
class LockId:
    module: str
    cls: str  # "*" = conflated by-name group within the module
    attr: str

    def label(self) -> str:
        owner = self.cls if self.cls != "*" else self.module
        return f"{owner}.{self.attr}"


@dataclass
class FuncFacts:
    key: tuple
    # (held lock-id tuple, acquired lock id, lineno)
    acquisitions: list = field(default_factory=list)
    # (held lock-id tuple, callee func key, lineno)
    calls: list = field(default_factory=list)
    # (held lock-id tuple, kind, detail, lineno); kind "cv-wait" is a
    # bounded wait on an already-held condition — excluded from the
    # local blocking-under-lock flag, included in the may-block summary
    blocking: list = field(default_factory=list)
    # (held lock-id tuple, (cls, attr), lineno)
    writes: list = field(default_factory=list)
    direct_acquires: set = field(default_factory=set)


@dataclass
class ThreadSpawn:
    module_path: str
    module: str
    lineno: int
    daemon: bool
    holder: Optional[str]  # "self._thread" / "t" / None (anonymous)
    timer: bool = False


@dataclass
class LockModel:
    index: PackageIndex
    # (module, cls, attr) -> canonical LockId (Condition aliasing folded)
    decls: dict = field(default_factory=dict)
    # attr -> [(module, cls)] declaring it (for by-name resolution)
    by_attr: dict = field(default_factory=dict)
    # (module, cls, attr) -> (module, cls) instance type
    attr_types: dict = field(default_factory=dict)
    # (module, cls, attr) -> lock attr name (guarded state declarations)
    guarded_attrs: dict = field(default_factory=dict)
    # func key -> lock attr name (method precondition declarations)
    guarded_methods: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)  # func key -> FuncFacts
    spawns: list = field(default_factory=list)  # [ThreadSpawn]
    # holder leaf name -> [(module, lineno, has_timeout)] join calls
    joins: dict = field(default_factory=dict)
    _may_block: Optional[dict] = None
    _acquires_star: Optional[dict] = None

    # -- lock resolution -----------------------------------------------------
    def canonical(self, module: str, cls: str, attr: str) -> Optional[LockId]:
        got = self.decls.get((module, cls, attr))
        return got

    def resolve_attr(self, module: str, attr: str,
                     cls: Optional[str]) -> Optional[LockId]:
        """A lock named by attribute: the function's own class first,
        then by name — unique declaration anywhere wins, several within
        reach conflate to (module-of-declaration, "*", attr)."""
        if cls is not None:
            got = self.decls.get((module, cls, attr))
            if got is not None:
                return got
        owners = self.by_attr.get(attr, ())
        if not owners:
            return None
        same_mod = [o for o in owners if o[0] == module]
        pool = same_mod or owners
        if len(pool) == 1:
            m, c = pool[0]
            return self.decls[(m, c, attr)]
        return LockId(pool[0][0], "*", attr)


def _is_class_name(name: str, mod: ModuleInfo, index: PackageIndex):
    """(module, cls) when `name` names a class with methods in `mod`'s
    scope (defined here or object-imported from a package module)."""
    for q in mod.functions:
        if q.startswith(name + ".") :
            return (mod.name, name)
    imp = mod.imports.get(name)
    if imp and imp[0] == "obj":
        src = index.modules.get(imp[1])
        if src is not None:
            for q in src.functions:
                if q.startswith(imp[2] + "."):
                    return (imp[1], imp[2])
    return None


def _collect_decls(model: LockModel):
    """Lock declarations, Condition aliases, instance typing, and
    guarded-by annotations — one pass over every `self.X = ...`."""
    index = model.index
    pending_aliases = []  # ((module, cls, attr), source attr)
    for mod in index.modules.values():
        for fn in mod.functions.values():
            if "." not in fn.qualname:
                continue
            cls = fn.qualname.split(".")[0]
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    t = node.target
                else:
                    continue
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                attr = t.attr
                v = node.value
                # guarded-by annotation on this assignment's line (or the
                # line above, for assignments too long to carry it)
                for ln in (node.lineno, node.lineno - 1):
                    if 1 <= ln <= len(mod.lines):
                        m = _GUARDED_RE.search(mod.lines[ln - 1])
                        if m and (
                            ln == node.lineno
                            or mod.lines[ln - 1].lstrip().startswith("#")
                        ):
                            model.guarded_attrs[(mod.name, cls, attr)] = (
                                m.group(1)
                            )
                            break
                if isinstance(v, ast.Call):
                    d = dotted(v.func)
                    if d in _LOCK_CTORS:
                        key = (mod.name, cls, attr)
                        if (
                            d in _CONDITION_CTORS and v.args
                            and isinstance(v.args[0], ast.Attribute)
                            and isinstance(v.args[0].value, ast.Name)
                            and v.args[0].value.id == "self"
                        ):
                            pending_aliases.append((key, v.args[0].attr))
                        else:
                            model.decls[key] = LockId(mod.name, cls, attr)
                        continue
                    # instance typing: self.X = ClassName(...)
                    typed = None
                    if isinstance(v.func, ast.Name):
                        typed = _is_class_name(v.func.id, mod, index)
                    elif isinstance(v.func, ast.Attribute) and isinstance(
                        v.func.value, ast.Name
                    ):
                        imp = mod.imports.get(v.func.value.id)
                        if imp and imp[0] == "module":
                            src = index.modules.get(imp[1])
                            if src is not None and any(
                                q.startswith(v.func.attr + ".")
                                for q in src.functions
                            ):
                                typed = (imp[1], v.func.attr)
                    if typed is not None:
                        model.attr_types[(mod.name, cls, attr)] = typed
    for (module, cls, attr), src_attr in pending_aliases:
        target = model.decls.get((module, cls, src_attr))
        model.decls[(module, cls, attr)] = (
            target if target is not None else LockId(module, cls, attr)
        )
    for (module, cls, attr) in model.decls:
        model.by_attr.setdefault(attr, []).append((module, cls))


def _collect_guarded_methods(model: LockModel):
    for mod in model.index.modules.values():
        for fn in mod.functions.values():
            lines = [fn.node.lineno]
            decs = getattr(fn.node, "decorator_list", ())
            if decs:
                lines.append(decs[0].lineno - 1)
            else:
                lines.append(fn.node.lineno - 1)
            for ln in lines:
                if not (1 <= ln <= len(mod.lines)):
                    continue
                text = mod.lines[ln - 1]
                m = _GUARDED_RE.search(text)
                if m and (
                    ln == fn.node.lineno
                    or text.lstrip().startswith("#")
                ):
                    model.guarded_methods[fn.key] = m.group(1)
                    break


def _resolve_lock_expr(expr: ast.AST, cls: Optional[str],
                       mod: ModuleInfo, model: LockModel) -> Optional[LockId]:
    """The lock a `with <expr>:` item or a `<expr>.wait()` receiver
    names, or None when it is not a known lock."""
    if not isinstance(expr, ast.Attribute):
        return None
    attr = expr.attr
    base = expr.value
    if isinstance(base, ast.Name) and base.id == "self":
        got = model.canonical(mod.name, cls or "", attr)
        if got is not None:
            return got
        return model.resolve_attr(mod.name, attr, None)
    # typed base: self.X.lock -> type(X).lock
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
        and cls is not None
    ):
        typed = model.attr_types.get((mod.name, cls, base.attr))
        if typed is not None:
            got = model.canonical(typed[0], typed[1], attr)
            if got is not None:
                return got
    return model.resolve_attr(mod.name, attr, None)


def _resolve_call(node: ast.Call, fn: FuncInfo, cls: Optional[str],
                  mod: ModuleInfo, model: LockModel) -> Optional[tuple]:
    """Callee func key for edges the lock rules can trust: bare names
    (local/module/imported), `self.m()`, module-alias calls, and typed
    `self.X.m()` through the instance-typing map."""
    index = model.index
    f = node.func
    if isinstance(f, ast.Name):
        local = _local_scope(fn, mod)
        if f.id in local:
            return local[f.id].key
        t = mod.functions.get(f.id)
        if t is not None and "." not in t.qualname:
            return t.key
        imp = mod.imports.get(f.id)
        if imp and imp[0] == "obj":
            t = index.get(imp[1], imp[2])
            if t is not None:
                return t.key
        return None
    if not isinstance(f, ast.Attribute):
        return None
    base = f.value
    if isinstance(base, ast.Name):
        if base.id == "self":
            methods = _class_scope(fn, mod)
            t = methods.get(f.attr)
            if t is not None:
                return t.key
            return None
        imp = mod.imports.get(base.id)
        if imp and imp[0] == "module":
            t = index.get(imp[1], f.attr)
            if t is not None:
                return t.key
        return None
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
        and cls is not None
    ):
        typed = model.attr_types.get((mod.name, cls, base.attr))
        if typed is not None:
            t = index.get(typed[0], f"{typed[1]}.{f.attr}")
            if t is not None:
                return t.key
    return None


def _kwarg(node: ast.Call, name: str):
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _blocking_kind(node: ast.Call, held, cls, mod, model) -> Optional[tuple]:
    """(kind, detail) when this call can block the calling thread."""
    d = dotted(node.func)
    if d in _SLEEP_CALLS:
        return "sleep", d + "()"
    if d in _DEVICE_SYNC_CALLS:
        return "device-sync", d + "()"
    if d is not None and any(d.startswith(p) for p in _HTTP_PREFIXES):
        return "http", d + "()"
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    base = node.func.value
    if attr in _DEVICE_SYNC_ATTRS:
        return "device-sync", f".{attr}()"
    if attr in ("put", "get"):
        blk = _kwarg(node, "block")
        if isinstance(blk, ast.Constant) and blk.value is True:
            return "queue-block", f".{attr}(block=True)"
        return None
    if attr == "join":
        # str.join / os.path.join are not synchronization
        if isinstance(base, ast.Constant):
            return None
        if d is not None and ("path" in d or d.startswith("str.")):
            return None
        return "join", ".join()"
    if attr == "wait":
        lid = _resolve_lock_expr(base, cls, mod, model)
        if lid is not None and lid in held:
            # waiting on an already-held condition RELEASES it — the
            # normal pattern; still a may-block fact for callers
            return "cv-wait", ".wait() on held condition"
        if held:
            return "wait", ".wait() on a foreign lock/event"
        return None
    return None


_SPAWN_DOTTED = {"threading.Thread", "Thread"}
_TIMER_DOTTED = {"threading.Timer", "Timer"}


def _holder_of(stmt: ast.Assign) -> Optional[str]:
    if len(stmt.targets) != 1:
        return None
    t = stmt.targets[0]
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
        return t.attr
    return None


def _analyze_function(fn: FuncInfo, mod: ModuleInfo, model: LockModel):
    facts = FuncFacts(key=fn.key)
    cls = fn.qualname.split(".")[0] if "." in fn.qualname else None

    def scan_expr(node: ast.AST, held: tuple):
        """Calls + blocking + writes inside one statement (lambdas
        included — they run on this thread; nested defs excluded)."""
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Call):
                callee = _resolve_call(child, fn, cls, mod, model)
                if callee is not None:
                    facts.calls.append((held, callee, child.lineno))
                blk = _blocking_kind(child, held, cls, mod, model)
                if blk is not None:
                    facts.blocking.append(
                        (held, blk[0], blk[1], child.lineno)
                    )

    def note_writes(stmt: ast.AST, held: tuple):
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        for t in targets:
            node = t
            if isinstance(node, ast.Subscript):
                node = node.value
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                facts.writes.append(
                    (held, (cls, node.attr), t.lineno)
                )

    def visit(stmts, held: tuple):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in st.items:
                    scan_expr(item.context_expr, new_held)
                    lid = _resolve_lock_expr(
                        item.context_expr, cls, mod, model
                    )
                    if lid is not None:
                        facts.acquisitions.append(
                            (new_held, lid, st.lineno)
                        )
                        facts.direct_acquires.add(lid)
                        if lid not in new_held:
                            new_held = new_held + (lid,)
                visit(st.body, new_held)
                continue
            note_writes(st, held)
            if isinstance(st, ast.If):
                scan_expr(st.test, held)
                visit(st.body, held)
                visit(st.orelse, held)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                scan_expr(st.iter, held)
                visit(st.body, held)
                visit(st.orelse, held)
            elif isinstance(st, ast.While):
                scan_expr(st.test, held)
                visit(st.body, held)
                visit(st.orelse, held)
            elif isinstance(st, ast.Try):
                visit(st.body, held)
                for h in st.handlers:
                    visit(h.body, held)
                visit(st.orelse, held)
                visit(st.finalbody, held)
            else:
                scan_expr(st, held)

    visit(fn.node.body, ())
    model.functions[fn.key] = facts

    # thread spawns + joins (join-hygiene facts)
    for st in ast.walk(fn.node):
        if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
            d = dotted(st.value.func)
            if d in _SPAWN_DOTTED or d in _TIMER_DOTTED:
                daemon = _kwarg(st.value, "daemon")
                model.spawns.append(ThreadSpawn(
                    module_path=mod.path, module=mod.name,
                    lineno=st.lineno,
                    daemon=isinstance(daemon, ast.Constant)
                    and daemon.value is True,
                    holder=_holder_of(st), timer=d in _TIMER_DOTTED,
                ))
        elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            d = dotted(call.func)
            if d in _SPAWN_DOTTED or d in _TIMER_DOTTED:
                model.spawns.append(ThreadSpawn(
                    module_path=mod.path, module=mod.name,
                    lineno=st.lineno,
                    daemon=isinstance(_kwarg(call, "daemon"), ast.Constant)
                    and _kwarg(call, "daemon").value is True,
                    holder=None, timer=d in _TIMER_DOTTED,
                ))
            elif (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "start"
                and isinstance(call.func.value, ast.Call)
            ):
                inner = call.func.value
                di = dotted(inner.func)
                if di in _SPAWN_DOTTED or di in _TIMER_DOTTED:
                    dm = _kwarg(inner, "daemon")
                    model.spawns.append(ThreadSpawn(
                        module_path=mod.path, module=mod.name,
                        lineno=st.lineno,
                        daemon=isinstance(dm, ast.Constant)
                        and dm.value is True,
                        holder=None, timer=di in _TIMER_DOTTED,
                    ))
        if isinstance(st, ast.Call) and isinstance(st.func, ast.Attribute) \
                and st.func.attr == "join":
            base = st.func.value
            leaf = None
            if isinstance(base, ast.Name):
                leaf = base.id
            elif isinstance(base, ast.Attribute):
                leaf = base.attr
            if leaf is not None:
                has_timeout = bool(st.args) or any(
                    kw.arg == "timeout" for kw in st.keywords
                )
                model.joins.setdefault(leaf, []).append(
                    (mod.name, st.lineno, has_timeout)
                )


def build_lock_model(index: PackageIndex) -> LockModel:
    cached = getattr(index, "_lock_model", None)
    if cached is not None:
        return cached
    model = LockModel(index=index)
    _collect_decls(model)
    _collect_guarded_methods(model)
    for mod in index.modules.values():
        for fn in mod.functions.values():
            _analyze_function(fn, mod, model)
    index._lock_model = model
    return model


def acquires_star(model: LockModel) -> dict:
    """Transitive lock acquisitions per function (fixpoint over the
    resolved call edges)."""
    if model._acquires_star is not None:
        return model._acquires_star
    acq = {k: set(f.direct_acquires) for k, f in model.functions.items()}
    changed = True
    while changed:
        changed = False
        for k, f in model.functions.items():
            for _, callee, _ in f.calls:
                extra = acq.get(callee)
                if extra and not extra <= acq[k]:
                    acq[k] |= extra
                    changed = True
    model._acquires_star = acq
    return acq


def may_block(model: LockModel) -> dict:
    """{func key: (kind, detail) or None}: can calling this function
    block the calling thread (directly or transitively)? cv-waits count
    — a bounded wait on the callee's own condition still stalls the
    CALLER'S held locks."""
    if model._may_block is not None:
        return model._may_block
    out = {}
    for k, f in model.functions.items():
        direct = [
            (kind, detail) for _, kind, detail, _ in f.blocking
        ]
        out[k] = direct[0] if direct else None
    changed = True
    while changed:
        changed = False
        for k, f in model.functions.items():
            if out[k] is not None:
                continue
            for _, callee, _ in f.calls:
                got = out.get(callee)
                if got is not None:
                    out[k] = (got[0], f"{callee[1]} -> {got[1]}")
                    changed = True
                    break
    model._may_block = out
    return out
