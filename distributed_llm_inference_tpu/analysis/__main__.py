"""CLI: `python -m distributed_llm_inference_tpu.analysis`.

Exit 0 when the package is clean; exit 1 with `file:line: [rule] message`
diagnostics otherwise. `--hlo` additionally lowers the real decode
programs (tiny config, CPU) and verifies the compiled artifacts — this
is the CI gate (.github/workflows/ci.yml `analysis` job).
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_llm_inference_tpu.analysis",
        description="compiled-decode invariant checker (AST lint + "
                    "jaxpr/StableHLO verification)",
    )
    ap.add_argument(
        "--root", default=None, metavar="DIR",
        help="package directory to lint (default: the installed "
             "distributed_llm_inference_tpu package — pass a fixture tree "
             "to lint something else)",
    )
    ap.add_argument(
        "--rules", default=None, metavar="R1,R2",
        help="comma-separated rule subset (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="also lower the decode programs and verify the compiled "
             "artifacts (host callbacks, donation aliasing, recompiles)",
    )
    ap.add_argument(
        "--hlo-only", action="store_true", help="skip the lint pass"
    )
    ap.add_argument(
        "--comms", action="store_true",
        help="emit the comms-contract report: per-link symbolic wire "
             "bytes with accounting provenance, the collective-site "
             "census by role, and the fat-collective inventory",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="with --comms: emit the report as JSON",
    )
    args = ap.parse_args(argv)

    from .rules import ALL_RULES

    if args.list_rules:
        for rule_id, fn in sorted(ALL_RULES.items()):
            doc = (sys.modules[fn.__module__].__doc__ or "").strip()
            first = doc.splitlines()[0] if doc else ""
            if first.startswith(rule_id + ":"):
                first = first[len(rule_id) + 1:].strip()
            print(f"{rule_id}: {first}")
        return 0

    root = args.root or os.path.dirname(os.path.dirname(__file__))
    failed = False

    if not args.hlo_only:
        from .lint import format_diagnostics, run_lint

        rules = args.rules.split(",") if args.rules else None
        diagnostics, suppressed = run_lint(root, rules=rules)
        if not (args.comms and args.json):
            print(format_diagnostics(diagnostics, suppressed))
        failed = failed or bool(diagnostics)

    if args.comms:
        import json as _json

        from .comms import build_report, format_report

        report = build_report(root=root)
        if args.json:
            if not args.hlo_only:
                report["diagnostics"] = [d.format() for d in diagnostics]
                report["suppressed"] = suppressed
            print(_json.dumps(report, indent=2, sort_keys=True))
        else:
            print(format_report(report))
        failed = failed or bool(report["problems"])

    if args.hlo or args.hlo_only:
        # CPU is the reference surface for artifact checks (CI runs here);
        # setdefault so an explicit TPU run still wins
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from .hlo import run_hlo_checks

        results = run_hlo_checks()
        for name, problems in results.items():
            status = "ok" if not problems else "FAIL"
            print(f"hlo:{name}: {status}")
            for p in problems:
                print(f"  - {p}")
        failed = failed or any(problems for problems in results.values())

    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
