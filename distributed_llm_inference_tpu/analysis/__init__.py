"""Static invariant checking for the compiled-decode contract.

The whole point of this system versus the reference (JSON-over-HTTP, four
hops per token) is that decode is ONE compiled XLA program with zero
Python per token. That invariant is defended here, mechanically, in two
complementary passes:

  * `lint` — an AST rule engine over the package (rules/): no host-sync
    calls in functions reachable from the jitted entry points, no Python
    branching on traced values in ops//parallel/, donation coverage for
    KV caches, recompile-hazard static args, metrics label hygiene, and
    HTTP status-counter coverage. Per-line suppressions:
    `# jaxlint: disable=RULE -- reason` (the reason is mandatory).
  * `hlo` — compiled-artifact verification: lower the real decode
    programs with tiny configs and assert on the StableHLO (zero host
    callbacks, donation aliasing actually present, the loop compiled,
    no recompile across invocations).

CLI: `python -m distributed_llm_inference_tpu.analysis` (CI-gated; see
.github/workflows/ci.yml and ARCHITECTURE.md "Invariants").
"""

from .callgraph import PackageIndex, build_index, traced_reachable
from .lint import Diagnostic, format_diagnostics, run_lint

__all__ = [
    "Diagnostic",
    "PackageIndex",
    "build_index",
    "format_diagnostics",
    "run_lint",
    "traced_reachable",
]
