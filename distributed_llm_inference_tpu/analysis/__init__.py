"""Static invariant checking: the compiled-decode contract AND the
host control plane's concurrency contract.

The whole point of this system versus the reference (JSON-over-HTTP, four
hops per token) is that decode is ONE compiled XLA program with zero
Python per token — served by a multi-threaded host control plane
(supervisor loop, shadow copier, queue dispatcher, router prober) whose
own dominant bug classes are lock-order inversions, blocking calls under
admission locks, and refcount leaks on early-return paths. Both contracts
are defended here, mechanically:

  * `lint` — an AST rule engine over the package (rules/): no host-sync
    calls in functions reachable from the jitted entry points, no Python
    branching on traced values in ops//parallel/, donation coverage for
    KV caches, recompile-hazard static args, metrics label hygiene, HTTP
    status-counter coverage — plus the host-control-plane families over
    the thread-aware call graph (callgraph.py) and lock model (locks.py):
    thread-reach (derived decode-unreachability), lock-order,
    blocking-under-lock, guarded-by, resource-lifecycle, join-hygiene.
    Per-line suppressions: `# jaxlint: disable=RULE -- reason` (the
    reason is mandatory).
  * `hlo` — compiled-artifact verification: lower the real decode
    programs with tiny configs and assert on the StableHLO (zero host
    callbacks, donation aliasing actually present, the loop compiled,
    no recompile across invocations).

CLI: `python -m distributed_llm_inference_tpu.analysis` (CI-gated; see
.github/workflows/ci.yml and ARCHITECTURE.md "Invariants").
"""

from .callgraph import (
    PackageIndex, build_index, decode_unreachable, host_reachable,
    thread_roots, traced_reachable,
)
from .lint import Diagnostic, format_diagnostics, run_lint

__all__ = [
    "Diagnostic",
    "PackageIndex",
    "build_index",
    "decode_unreachable",
    "format_diagnostics",
    "host_reachable",
    "run_lint",
    "thread_roots",
    "traced_reachable",
]
