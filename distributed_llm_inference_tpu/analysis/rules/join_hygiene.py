"""join-hygiene: spawned threads must be joinable, and joins must be
bounded.

The PR-9 follower-wedge class, as a rule: a thread another component
waits on at shutdown can wedge the whole process if (a) it is
non-daemon with no bounded join anywhere (interpreter exit blocks on
it forever), or (b) some drain path calls `.join()` on it with NO
timeout (a wedged thread body — a stuck device call, a dead peer —
holds shutdown hostage; `multihost.shutdown_followers` grew its
abandonment timeout for exactly this).

Concretely:
  * a `threading.Thread(...)`/`Timer(...)` spawn without `daemon=True`
    must have a `holder.join(timeout=...)` (bounded) somewhere in its
    module — no holder at all means it can never be joined;
  * any `.join()` on a KNOWN thread holder (a name some spawn in the
    module assigns) without a timeout argument is flagged, daemon or
    not — every drain path in this codebase is deadline-bounded, and an
    unbounded join is how a wedge propagates."""

from __future__ import annotations

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from ..locks import build_lock_model

RULE_ID = "join-hygiene"


def check(index: PackageIndex) -> list:
    model = build_lock_model(index)
    out: list = []
    thread_holders: dict = {}  # (module, leaf) -> spawn
    for spawn in model.spawns:
        if spawn.holder is not None:
            thread_holders[(spawn.module, spawn.holder)] = spawn
    for spawn in model.spawns:
        if spawn.daemon:
            continue
        kind = "Timer" if spawn.timer else "Thread"
        if spawn.holder is None:
            out.append(Diagnostic(
                path=spawn.module_path, line=spawn.lineno, rule=RULE_ID,
                message=f"non-daemon {kind} spawned without a holder — "
                        f"it can never be joined; mark it daemon=True "
                        f"or keep a handle and join(timeout=...) on the "
                        f"drain path",
            ))
            continue
        joins = [
            j for j in model.joins.get(spawn.holder, ())
            if j[0] == spawn.module
        ]
        if not joins:
            out.append(Diagnostic(
                path=spawn.module_path, line=spawn.lineno, rule=RULE_ID,
                message=f"non-daemon {kind} {spawn.holder!r} has no "
                        f"join(timeout=...) in this module — a wedged "
                        f"body blocks interpreter exit forever",
            ))
        elif not any(has_timeout for _, _, has_timeout in joins):
            out.append(Diagnostic(
                path=spawn.module_path, line=spawn.lineno, rule=RULE_ID,
                message=f"non-daemon {kind} {spawn.holder!r} is only "
                        f"ever joined UNBOUNDED — pass timeout= so a "
                        f"wedge cannot hold shutdown hostage",
            ))
    # unbounded joins on known thread holders (daemon included)
    for (leaf, joins) in sorted(model.joins.items()):
        for module, lineno, has_timeout in joins:
            if has_timeout:
                continue
            spawn = thread_holders.get((module, leaf))
            if spawn is None:
                continue
            out.append(Diagnostic(
                path=index.modules[module].path, line=lineno,
                rule=RULE_ID,
                message=f"unbounded .join() on thread {leaf!r} — the "
                        f"PR-9 follower-wedge shape; pass timeout= and "
                        f"handle the straggler",
            ))
    return out
