"""comms-fat-collective: wide unquantized collectives are inventoried.

The ROADMAP's "quantized logits all_gather" item exists because one
class of collective dwarfs the (now int8) activation hops: full-vocab
fp32 gathers. This rule turns that prose into a machine-tracked
worklist. Both directions are enforced:

  * every raw `all_gather` on a parallel/ module must either match a
    `FAT_INVENTORY` entry in analysis/comms.py (classified: a standing
    fat collective with symbolic bytes, visible in `--comms`) or be
    suppressed with a reason (cheap control payloads — int32 slot-fill
    vectors and the like);
  * every inventory entry whose module exists in the indexed package
    must still match a live call site — a stale entry means the fat
    collective moved or died and the worklist lied.

all_to_all is out of scope here: the ulysses exchanges quantize their
operands at function entry under the wire flag, so they have a
quantized path (they carry comms-wire-coverage suppressions that say
so). Entries also must actually be fat: a below-threshold entry at the
reference dims is itself flagged, so the inventory cannot silt up.
"""

from __future__ import annotations

from ..comms import (
    FAT_INVENTORY, FAT_THRESHOLD, REFERENCE_PARAMS, collect_sites,
    fat_entry_for, in_parallel,
)
from ..lint import Diagnostic

RULE_ID = "comms-fat-collective"


def check(index):
    sites = collect_sites(index, traced=set())
    out = []
    for entry in FAT_INVENTORY:
        mods = [
            m for m in index.modules
            if m == entry.module or m.endswith("." + entry.module)
        ]
        if not mods:
            continue  # fixture tree without the module: entry inactive
        matched = [s for s in sites if fat_entry_for(s) is entry]
        if not matched:
            out.append(Diagnostic(
                path=index.modules[mods[0]].path,
                line=1,
                rule=RULE_ID,
                message=(
                    f"stale fat-collective inventory entry "
                    f"{entry.module}.{entry.func} ({entry.primitive}) — "
                    "no matching call site; update FAT_INVENTORY in "
                    "analysis/comms.py"
                ),
            ))
            continue
        if entry.bytes_fn(REFERENCE_PARAMS) < FAT_THRESHOLD:
            out.append(Diagnostic(
                path=index.modules[mods[0]].path,
                line=matched[0].line,
                rule=RULE_ID,
                message=(
                    f"inventory entry {entry.module}.{entry.func} is "
                    f"below FAT_THRESHOLD at the reference dims — not "
                    "fat; drop it from FAT_INVENTORY"
                ),
            ))
    for site in sites:
        if site.primitive != "all_gather" or site.role != "raw":
            continue
        if not in_parallel(site.module):
            continue
        if fat_entry_for(site) is not None:
            continue
        out.append(Diagnostic(
            path=site.path,
            line=site.line,
            rule=RULE_ID,
            message=(
                f"raw all_gather (in {site.func}) with no "
                "fat-collective inventory entry — classify it in "
                "FAT_INVENTORY (analysis/comms.py) with its symbolic "
                "bytes, or suppress with a reason if the payload is "
                "control-plane cheap"
            ),
        ))
    return out
