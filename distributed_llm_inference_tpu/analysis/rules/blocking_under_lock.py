"""blocking-under-lock: no blocking call while holding a control-plane
lock.

A lock in this codebase protects scheduler/admission state that every
serving thread contends on (the continuous engine's cv, the queue's cv,
the router's replica/residency locks, the shadow store's lock). A
blocking call made WHILE HOLDING one — an HTTP fetch, `time.sleep`, an
unbounded `.join()`, `queue.put(block=True)`, a device sync
(`.block_until_ready()`, `.item()`, `jax.device_get`), or a `.wait()`
on some OTHER lock's condition — turns one slow peer into a stall of
every thread behind that lock (and at worst a deadlock, when the callee
waits on a thread that needs the held lock). Flagged at the call site,
with one level of transitivity: a call under a lock into a function the
lock model proves may block is flagged at the CALL (the blocking is a
property of the callee's body, the bug is holding the lock across it).

Waiting on the condition you hold is the one legitimate blocking shape
(wait releases it) and is never flagged locally — but it still makes
the callee may-block for callers holding OTHER locks."""

from __future__ import annotations

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from ..locks import build_lock_model, may_block

RULE_ID = "blocking-under-lock"


def check(index: PackageIndex) -> list:
    model = build_lock_model(index)
    blocks = may_block(model)
    out: list = []
    seen = set()
    for key, facts in sorted(model.functions.items()):
        mod = model.index.modules[key[0]]
        for held, kind, detail, line in facts.blocking:
            if not held or kind == "cv-wait":
                continue
            dedup = (mod.path, line)
            if dedup in seen:
                continue
            seen.add(dedup)
            out.append(Diagnostic(
                path=mod.path, line=line, rule=RULE_ID,
                message=f"{detail} ({kind}) while holding "
                        f"{held[-1].label()} — a blocking call under a "
                        f"control-plane lock stalls every thread behind "
                        f"it",
            ))
        for held, callee, line in facts.calls:
            if not held:
                continue
            got = blocks.get(callee)
            if got is None:
                continue
            dedup = (mod.path, line)
            if dedup in seen:
                continue
            seen.add(dedup)
            out.append(Diagnostic(
                path=mod.path, line=line, rule=RULE_ID,
                message=f"call into {callee[1]} while holding "
                        f"{held[-1].label()} — it can block "
                        f"({got[0]}: {got[1]})",
            ))
    return out
