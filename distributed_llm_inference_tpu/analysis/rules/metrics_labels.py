"""metrics-labels: metric registrations must declare literal, bounded
label sets.

The registry (utils/metrics.py) caps series per family at MAX_SERIES and
collapses overflow into `_other_` — but that fence only works when the
LABEL NAMES are a small fixed set. A computed labelnames argument (or a
wide one) turns label cardinality into a runtime property nobody can
audit from the code, and a request-controlled label name is a
memory-growth primitive the cap cannot see. So every
`registry.counter/gauge/histogram(...)` registration must pass
labelnames as a literal tuple/list of string constants, at most
_MAX_LABELNAMES wide.
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from . import walk_own_body

RULE_ID = "metrics-labels"

_REGISTRATION_METHODS = {"counter", "gauge", "histogram"}
# conservative: wider label sets multiply series counts combinatorially
# against the registry's MAX_SERIES cap
_MAX_LABELNAMES = 4
# positional slot of labelnames in counter/gauge/histogram(name, help, labelnames)
_LABELNAMES_POS = 2


def _labelnames_arg(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "labelnames":
            return kw.value
    if len(call.args) > _LABELNAMES_POS:
        return call.args[_LABELNAMES_POS]
    return None


def _literal_strs(node: ast.AST):
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return vals
    return None


def check(index: PackageIndex) -> list:
    out: list = []
    for mod in index.modules.values():
        if mod.name.startswith("utils.metrics") or mod.name == "utils.metrics":
            continue  # the registry's own internals register nothing
        for fn in mod.functions.values():
            for node in walk_own_body(fn.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRATION_METHODS
                ):
                    continue
                # only metric registrations: first positional arg is the
                # metric name, a string literal by convention — anything
                # else (e.g. collections.Counter) is not a registration
                if not (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("dli_")
                ):
                    continue
                arg = _labelnames_arg(node)
                if arg is None:
                    continue  # no labels: one unlabeled series, fine
                names = _literal_strs(arg)
                if names is None:
                    out.append(Diagnostic(
                        path=mod.path, line=node.lineno, rule=RULE_ID,
                        message=f"metric {node.args[0].value!r}: labelnames "
                                f"must be a literal tuple of string "
                                f"constants (computed label sets defeat the "
                                f"cardinality cap audit)",
                    ))
                elif len(names) > _MAX_LABELNAMES:
                    out.append(Diagnostic(
                        path=mod.path, line=node.lineno, rule=RULE_ID,
                        message=f"metric {node.args[0].value!r} declares "
                                f"{len(names)} labels (> {_MAX_LABELNAMES}) "
                                f"— series counts multiply per label "
                                f"against the registry cap",
                    ))
    return out
