"""comms-wire-coverage: parallel/ transfer paths must use the wire wrappers.

The int8 wire (ops/wire_quant.py) only covers hand-offs that route
through `wire_ppermute`/`masked_psum`; a raw `lax.{ppermute, psum,
all_gather, all_to_all, psum_scatter}` added to a parallel/ module
silently bypasses quantization AND the bytes accounting. This rule
makes that a lint error: raw transfer-class collectives in parallel/
modules are flagged unless suppressed with a reason (the suppression
census in ARCHITECTURE.md "Comms contract" documents every sanctioned
one: control-plane int32 gathers, log-sum-exp merges, operands already
quantized at function entry, and the fat-inventory logits gathers).

Exempt by classification, not suppression: ops/wire_quant.py internals
(the one sanctioned home of raw collectives), the `psum(1, axis)`
axis-size idiom (constant-folded bookkeeping), `pmax`/`pmin` scalar
merges, and tp/ep weight-reduction psums in models/ (not a transfer —
see the role taxonomy in analysis/comms.py).
"""

from __future__ import annotations

from ..comms import TRANSFER_PRIMS, collect_sites, in_parallel
from ..lint import Diagnostic

RULE_ID = "comms-wire-coverage"


def check(index):
    out = []
    for site in collect_sites(index, traced=set()):
        if site.role != "raw":
            continue
        if site.primitive not in TRANSFER_PRIMS:
            continue
        if not in_parallel(site.module):
            continue
        out.append(Diagnostic(
            path=site.path,
            line=site.line,
            rule=RULE_ID,
            message=(
                f"raw lax.{site.primitive} on a parallel/ transfer path "
                f"(in {site.func}) bypasses the int8 wire and the bytes "
                "accounting — route it through wire_ppermute/masked_psum "
                "(ops/wire_quant) or suppress with a reason"
            ),
        ))
    return out
