"""Rule registry + shared AST helpers.

Each rule module exposes `RULE_ID: str` and `check(index) ->
list[Diagnostic]`. Register new rules in ALL_RULES; document them in
ARCHITECTURE.md "Invariants" when you do.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted

# attribute chains that read host-known metadata, not device values
_HOST_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
# names whose attributes are static under every hot-path jit (cfg is in
# static_argnames everywhere; self only appears in host-side builders)
_STATIC_BASES = {"cfg", "dcfg", "self"}
_HOST_CALLS = {"len", "isinstance", "getattr", "hasattr", "min", "max", "abs"}


def is_host_safe(node: ast.AST) -> bool:
    """True when evaluating `node` cannot force a device sync: constants,
    shape/dtype metadata, len(), static-config attribute chains, and
    arithmetic over those. Conservative — unknown names are NOT safe."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in _HOST_ATTRS:
            return True
        base = node.value
        while isinstance(base, ast.Attribute):
            base = base.value
        return isinstance(base, ast.Name) and base.id in _STATIC_BASES
    if isinstance(node, ast.Name):
        return node.id in _STATIC_BASES
    if isinstance(node, ast.Subscript):
        return is_host_safe(node.value)
    if isinstance(node, ast.Call):
        f = dotted(node.func)
        return f in _HOST_CALLS and all(is_host_safe(a) for a in node.args)
    if isinstance(node, ast.BinOp):
        return is_host_safe(node.left) and is_host_safe(node.right)
    if isinstance(node, ast.UnaryOp):
        return is_host_safe(node.operand)
    if isinstance(node, ast.Compare):
        return is_host_safe(node.left) and all(
            is_host_safe(c) for c in node.comparators
        )
    if isinstance(node, ast.BoolOp):
        return all(is_host_safe(v) for v in node.values)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(is_host_safe(e) for e in node.elts)
    return False


def walk_own_body(fn_node: ast.AST):
    """Walk a function body without descending into nested defs/lambdas
    (those are separate call-graph nodes)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield from walk(child)

    for stmt in fn_node.body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from walk(stmt)


from . import (  # noqa: E402 — registry needs the helpers above
    blocking_under_lock,
    comms_axis,
    comms_fat_collective,
    comms_masked_psum,
    comms_wire_coverage,
    donation,
    guarded_by,
    host_sync,
    join_hygiene,
    lifecycle,
    lock_order,
    metrics_labels,
    routes,
    static_args,
    thread_reach,
    tracer_branch,
)

ALL_RULES = {
    mod.RULE_ID: mod.check
    for mod in (
        host_sync, tracer_branch, donation, static_args, metrics_labels,
        routes,
        # host-control-plane rules (lock discipline, resource lifecycle,
        # thread reachability — ARCHITECTURE.md "Invariants")
        thread_reach, lock_order, blocking_under_lock, guarded_by,
        lifecycle, join_hygiene,
        # comms-contract rules (collective graph, wire coverage, fat
        # inventory — ARCHITECTURE.md "Comms contract")
        comms_axis, comms_wire_coverage, comms_masked_psum,
        comms_fat_collective,
    )
}

__all__ = ["ALL_RULES", "is_host_safe", "walk_own_body"]
