"""route-counter: every HTTP response path in serving/ must hit the
status counter.

The serving stack promises that `dli_http_requests_total` covers every
response (ISSUE 2 carried this by hand). The server routes all JSON/HTML
responses through `_send` (which counts), but streaming paths (SSE,
NDJSON) write their own `send_response` — each of those call sites must
be preceded by a `self._count(...)` in the same function, or the scrape
silently undercounts exactly the long-lived requests that matter most.

Rule: in serving/ modules, every call to `send_response` must either be
inside a function whose name is `_send`, or have a `_count(...)` call
earlier in the same function body.
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from . import walk_own_body

RULE_ID = "route-counter"


def _is_method_call(node: ast.Call, name: str) -> bool:
    return isinstance(node.func, ast.Attribute) and node.func.attr == name


def check(index: PackageIndex) -> list:
    out: list = []
    for mod in index.modules.values():
        if mod.name.split(".")[0] != "serving":
            continue
        for fn in mod.functions.values():
            if fn.qualname.rsplit(".", 1)[-1] == "_send":
                continue
            count_lines = []
            sends = []
            for node in walk_own_body(fn.node):
                if isinstance(node, ast.Call):
                    if _is_method_call(node, "_count"):
                        count_lines.append(node.lineno)
                    elif _is_method_call(node, "send_response"):
                        sends.append(node)
            for node in sends:
                if not any(line <= node.lineno for line in count_lines):
                    out.append(Diagnostic(
                        path=mod.path, line=node.lineno, rule=RULE_ID,
                        message=f"send_response in {fn.qualname} without a "
                                f"preceding self._count(...) — this "
                                f"response path is invisible to "
                                f"dli_http_requests_total",
                    ))
    return out
