"""donate-cache: every jitted hot-path program must donate its KV cache.

A decode/prefill/extend program without `donate_argnums`/`donate_argnames`
covering its cache parameter makes XLA copy the whole cache (tens of MB
to GB) every step instead of updating it in place in HBM — functionally
invisible, catastrophic for tok/s and memory headroom. Parameters named
`cache` / `dcache` / `pool` / `*_cache` are treated as KV caches.

Shared-block exception (block-level prefix sharing): a parameter named
`shared_pool` is a pool whose blocks are MAPPED into other requests'
block tables (engine/block_prefix.py) — the program only reads it, and
donating it would let XLA reuse the buffer while every other table still
reads those exact blocks. The rule INVERTS for that name: `shared_pool`
must NOT be donated, and donating it is flagged.

Resolvable jit sites are checked: decorated defs (`@jax.jit`,
`@functools.partial(jax.jit, ...)`) and `jax.jit(f, ...)` calls whose
wrapped callable traces back — through simple local assignments like
`shmapped = self._shard(body, ...)` — to a function definition in the
same scope (the parallel/ backends' pattern). Sites whose wrapped
callable cannot be resolved are skipped, not guessed at.
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex, dotted
from ..lint import Diagnostic
from . import walk_own_body

RULE_ID = "donate-cache"

_CACHE_NAMES = {"cache", "dcache", "pool"}
# READ-ONLY mapped-pool convention: blocks of a `shared_pool` are mapped
# into other live block tables, so the buffer must outlive this program —
# donation is the bug here, not the fix.
_SHARED_RO_NAMES = {"shared_pool"}


def _is_cache_param(name: str) -> bool:
    return (
        name not in _SHARED_RO_NAMES
        and (name in _CACHE_NAMES or name.endswith("_cache"))
    )


def _params_of(node: ast.AST) -> tuple:
    a = node.args
    return tuple(p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs))


def _donated(call: ast.Call, params: tuple) -> set:
    """Param names covered by donate_argnames/donate_argnums on a jit (or
    partial(jit, ...)) call."""
    out = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnames":
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
            elif isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                out.add(kw.value.value)
        elif kw.arg == "donate_argnums":
            nums = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                nums = [
                    e.value for e in kw.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            elif isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, int
            ):
                nums = [kw.value.value]
            for n in nums:
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


def _jit_call_of_decorator(dec: ast.AST):
    """The Call carrying donate kwargs for a decorated def, or None for a
    bare `@jax.jit` (no kwargs at all)."""
    if isinstance(dec, ast.Call):
        d = dotted(dec.func)
        if d in ("jax.jit", "jit"):
            return dec
        if d in ("functools.partial", "partial") and dec.args:
            if dotted(dec.args[0]) in ("jax.jit", "jit"):
                return dec
    return None


def _check_site(path: str, line: int, qualname: str, params: tuple,
                jit_call, out: list) -> None:
    cache_params = [p for p in params if _is_cache_param(p)]
    shared_params = [p for p in params if p in _SHARED_RO_NAMES]
    if not cache_params and not shared_params:
        return
    donated = _donated(jit_call, params) if jit_call is not None else set()
    for p in cache_params:
        if p not in donated:
            out.append(Diagnostic(
                path=path, line=line, rule=RULE_ID,
                message=f"jit of {qualname} does not donate cache argument "
                        f"{p!r} (index {params.index(p)}) — XLA will copy "
                        f"the cache every call instead of updating in place",
            ))
    for p in shared_params:
        if p in donated:
            out.append(Diagnostic(
                path=path, line=line, rule=RULE_ID,
                message=f"jit of {qualname} DONATES shared pool argument "
                        f"{p!r} — mapped shared blocks must not be "
                        f"donated: other requests' block tables still "
                        f"read those buffers",
            ))


def check(index: PackageIndex) -> list:
    out: list = []
    for mod in index.modules.values():
        # decorated defs
        for fn in mod.functions.values():
            for dec in getattr(fn.node, "decorator_list", ()):
                call = _jit_call_of_decorator(dec)
                is_bare = dotted(dec) in ("jax.jit", "jit")
                if call is None and not is_bare:
                    continue
                _check_site(
                    mod.path, fn.node.lineno, fn.qualname,
                    _params_of(fn.node), call, out,
                )
        # jax.jit(name, ...) call sites, resolved through local aliases
        for fn in mod.functions.values():
            local_defs = {}
            prefix = fn.qualname + "."
            for q, f in mod.functions.items():
                if q.startswith(prefix) and "." not in q[len(prefix):]:
                    local_defs[q[len(prefix):]] = f
            aliases = dict(local_defs)
            for node in walk_own_body(fn.node):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    src = node.value
                    if (
                        src.args
                        and isinstance(src.args[0], ast.Name)
                        and src.args[0].id in aliases
                    ):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                aliases[tgt.id] = aliases[src.args[0].id]
                elif isinstance(node, ast.Call) and dotted(node.func) in (
                    "jax.jit", "jit"
                ):
                    if not (
                        node.args and isinstance(node.args[0], ast.Name)
                    ):
                        continue
                    wrapped = aliases.get(node.args[0].id)
                    if wrapped is None:
                        top = mod.functions.get(node.args[0].id)
                        wrapped = top
                    if wrapped is None:
                        continue
                    _check_site(
                        mod.path, node.lineno,
                        f"{fn.qualname}:{wrapped.qualname}",
                        _params_of(wrapped.node), node, out,
                    )
    return out
