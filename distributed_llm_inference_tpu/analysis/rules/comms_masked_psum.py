"""comms-masked-psum: int8 psum operands must carry the one-hot mask.

The quantized masked-psum broadcast is only overflow-safe because
EXACTLY ONE participant contributes a nonzero operand — int8 values
sum across the axis, and two live participants would wrap at ±127.
ops/wire_quant.masked_psum establishes that precondition syntactically:
`lax.psum(jnp.where(sel, w.q, zeros), axis)`. This rule enforces the
same discipline at every raw psum site: an operand that is (or aliases)
the output of `quantize_rows`/`wire_encode` — including its `.q`/`.s`
leaves — may only be psum'd wrapped in a `where` mask. A bare
`lax.psum(q, axis)` of quantized data is a lint error: nothing
establishes the single-owner precondition, so the sum can overflow.

Scope: a per-function taint pass (assignments from the quantizers and
direct aliases of tainted names/attributes), matching how the wire code
is actually written — quantize immediately before the collective, in
the same function. Cross-function data flow is out of scope; the wire
contract routes those through masked_psum itself.
"""

from __future__ import annotations

import ast

from ..callgraph import _walk_own_body, dotted
from ..comms import _primitive_of
from ..lint import Diagnostic

RULE_ID = "comms-masked-psum"

_QUANT_SOURCES = {"quantize_rows", "wire_encode"}


def _is_quant_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return d is not None and d.split(".")[-1] in _QUANT_SOURCES


def _is_tainted(expr, tainted: set) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute) and expr.attr in ("q", "s"):
        return isinstance(expr.value, ast.Name) and expr.value.id in tainted
    return False


def _is_where_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return d is not None and d.split(".")[-1] == "where"


def check(index):
    out = []
    for mod in index.modules.values():
        for fn in mod.functions.values():
            tainted: set = set()
            for node in _walk_own_body(fn):
                if isinstance(node, ast.Assign):
                    targets = []
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            targets.append([t])
                        elif isinstance(t, ast.Tuple):
                            targets.append(
                                [e for e in t.elts
                                 if isinstance(e, ast.Name)]
                            )
                    flat = [n for group in targets for n in group]
                    if _is_quant_call(node.value):
                        tainted.update(n.id for n in flat)
                    elif _is_tainted(node.value, tainted):
                        tainted.update(n.id for n in flat)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if _primitive_of(node) != "psum" or not node.args:
                    continue
                operand = node.args[0]
                if _is_where_call(operand):
                    continue  # masked — the precondition is established
                if _is_tainted(operand, tainted):
                    out.append(Diagnostic(
                        path=mod.path,
                        line=node.lineno,
                        rule=RULE_ID,
                        message=(
                            "psum of a quantized operand without the "
                            "exactly-one-nonzero mask — int8 partial "
                            "sums overflow with >1 live participant; "
                            "wrap in jnp.where(sel, ..., zeros) or use "
                            "ops/wire_quant.masked_psum"
                        ),
                    ))
    return out
