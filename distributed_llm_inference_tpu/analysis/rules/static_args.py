"""static-args: recompile hazards in jit static arguments.

Two hazards, both of which turn "compile once per bucket" into "compile
per request":

  * a `static_argnames`/`static_argnums` value that is not a literal
    tuple/list/str of constants — computed static names defeat auditing
    and usually indicate a dynamically-varying static set;
  * a CALL SITE passing an unhashable or per-call-fresh value (f-string,
    list/dict/set literal or comprehension, lambda) as a known static
    parameter of a package jit function — every distinct value is a new
    cache entry, every call a potential recompile. (jax raises on
    unhashables; f-strings hash fine and silently recompile per string —
    the worse failure.)
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex, dotted
from ..lint import Diagnostic
from . import walk_own_body

RULE_ID = "static-args"

_FRESH_VALUE_NODES = (
    ast.JoinedStr, ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
    ast.SetComp, ast.GeneratorExp, ast.Lambda,
)


def _literal_str_seq(node: ast.AST):
    """The tuple of strings in a literal static_argnames value, or None
    when the value is computed."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None


def _jit_static_kwargs(call: ast.Call):
    """(static_names or None, computed: bool) from a jit/partial call."""
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _literal_str_seq(kw.value)
            return names, names is None
    return (), False


def _collect_jit_statics(index: PackageIndex) -> tuple:
    """({func_bare_name: static_names}, diagnostics for computed sets)."""
    statics = {}
    diags: list = []
    for mod in index.modules.values():
        for fn in mod.functions.values():
            for dec in getattr(fn.node, "decorator_list", ()):
                call = None
                if isinstance(dec, ast.Call):
                    d = dotted(dec.func)
                    if d in ("jax.jit", "jit"):
                        call = dec
                    elif d in ("functools.partial", "partial") and dec.args:
                        if dotted(dec.args[0]) in ("jax.jit", "jit"):
                            call = dec
                if call is None:
                    continue
                names, computed = _jit_static_kwargs(call)
                if computed:
                    diags.append(Diagnostic(
                        path=mod.path, line=call.lineno, rule=RULE_ID,
                        message=f"static_argnames of {fn.qualname} is not a "
                                f"literal tuple of strings — static sets "
                                f"must be auditable constants",
                    ))
                elif names:
                    statics.setdefault(
                        fn.qualname.rsplit(".", 1)[-1], set()
                    ).update(names)
    return statics, diags


def check(index: PackageIndex) -> list:
    statics, out = _collect_jit_statics(index)
    if not statics:
        return out
    for mod in index.modules.values():
        for fn in mod.functions.values():
            for node in walk_own_body(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    callee = node.func.attr
                names = statics.get(callee)
                if not names:
                    continue
                for kw in node.keywords:
                    if kw.arg in names and isinstance(
                        kw.value, _FRESH_VALUE_NODES
                    ):
                        what = type(kw.value).__name__
                        out.append(Diagnostic(
                            path=mod.path, line=node.lineno, rule=RULE_ID,
                            message=f"{callee}({kw.arg}=<{what}>): passing a "
                                    f"fresh/unhashable value as a static "
                                    f"argument recompiles per call — hoist "
                                    f"it to a hashable constant",
                        ))
    return out
