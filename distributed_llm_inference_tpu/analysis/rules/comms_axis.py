"""comms-axis: collective axis names must resolve to declared mesh axes.

A typo'd axis name (`lax.ppermute(x, "ppp", perm)`) is invisible until
trace time on a mesh that actually binds the axis — which CPU CI never
builds, so the bug ships. Statically: every axis argument of a
collective (raw lax primitive or wire wrapper) that resolves to a
string constant — a literal, a module-level `AXIS_*` binding, or an
import of one — must be a member of the package's declared axis set
(the values of every module-level `AXIS_* = "..."`; parallel/mesh.py
declares all five). Function parameters and attribute chains are
honestly unresolvable by an AST pass and are skipped, not flagged —
their call sites resolve somewhere up the stack where this rule DOES
see the constant.
"""

from __future__ import annotations

from ..comms import collect_sites, declared_axes
from ..lint import Diagnostic

RULE_ID = "comms-axis"


def check(index):
    declared = declared_axes(index)
    if not declared:
        # no AXIS_* declarations anywhere (bare fixture tree): nothing
        # to validate against
        return []
    out = []
    for site in collect_sites(index, traced=set()):
        for axis in site.axes:
            if axis not in declared:
                out.append(Diagnostic(
                    path=site.path,
                    line=site.line,
                    rule=RULE_ID,
                    message=(
                        f"{site.primitive} uses axis {axis!r} which is "
                        f"not a declared mesh axis "
                        f"({', '.join(sorted(declared))}) — typo'd axes "
                        "only fail at trace time on a real mesh"
                    ),
                ))
    return out
