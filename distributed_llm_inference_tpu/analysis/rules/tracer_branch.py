"""tracer-branch: no Python control flow on traced values in ops/ and
parallel/.

`if jnp.any(mask):` inside kernel/collective code either raises a
ConcretizationTypeError at trace time or — when the module is also
imported eagerly — silently branches on a single test value and bakes
that branch into every compiled program. Data-dependent control flow in
the hot path belongs in `lax.cond` / `lax.while_loop` / `jnp.where`.

Detection is deliberately precise rather than exhaustive: a Python
`if` / `while` / ternary / assert whose test contains a `jnp.*` /
`jax.numpy.*` / `jax.lax.*` call, or an array-reduction method call
(`.any()` / `.all()` / `.sum()` / `.max()` / `.min()`), is definitively
branching on a computed array predicate. Shape / dtype / None tests
never trip it.
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex, dotted
from ..lint import Diagnostic
from . import walk_own_body

RULE_ID = "tracer-branch"

_SCOPED_DIRS = ("ops", "parallel")
_REDUCTIONS = {"any", "all", "sum", "max", "min", "argmax", "argmin"}
_ARRAY_NAMESPACES = {"jnp", "jax.numpy", "jax.lax", "lax"}


def _is_array_predicate(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d:
            ns = d.rsplit(".", 1)[0]
            if ns in _ARRAY_NAMESPACES:
                return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _REDUCTIONS
            and not isinstance(node.func.value, ast.Name)
        ):
            # method reduction on a non-trivial expression; bare
            # `name.sum()` also counts when name isn't a module alias
            return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _REDUCTIONS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id not in ("builtins", "math")
        ):
            return True
    return False


def check(index: PackageIndex) -> list:
    out: list = []
    for mod in index.modules.values():
        top = mod.name.split(".")[0]
        if top not in _SCOPED_DIRS:
            continue
        for fn in mod.functions.values():
            for node in walk_own_body(fn.node):
                test = None
                kind = None
                if isinstance(node, (ast.If, ast.While)):
                    test, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.IfExp):
                    test, kind = node.test, "conditional expression"
                elif isinstance(node, ast.Assert):
                    test, kind = node.test, "assert"
                if test is not None and _is_array_predicate(test):
                    out.append(Diagnostic(
                        path=mod.path, line=node.lineno, rule=RULE_ID,
                        message=f"Python {kind} on an array predicate in "
                                f"{fn.qualname} — use lax.cond/"
                                f"lax.while_loop/jnp.where in traced code",
                    ))
    return out
