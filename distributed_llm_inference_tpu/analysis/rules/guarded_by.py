"""guarded-by: declared shared state is only written under its lock.

The convention (ARCHITECTURE.md "Invariants"): a comment on the
attribute's initializing assignment declares which lock guards it —

    self._queue = []  # guarded-by: _cv

— and from then on EVERY write to `self._queue` in that class (plain
assignment, augmented assignment, subscript store, del) must happen
while that lock is held. A method may instead declare the precondition
on its def line —

    def _note_queue_locked(self):  # guarded-by: _cv

— which (a) exempts its own writes (the caller holds the lock) and
(b) obliges every resolved call site to hold the lock, machine-checking
the `_locked`-suffix convention the engine has relied on by hand.
`__init__` is exempt (no second thread can hold a reference yet).

Writes the model cannot see (mutating method calls like `.append()`,
writes through an alias) are out of scope — declare guarded-by on the
attributes whose mutation shape IS assignment, which is what the
control plane's queues/maps/flags use."""

from __future__ import annotations

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from ..locks import build_lock_model

RULE_ID = "guarded-by"


def check(index: PackageIndex) -> list:
    model = build_lock_model(index)
    out: list = []

    # resolve each declaration's lock once
    resolved_attrs = {}
    for (module, cls, attr), lock_name in model.guarded_attrs.items():
        lid = model.canonical(module, cls, lock_name)
        if lid is None:
            lid = model.resolve_attr(module, lock_name, cls)
        if lid is None:
            mod = index.modules[module]
            out.append(Diagnostic(
                path=mod.path, line=1, rule=RULE_ID,
                message=f"guarded-by on {cls}.{attr} names unknown lock "
                        f"{lock_name!r} (no threading.Lock/RLock/"
                        f"Condition assignment found)",
            ))
            continue
        resolved_attrs[(module, cls, attr)] = lid

    guarded_fn = {}
    for key, lock_name in model.guarded_methods.items():
        module, qualname = key
        cls = qualname.split(".")[0] if "." in qualname else None
        lid = (
            model.canonical(module, cls, lock_name) if cls else None
        ) or model.resolve_attr(module, lock_name, cls)
        if lid is not None:
            guarded_fn[key] = lid

    for key, facts in sorted(model.functions.items()):
        module, qualname = key
        mod = index.modules[module]
        if qualname.endswith("__init__") and qualname.count(".") <= 1:
            continue  # construction happens before sharing
        own = guarded_fn.get(key)
        for held, (cls, attr), line in facts.writes:
            lid = resolved_attrs.get((module, cls, attr))
            if lid is None:
                continue
            if lid in held or own == lid:
                continue
            out.append(Diagnostic(
                path=mod.path, line=line, rule=RULE_ID,
                message=f"write to {cls}.{attr} outside its declared "
                        f"lock {lid.label()} (guarded-by)",
            ))
        for held, callee, line in facts.calls:
            need = guarded_fn.get(callee)
            if need is None:
                continue
            if need in held or own == need:
                continue
            out.append(Diagnostic(
                path=mod.path, line=line, rule=RULE_ID,
                message=f"call to {callee[1]} without holding "
                        f"{need.label()} — its def declares "
                        f"`# guarded-by: {model.guarded_methods[callee]}`",
            ))
    return out
