"""lock-order: no lock-acquisition-order inversions in the control plane.

The host control plane holds several locks (engine cv, shadow store
lock, router replica/residency/rolling locks, metrics family locks) and
acquires them from several threads. Deadlock needs exactly one shape: a
cycle in the lock-ORDER graph — lock B acquired while A is held on one
path, A while B is held on another. This rule builds that graph from
the lock model (analysis/locks.py): direct nested `with` acquisitions
contribute edges, and a call made while holding A contributes A -> every
lock the callee may transitively acquire. Any cycle over DISTINCT locks
is flagged at each participating acquisition site (re-entries of the
same lock are not ordering facts and are ignored — RLock re-entry and
by-name conflation would otherwise self-loop)."""

from __future__ import annotations

from ..callgraph import PackageIndex
from ..lint import Diagnostic
from ..locks import acquires_star, build_lock_model

RULE_ID = "lock-order"


def _edges(model) -> dict:
    """{(a, b): [(path, line)]} — b acquired (directly or via a call)
    while a is held."""
    acq = acquires_star(model)
    out: dict = {}
    for key, facts in model.functions.items():
        mod = model.index.modules[key[0]]
        for held, lid, line in facts.acquisitions:
            for h in held:
                if h != lid:
                    out.setdefault((h, lid), []).append((mod.path, line))
        for held, callee, line in facts.calls:
            if not held:
                continue
            for lid in acq.get(callee, ()):
                for h in held:
                    if h != lid:
                        out.setdefault((h, lid), []).append(
                            (mod.path, line)
                        )
    return out


def _cycle_nodes(edges) -> set:
    """Nodes on some cycle (Tarjan SCCs of size > 1; the self-loop case
    is filtered at edge construction)."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    idx = {}
    low = {}
    stack = []
    on = set()
    out = set()
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan (the control plane is small, but recursion
        # limits are not a failure mode a linter should have)
        work = [(v, iter(graph[v]))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(graph[w])))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    out.update(scc)

    for v in graph:
        if v not in idx:
            strongconnect(v)
    return out


def check(index: PackageIndex) -> list:
    model = build_lock_model(index)
    edges = _edges(model)
    bad = _cycle_nodes(edges)
    out: list = []
    seen = set()
    for (a, b), sites in sorted(
        edges.items(), key=lambda kv: (kv[1][0], kv[0][0].label())
    ):
        if a not in bad or b not in bad:
            continue
        path, line = sites[0]
        dedup = (path, line, a, b)
        if dedup in seen:
            continue
        seen.add(dedup)
        out.append(Diagnostic(
            path=path, line=line, rule=RULE_ID,
            message=f"lock-order inversion: {b.label()} is acquired "
                    f"while holding {a.label()}, and the reverse order "
                    f"exists elsewhere — a cross-thread deadlock shape",
        ))
    return out
