"""host-sync: no host synchronization inside traced (decode-reachable)
functions.

Any of these in a function reachable from a jitted entry point either
breaks the trace outright or — worse — silently lowers to a host
callback, reintroducing a Python round-trip per token (the exact failure
mode this reproduction exists to delete):

  * `.item()` / `.tolist()` / `.block_until_ready()` /
    `.copy_to_host_async()` — explicit device→host fetches;
  * `int()` / `float()` / `bool()` on anything but host-known metadata
    (shape/dtype/len/static cfg) — implicit concretization;
  * `np.*` / `numpy.*` calls — numpy forces host values;
  * `jax.device_get`, `jax.debug.*`, `jax.pure_callback`,
    `io_callback` — host callbacks by construction;
  * `print(...)` and `time.*` — host side effects (timestamps belong at
    already-host-blocking boundaries, never inside the trace).
"""

from __future__ import annotations

import ast

from ..callgraph import PackageIndex, dotted, traced_reachable
from ..lint import Diagnostic
from . import is_host_safe, walk_own_body

RULE_ID = "host-sync"

_SYNC_METHODS = {
    "item", "tolist", "block_until_ready", "copy_to_host_async",
}
_CONCRETIZERS = {"int", "float", "bool"}
_NUMPY_ALIASES = {"np", "numpy"}
_JAX_ESCAPES = {
    "jax.device_get", "jax.pure_callback", "jax.debug.print",
    "jax.debug.callback", "jax.debug.breakpoint",
    "jax.experimental.io_callback", "io_callback", "pure_callback",
}


def _check_call(node: ast.Call, path: str, out: list) -> None:
    func = node.func
    d = dotted(func)
    if isinstance(func, ast.Attribute):
        if func.attr in _SYNC_METHODS:
            out.append(Diagnostic(
                path=path, line=node.lineno, rule=RULE_ID,
                message=f".{func.attr}() forces a device->host sync inside "
                        f"a traced function",
            ))
            return
        base = d.split(".")[0] if d else None
        if base in _NUMPY_ALIASES:
            out.append(Diagnostic(
                path=path, line=node.lineno, rule=RULE_ID,
                message=f"{d}() runs on host (numpy concretizes traced "
                        f"values); use jnp inside traced code",
            ))
            return
        if base == "time":
            out.append(Diagnostic(
                path=path, line=node.lineno, rule=RULE_ID,
                message=f"{d}() is a host side effect inside a traced "
                        f"function; timestamps belong at host-blocking "
                        f"boundaries",
            ))
            return
    if d in _JAX_ESCAPES:
        out.append(Diagnostic(
            path=path, line=node.lineno, rule=RULE_ID,
            message=f"{d} lowers to a host callback — zero Python per "
                    f"token means zero callbacks in the decode program",
        ))
        return
    if isinstance(func, ast.Name):
        if func.id == "print":
            out.append(Diagnostic(
                path=path, line=node.lineno, rule=RULE_ID,
                message="print() inside a traced function (prints at trace "
                        "time, or syncs via debug callback)",
            ))
        elif (
            func.id in _CONCRETIZERS
            and node.args
            and not all(is_host_safe(a) for a in node.args)
        ):
            out.append(Diagnostic(
                path=path, line=node.lineno, rule=RULE_ID,
                message=f"{func.id}() on a possibly-traced value "
                        f"concretizes it (host sync); use jnp.{func.id}32/"
                        f"astype, or compute from shapes/static cfg",
            ))


def check(index: PackageIndex) -> list:
    out: list = []
    reachable = traced_reachable(index)
    for mod in index.modules.values():
        for fn in mod.functions.values():
            if fn.key not in reachable:
                continue
            for node in walk_own_body(fn.node):
                if isinstance(node, ast.Call):
                    _check_call(node, mod.path, out)
    return out
