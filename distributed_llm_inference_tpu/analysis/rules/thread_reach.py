"""thread-reach: host-plane entry points stay out of every trace.

The thread-aware call graph (analysis/callgraph.py) derives the host
control plane from its real roots — `threading.Thread(target=...)` /
`Timer` / `executor.submit` spawn targets, HTTP `do_*` handler methods,
CLI `main`s, signal/atexit registrations — and `decode_unreachable()`
(host-reachable minus traced-reachable, plus the annotated escape
hatch) replaced the hand-pinned fixture list tests/test_analysis.py
used to grow per PR. This rule is what makes that derivation SOUND:

  * a THREAD ENTRY POINT that is also reachable from a jit root is a
    host loop leaking into compiled code (its blocking waits, sleeps,
    and mutations would land inside a trace) — flagged at the spawn;
  * a function annotated `# jaxlint: decode-unreachable -- reason` that
    IS traced-reachable is a broken promise — flagged at the def;
  * an annotation without a reason is flagged, exactly like a
    reasonless suppression.
"""

from __future__ import annotations

from ..callgraph import (
    PackageIndex, annotated_decode_unreachable, thread_roots,
    traced_reachable,
)
from ..lint import Diagnostic

RULE_ID = "thread-reach"


def check(index: PackageIndex) -> list:
    out: list = []
    traced = traced_reachable(index)
    for key, (path, lineno) in sorted(thread_roots(index).items()):
        if key in traced:
            out.append(Diagnostic(
                path=path, line=lineno, rule=RULE_ID,
                message=f"thread entry point {key[0]}.{key[1]} is "
                        f"reachable from a jit root — a spawned loop's "
                        f"blocking calls must never land inside a trace",
            ))
    for key, reason in sorted(annotated_decode_unreachable(index).items()):
        mod = index.modules.get(key[0])
        fn = mod.functions.get(key[1]) if mod else None
        if fn is None:
            continue
        if not reason:
            out.append(Diagnostic(
                path=mod.path, line=fn.node.lineno, rule=RULE_ID,
                message="decode-unreachable annotation without a reason "
                        "— write `# jaxlint: decode-unreachable -- why "
                        "this is host-only`",
            ))
        if key in traced:
            out.append(Diagnostic(
                path=mod.path, line=fn.node.lineno, rule=RULE_ID,
                message=f"{key[1]} is annotated decode-unreachable but "
                        f"IS reachable from a jit root",
            ))
    return out
