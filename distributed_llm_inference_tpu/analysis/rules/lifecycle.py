"""resource-lifecycle: every alloc/incref/acquire is released on every
early-return path of the acquiring function.

The PR-4 `_BLOCKED` leak shape, as a rule: paged admission increfs a
shared block chain, then a LATER acquisition fails (constraint table
full), and the function returns a retry sentinel without decref'ing
what it already holds — the pool bleeds a few blocks per retry until
admission wedges. The matching APIs in this codebase:

    blocks = alloc.alloc(n)      ... alloc.decref(blocks) / .free(blocks)
    alloc.incref(shared)         ... alloc.decref(shared)
    off = table.acquire(art)     ... table.release(key)
    sp = store.start_span(...)   ... store.end_span(sp)

The last pair is the tracing span discipline (serving/trace_store.py):
an explicitly started span left open on a return path never commits to
the store — the trace silently loses that hop. Prefer the `span()`
contextmanager (invisible to this rule, safe by construction); the
explicit pair is for spans that outlive one frame, which is exactly the
ownership-transfer shape the tracker already exempts.

The rule tracks, per function and in source order: an ACQUIRE binds the
target variable as a live resource; a RELEASE call (`decref`/`free`/
`release`) naming it clears it; storing it into an attribute or
subscript, or returning it, is an OWNERSHIP TRANSFER and clears it
(request/instance state owns it now — the engine's real convention); a
`return` while a resource is live is flagged. Returns inside an
`if X is None:` (or `while X is None:`) body are exempt for X — that is
the acquisition-FAILED branch. Releases in a `finally` cover the whole
try statement. `raise` paths are deliberately out of scope: the
supervisor's unwind handlers own those (and are themselves exercised by
the chaos suite).

Intentional leaks-on-return (true ownership transfer through a channel
the tracker cannot see) use the standard reasoned suppression:
`# jaxlint: disable=resource-lifecycle -- handed to X`."""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import PackageIndex, dotted
from ..lint import Diagnostic

RULE_ID = "resource-lifecycle"

_ACQUIRE_ATTRS = {"alloc", "incref", "acquire", "start_span"}
_RELEASE_ATTRS = {"decref", "free", "release", "end_span"}


def _holder_name(node: ast.AST) -> Optional[str]:
    """A trackable holder: bare Name or dotted attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    d = dotted(node)
    return d


def _names_in(node: ast.AST) -> set:
    out = set()
    for child in ast.walk(node):
        if isinstance(child, ast.Name):
            out.add(child.id)
        d = dotted(child)
        if d is not None:
            out.add(d)
    return out


def _release_targets(call: ast.Call) -> set:
    """Holders a release call clears: every Name/attr in its args
    (including list literals — `decref([b])` releases b)."""
    out = set()
    for arg in call.args:
        out |= _names_in(arg)
    return out


def _is_acquire(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _ACQUIRE_ATTRS
    )


def _is_release(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _RELEASE_ATTRS
    )


def _scan_releases(stmts) -> set:
    out = set()
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.Call) and _is_release(node):
                out |= _release_targets(node)
    return out


def _none_guard_var(test: ast.AST) -> Optional[str]:
    """`X is None` / `not X` -> "X" (the acquisition-FAILED guard)."""
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Is)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        return _holder_name(test.left)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _holder_name(test.operand)
    return None


def _not_none_guard_var(test: ast.AST) -> Optional[str]:
    """`X is not None` / bare `X` -> "X" (held in the body; the ELSE
    branch — and the fallthrough past a terminating body — means the
    acquisition failed)."""
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.IsNot)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        return _holder_name(test.left)
    if isinstance(test, (ast.Name, ast.Attribute)):
        return _holder_name(test)
    return None


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _Tracker:
    def __init__(self, path: str):
        self.path = path
        self.diags: list = []

    def run(self, fn_node: ast.AST):
        self.visit(fn_node.body, {})

    # state: holder -> (lineno, what) for live resources
    def visit(self, stmts, state: dict):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            self.statement(st, state)

    def handle_calls(self, st: ast.AST, state: dict):
        """Releases + bare increfs anywhere inside one leaf statement."""
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            if _is_release(node):
                for h in _release_targets(node):
                    state.pop(h, None)

    def note_transfers(self, st: ast.AST, state: dict):
        """Attribute / subscript stores referencing a live holder move
        ownership out of the function's hands."""
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        if not targets or st.value is None:
            return
        if all(isinstance(t, ast.Name) for t in targets):
            return  # local rebinding is not a transfer
        referenced = _names_in(st.value)
        for h in [h for h in state if h in referenced]:
            state.pop(h, None)

    def statement(self, st: ast.AST, state: dict):
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(st.value, ast.Call) \
                and _is_acquire(st.value):
            self.handle_calls(st, state)  # releases in args, defensively
            recv = dotted(st.value.func) or st.value.func.attr
            state[st.targets[0].id] = (st.lineno, recv)
            return
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call) \
                and _is_acquire(st.value) \
                and st.value.func.attr == "incref" and st.value.args:
            h = _holder_name(st.value.args[0])
            if h is not None:
                state[h] = (st.lineno, dotted(st.value.func) or "incref")
            return
        if isinstance(st, ast.Return):
            self.handle_calls(st, state)
            returned = _names_in(st.value) if st.value is not None else set()
            for h, (line, recv) in sorted(state.items()):
                if h in returned:
                    continue  # ownership transferred to the caller
                self.diags.append(Diagnostic(
                    path=self.path, line=st.lineno, rule=RULE_ID,
                    message=f"return leaks {h!r} acquired via "
                            f"{recv}() at line {line} — release it "
                            f"(decref/free/release) on this path or "
                            f"suppress with the ownership-transfer "
                            f"reason",
                ))
            return
        if isinstance(st, ast.If):
            self.handle_calls(st.test, state)
            guard = _none_guard_var(st.test)
            pos = _not_none_guard_var(st.test)
            body_state = dict(state)
            if guard is not None:
                body_state.pop(guard, None)  # acquisition failed here
            else_state = dict(state)
            if pos is not None:
                else_state.pop(pos, None)  # failed on the else path
            self.visit(st.body, body_state)
            self.visit(st.orelse, else_state)
            if _terminates(st.body) and not st.orelse:
                # the body never falls through: onward state is the
                # else path's (e.g. `if X is not None: return X` — X is
                # definitely None afterwards)
                state.clear()
                state.update(else_state)
                return
            if st.orelse and _terminates(st.orelse) \
                    and not _terminates(st.body):
                state.clear()
                state.update(body_state)
                return
            # optimistic merge: released in either branch counts (the
            # flagged shape is the return INSIDE a branch, caught
            # above); a guard-popped holder only counts released when
            # gone from BOTH sides
            for h in list(state):
                if h not in body_state and h not in else_state:
                    state.pop(h, None)
                elif guard is None and pos is None and (
                    h not in body_state or h not in else_state
                ):
                    state.pop(h, None)
            return
        if isinstance(st, ast.While):
            self.handle_calls(st.test, state)
            guard = _none_guard_var(st.test)
            body_state = dict(state)
            if guard is not None:
                body_state.pop(guard, None)
            self.visit(st.body, body_state)
            self.visit(st.orelse, dict(state))
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.handle_calls(st.iter, state)
            self.visit(st.body, state)
            self.visit(st.orelse, state)
            return
        if isinstance(st, ast.Try):
            finally_released = _scan_releases(st.finalbody)
            body_state = dict(state)
            for h in finally_released:
                body_state.pop(h, None)
            self.visit(st.body, body_state)
            for handler in st.handlers:
                self.visit(handler.body, dict(state))
            self.visit(st.orelse, body_state)
            self.visit(st.finalbody, state)
            for h in finally_released:
                state.pop(h, None)
            for h in list(state):
                if h not in body_state:
                    state.pop(h, None)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.handle_calls(item.context_expr, state)
            self.visit(st.body, state)
            return
        # leaf statement: transfers first (the engine's incref-then-
        # store idiom), then releases
        self.note_transfers(st, state)
        self.handle_calls(st, state)


def check(index: PackageIndex) -> list:
    out: list = []
    for mod in index.modules.values():
        for fn in mod.functions.values():
            # cheap pre-filter: only functions containing an acquire
            has = any(
                isinstance(n, ast.Call) and _is_acquire(n)
                for n in ast.walk(fn.node)
            )
            if not has:
                continue
            leaf = fn.qualname.rsplit(".", 1)[-1]
            if leaf in _ACQUIRE_ATTRS | _RELEASE_ATTRS:
                continue  # the allocator/table's own implementation
            tracker = _Tracker(mod.path)
            tracker.run(fn.node)
            out.extend(tracker.diags)
    return out
