"""InferenceEngine: the request-level decode engine (reference L3).

Replaces `Orchestrator.generate_with_sampling`
(/root/reference/orchestration.py:69-228): tokenize → chat-template →
prefill (TTFT) → decode loop → detokenize → perf stats, with the same
response schema (`prompt`, `response`, `status`, `time_taken`,
`tokens_generated`, `tokens_per_sec` — orchestration.py:211-218) plus
first-class `ttft_s` (BASELINE.json's p50-TTFT metric is a measurement, not
a print).

Single-owner by construction: one lock serializes generations — the
reference's shared-global Flask state would interleave worker calls across
concurrent requests with no locking (SURVEY.md §5 race note).

The compute backend is pluggable: `SingleDeviceBackend` (this file) runs
the whole model on one chip; `parallel.pipeline.PipelineBackend` runs
N stages over a mesh with the same (prefill, decode) interface.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import EngineConfig, ModelConfig
from ..models import api as M
from ..utils import faults
from ..utils.logging import get_logger, request_id_context
from ..utils.metrics import DEFAULT_SIZE_BUCKETS, MetricsRegistry
from ..utils.tokenizer import load_tokenizer
from ..utils.tracing import FlightRecorder, Trace
from ..serving.trace_store import TraceStore
from . import generate as G
from .prefix import PrefixCache

log = get_logger("engine")

DECODE_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)
# generate_batch pads the row count up to one of these (compile-once per
# batch bucket, like the prompt/decode buckets)
BATCH_BUCKETS = (1, 2, 4, 8, 16)


def batch_buckets_for(granularity: int) -> tuple:
    """Batch-bucket ladder for a backend's row-count quantum.

    gran 1 -> BATCH_BUCKETS; gran g > 1 -> (g, 2g, 4g, ...) up past
    BATCH_BUCKETS[-1], so every batch size the API admits maps to a
    bucket that warmup() compiled — the request path and warmup MUST
    share this ladder or --warmup's 'no request pays jit latency'
    contract breaks for granularities that divide no power of two."""
    if granularity <= 1:
        return BATCH_BUCKETS
    out = [granularity]
    while out[-1] < BATCH_BUCKETS[-1]:
        out.append(out[-1] * 2)
    return tuple(out)
# prompt-lookup speculation: drafted tokens verified per forward (the KV
# headroom _clamp_decode reserves past the last emitted token)
SPEC_DRAFT_LEN = 4


class SingleDeviceBackend:
    """Whole model on one device: prefill + while-loop decode, both jitted."""

    name = "single-device"
    n_stages = 1
    # Ragged (left-padded, per-row valid_start) batches; PipelineBackend
    # threads valid_start too, so pp meshes serve the same request surface.
    supports_ragged = True

    def __init__(self, cfg: ModelConfig, params):
        self.cfg = cfg
        self.params = params

    def init_cache(self, batch: int, max_seq: int):
        return M.init_kv_cache(self.cfg, batch, max_seq=max_seq)

    def prefill(self, tokens, prompt_len, cache, key, sampling,
                valid_start=None, presence=None, bias=None):
        # pos always passed as a traced array so ordinary prefill, warmup,
        # and the chunked final chunk all share one compiled program per
        # bucket shape. presence [B, V] (repetition-penalty token set) and
        # bias [V] (OpenAI logit_bias) are None on the default path —
        # such requests trace their own program variant, the
        # reference-parity path stays untouched.
        return G.prefill(
            self.cfg, self.params, tokens, prompt_len, cache, key, sampling,
            valid_start, jnp.int32(0), presence, bias,
        )

    # chunked prefill (prompts longer than the largest bucket); the engine
    # uses these on any backend that exposes them (this one and the SPMD
    # PipelineBackend) and falls back to the bucket-limit error elsewhere
    def extend(self, tokens, pos, cache):
        return G.extend(self.cfg, self.params, tokens, pos, cache)

    def prefill_at(self, tokens, pos, valid_len, cache, key, sampling,
                   presence=None, bias=None):
        return G.prefill(
            self.cfg, self.params, tokens, valid_len, cache, key, sampling,
            None, pos, presence, bias,
        )

    def decode(self, first_token, cache, start_pos, limit, key, sampling,
               valid_start=None, presence=None, counts=None, bias=None,
               constraint=None, *, max_steps, with_logprobs=False):
        return G.decode(
            self.cfg, self.params, first_token, cache, start_pos, limit, key,
            sampling, valid_start, presence, counts, bias, constraint,
            max_steps=max_steps, with_logprobs=with_logprobs,
        )

    # OpenAI logit_bias ([V] added to raw logits each sample)
    supports_bias = True
    # grammar-constrained decoding (constrain/): FSM state + mask tables
    # threaded through decode; first token rides the bias operand
    supports_constrain = True
    # teacher-forced scoring (OpenAI echo+logprobs / lm-eval loglikelihood)
    supports_score = True

    def score_chunk(self, tokens, pos, cache, *, top_n=0):
        return G.score_chunk(
            self.cfg, self.params, tokens, pos, cache, top_n=top_n
        )
    # deterministic beam search (HF generate(num_beams=N) semantics);
    # the KV cache reorders by parent beam with a batched gather
    supports_beam = True

    def decode_beam(self, logits0, cache, start_pos, limit, length_penalty,
                    *, max_steps, num_beams, early_stopping):
        return G.decode_beam(
            self.cfg, self.params, logits0, cache, start_pos, limit,
            length_penalty, max_steps=max_steps, num_beams=num_beams,
            early_stopping=early_stopping,
        )

    # greedy prompt-lookup speculative decode (engine opts in per request)
    supports_speculative = True
    # HF-parity repetition penalty (presence-tracked decode variants)
    supports_presence = True
    # OpenAI frequency/presence penalties (generated-count state)
    supports_counts = True
    # per-token logprobs (decode program variant with a logprob buffer)
    supports_logprobs = True
    # slot decode for continuous batching (engine/continuous.py);
    # PipelineBackend provides a shard_map equivalent
    supports_slots = True

    def decode_slots(self, state, cache, key, sparams, *, num_steps):
        return G.decode_slots(
            self.cfg, self.params, state, cache, key, sparams,
            num_steps=num_steps,
        )

    # constrained slot decode (continuous fleets with grammar-constrained
    # tenants; the fleet tables come from constrain/fleet.py)
    supports_constrained_slots = True

    def decode_slots_constrained(self, state, cache, key, sparams, fsm,
                                 cmask, ctrans, *, num_steps):
        return G.decode_slots_constrained(
            self.cfg, self.params, state, cache, key, sparams, fsm, cmask,
            ctrans, num_steps=num_steps,
        )

    # block-paged KV for the continuous fleet (engine/paged.py): pool +
    # block tables instead of n_slots x max_seq dense rows. Both families
    # — the attn_hook seam the pool writes ride is shared (gpt2's block
    # routes through llama.default_attn_hook since round 5).
    @property
    def supports_paged(self):
        return self.cfg.arch in ("llama", "gpt2")

    def init_paged_pool(self, n_blocks, block_size):
        from . import paged as P

        return P.init_pool(self.cfg, n_blocks, block_size)

    def insert_slot_paged(self, pool, scratch, state, sparams, slot,
                          table_row, *args):
        from . import paged as P

        return P.insert_slot_paged(
            self.cfg, pool, scratch, state, sparams, slot, table_row, *args
        )

    def decode_slots_paged(self, state, pool, table, key, sparams, *,
                           num_steps, pages=None):
        from . import paged as P

        return P.decode_slots_paged(
            self.cfg, self.params, state, pool, table, key, sparams,
            num_steps=num_steps, pages=pages,
        )

    def fill_scratch_paged(self, pool, table_row):
        # block-level prefix sharing: assemble a contiguous scratch view
        # of a hit's mapped blocks (the pool is read — NOT donated; other
        # block tables keep reading those exact buffers)
        from . import paged as P

        return P.gather_scratch_blocks(pool, table_row)

    # warm-recovery shadow seam (engine/shadow.py): single-device only
    # for now — the pp backend's layer-sharded pool would need shard_map
    # twins for the gather/scatter, so pp fleets recover cold (the
    # continuous engine gates on these attributes)
    def gather_shadow_blocks(self, pool, block_ids):
        from . import paged as P

        return P.gather_shadow_blocks(pool, block_ids)

    def restore_shadow_blocks(self, pool, blocks, block_ids):
        from . import paged as P

        return P.restore_shadow_blocks(pool, blocks, block_ids)

    # ragged ingest (engine/paged.py): admission prefills straight into
    # the pool through the ragged kernel/gather — no scratch, no insert
    # scatter, no bucket ladder. Gated per engine by
    # engine_cfg.ragged_prefill; PipelineBackend provides shard_map twins.
    @property
    def supports_ragged_fill(self):
        return self.supports_paged

    def extend_ragged_paged(self, tokens, tok_row, tok_pos, meta, pool,
                            table, pages=None):
        from . import paged as P

        return P.extend_ragged_paged(
            self.cfg, self.params, tokens, tok_row, tok_pos, meta, pool,
            table, pages=pages,
        )

    def prefill_ragged_paged(self, tokens, tok_row, tok_pos, meta, pool,
                             table, sample_at, key, sampling, presence=None,
                             bias=None, pages=None):
        from . import paged as P

        return P.prefill_ragged_paged(
            self.cfg, self.params, tokens, tok_row, tok_pos, meta, pool,
            table, sample_at, key, sampling, presence=presence, bias=bias,
            pages=pages,
        )

    def arm_slot_paged(self, state, sparams, slot, *arm):
        from . import paged as P

        return P.arm_slot_only(self.cfg, state, sparams, slot, *arm)

    # mixed scheduler launch (engine/scheduler.py): every active decode
    # row plus budget-sliced prefill chunks in ONE ragged program —
    # decode tokens/positions gathered from slot state on device,
    # completing admissions sample + arm in the same pass.
    @property
    def supports_mixed_step(self):
        return self.supports_ragged_fill

    def mixed_step_ragged(self, tokens, tok_row, tok_pos, dec_flag, meta,
                          pool, table, state, sparams, key, dec_idx, arm,
                          spec=None, spec_toks=None, dev=None, pages=None):
        from . import paged as P

        return P.mixed_step_ragged(
            self.cfg, self.params, tokens, tok_row, tok_pos, dec_flag,
            meta, pool, table, state, sparams, key, dec_idx, arm,
            spec=spec, spec_toks=spec_toks, dev=dev, pages=pages,
        )

    # paged adapter pool (engine/adapters.py): the lora leaves live in
    # self.params["layers"]; a load is one donation-aliased write per
    # factor stack with the page id TRACED (no recompile across pages)
    def write_adapter_page(self, page, updates):
        from .adapters import _page_write

        layers = dict(self.params["layers"])
        page = jnp.int32(page)
        for leaf, (a, b) in updates.items():
            for suffix, val in (("a", a), ("b", b)):
                name = f"lora_{leaf}_{suffix}"
                layers[name] = _page_write(
                    layers[name], page,
                    jnp.asarray(val, self.cfg.jnp_dtype),
                )
        self.params = dict(self.params)
        self.params["layers"] = layers

    def ragged_program_count(self) -> int:
        """Compiled ragged-ingest program count (jit cache entries of the
        two launch programs) — the dli_ragged_compiled_programs gauge:
        flat after warmup proves no per-shape recompile."""
        from . import paged as P

        return (
            P.extend_ragged_paged._cache_size()
            + P.prefill_ragged_paged._cache_size()
        )

    def decode_speculative(self, first_token, cache, hist, hist_len, limit,
                           *, max_steps, draft_len):
        return G.decode_speculative(
            self.cfg, self.params, first_token, cache, hist, hist_len, limit,
            max_steps=max_steps, draft_len=draft_len,
        )

    # two-model (draft) speculative decode — engine.set_draft() wires the
    # draft model in; the combined verify program runs both models
    supports_draft = True

    def decode_draft_speculative(self, dcfg, dparams, first_token, cache,
                                 dcache, start_pos, limit, *, max_steps,
                                 draft_len):
        return G.decode_draft_speculative(
            self.cfg, self.params, dcfg, dparams, first_token, cache,
            dcache, start_pos, limit, max_steps=max_steps,
            draft_len=draft_len,
        )

    def health(self) -> list[dict]:
        """Per-device health: a timed device probe, the in-process analogue
        of the reference's 5s-timeout /workers sweep
        (orchestration.py:306-329)."""
        from ..utils.probe import probe_device

        dev = jax.devices()[0]
        return [{"stage": 0, "devices": [str(dev)], **probe_device(dev)}]


class InferenceEngine:
    def __init__(
        self,
        cfg: ModelConfig,
        params: Any = None,
        backend: Any = None,
        tokenizer: Any = None,
        engine_cfg: EngineConfig = EngineConfig(),
        seed: int = 0,
    ):
        if backend is None:
            if params is None:
                params = M.init_params(cfg, jax.random.PRNGKey(seed))
            backend = SingleDeviceBackend(cfg, params)
        self.cfg = cfg
        self.backend = backend
        self.engine_cfg = engine_cfg
        self.tokenizer = tokenizer or load_tokenizer(
            None, pad_id=cfg.pad_token_id, bos_id=cfg.bos_token_id, eos_id=cfg.eos_token_id
        )
        self._lock = threading.Lock()
        self._key = jax.random.PRNGKey(seed)
        self.request_count = 0
        # Rolling per-request perf samples for p50/p90/p99 TTFT + throughput
        # (BASELINE.json's metric is p50 TTFT — a measurement, not a print).
        # Own lock, NOT self._lock: that one is held for a whole generation,
        # and /health must not block behind a multi-second decode.
        self._samples = collections.deque(maxlen=256)
        self._samples_lock = threading.Lock()
        self._samples_total = 0  # guarded-by: _samples_lock
        # Metrics registry (utils/metrics.py): owned per engine so tests /
        # embedded engines never cross-talk; the server, queue, continuous
        # engine, prefix cache, and constraint table all register into it,
        # and GET /metrics renders it. _record_sample is the ONE seam that
        # feeds both this registry and the rolling deque above, so the
        # /stats JSON view and the Prometheus view cannot diverge.
        self.metrics = MetricsRegistry()
        self._m_ttft = self.metrics.histogram(
            "dli_ttft_seconds", "time to first token", ("engine",)
        )
        self._m_tpot = self.metrics.histogram(
            "dli_tpot_seconds", "inter-token time (decode)", ("engine",)
        )
        self._m_duration = self.metrics.histogram(
            "dli_request_duration_seconds", "end-to-end request latency",
            ("engine",),
        )
        self._m_requests = self.metrics.counter(
            "dli_requests_total", "served generations", ("engine", "model")
        )
        self._m_failures = self.metrics.counter(
            "dli_request_failures_total", "failed generations",
            ("engine", "error_type"),
        )
        self._m_tokens = self.metrics.counter(
            "dli_tokens_generated_total", "generated tokens", ("engine",)
        )
        self._m_batch_size = self.metrics.histogram(
            "dli_batch_rows", "rows per batched fleet", ("engine",),
            buckets=DEFAULT_SIZE_BUCKETS,
        )
        self._m_speculative = self.metrics.counter(
            "dli_speculative_requests_total",
            "requests served speculatively (acceptance stays on device; "
            "no host callback inside the verify loop)", ("engine",),
        )
        # Pre-register the cross-component families (queue, continuous
        # fleet, prefix cache, constraint table, paged pool) so a scrape's
        # SCHEMA is stable across server configs — a bare solo server
        # exposes the full catalog shape, and components attaching later
        # (serving/queue.py, engine/continuous.py, ...) get-or-create the
        # same families and simply add their labeled series.
        self.metrics.gauge(
            "dli_queue_depth", "requests waiting for dispatch", ("queue",)
        )
        self.metrics.counter(
            "dli_queue_shed_total", "requests shed with 429", ("queue",)
        )
        self.metrics.histogram(
            "dli_admission_wait_seconds", "enqueue-to-dispatch wait",
            ("queue",),
        )
        self.metrics.gauge("dli_slots_total", "continuous-fleet decode slots")
        self.metrics.gauge(
            "dli_slots_occupied", "continuous-fleet slots serving a request"
        )
        self.metrics.histogram(
            "dli_decode_step_seconds",
            "per-token decode step time, chunk launch-to-fetch / "
            "chunk_steps (includes pipelining lag)", ("engine",),
        )
        self.metrics.counter(
            "dli_preemptions_total",
            "slots killed before their budget drained", ("reason",),
        )
        # graceful-degradation families (engine/continuous.py preemption
        # + the deadline/cancellation surface): preempt->resume latency,
        # cancellations by cause, end-to-end deadline_ms overruns
        self.metrics.histogram(
            "dli_preempted_resume_seconds",
            "preemption to successful re-admission latency",
        )
        self.metrics.counter(
            "dli_cancelled_total",
            "requests cancelled before completion", ("cause",),
        )
        self._m_deadline_exceeded = self.metrics.counter(
            "dli_deadline_exceeded_total",
            "requests failed by their end-to-end deadline_ms",
        ).labels()
        self.metrics.counter(
            "dli_prefix_cache_hits_total",
            "prefix-cache hits (tail actually planned and spliced)",
            ("scope",),
        )
        self.metrics.counter(
            "dli_prefix_cache_misses_total", "prefix-cache misses",
            ("scope",),
        )
        self.metrics.counter(
            "dli_prefix_cache_evictions_total",
            "prefix snapshots evicted by the LRU bound", ("scope",),
        )
        self.metrics.gauge(
            "dli_prefix_cache_entries", "resident prefix snapshots",
            ("scope",),
        )
        # failure-containment families (engine/continuous.py supervisor +
        # the serving drain path): restarts, salvaged re-admissions,
        # quarantined requests, drain latency
        self.metrics.counter(
            "dli_scheduler_restarts_total",
            "continuous-scheduler supervisor restarts", ("engine",),
        )
        self.metrics.counter(
            "dli_requests_recovered_total",
            "in-flight requests re-admitted (continuation prefill) after "
            "a scheduler restart", ("engine",),
        )
        self.metrics.counter(
            "dli_poison_requests_total",
            "requests quarantined as poison after repeated crash "
            "implication", ("engine",),
        )
        self.metrics.histogram(
            "dli_drain_duration_seconds",
            "graceful-drain wall time (SIGTERM / drain())", ("component",),
        )
        # warm-recovery families (engine/shadow.py + the continuous
        # supervisor's restore path): shadow residency/traffic, blocks
        # restored into rebuilt pools, and the per-salvage recompute
        # cost warm recovery exists to shrink
        self.metrics.gauge(
            "dli_shadow_blocks",
            "host-shadowed paged-KV blocks resident for warm recovery",
        )
        self.metrics.counter(
            "dli_shadow_copies_total",
            "paged-KV blocks copied device->host into the shadow store",
        )
        self.metrics.counter(
            "dli_shadow_dropped_total",
            "shadow blocks dropped (copier backpressure or a failed "
            "device->host transfer)",
        )
        self.metrics.counter(
            "dli_shadow_restored_blocks_total",
            "shadowed blocks scattered back into a rebuilt pool "
            "(supervisor restart or --restore-dir start)",
        )
        self.metrics.counter(
            "dli_recovery_tokens_recomputed_total",
            "prompt tokens re-prefilled for crash-recovery re-admissions "
            "(warm recovery bounds this by the partial tail block)",
            ("engine",),
        )
        # KV-fabric families (serving/kv_fabric.py — labeled by the
        # continuous engine's fetch client when the fabric is live;
        # role = this replica's --replica-class): cross-replica chain
        # fetches, their outcomes, wire bytes, and fetch latency
        self.metrics.counter(
            "dli_kv_fabric_fetches_total",
            "cross-replica /kv chain fetches attempted", ("role",),
        )
        self.metrics.counter(
            "dli_kv_fabric_hits_total",
            "fabric fetches that returned a verified chain", ("role",),
        )
        self.metrics.counter(
            "dli_kv_fabric_misses_total",
            "fabric fetches that fell back to local prefill (404, "
            "dead/wedged peer, failed content-key recheck)", ("role",),
        )
        self.metrics.counter(
            "dli_kv_fabric_bytes_total",
            "wire bytes of verified fabric chains moved, by serving tier "
            "(host/disk = pull source at the peer, push = proactive "
            "POST /kv at the prefill->decode handoff)",
            ("role", "tier"),
        )
        self.metrics.histogram(
            "dli_kv_fabric_fetch_seconds",
            "fabric fetch wall time, failures included",
        )
        # KV tier-hierarchy families (engine/shadow.py — ARCHITECTURE.md
        # "Tiered KV"): per-tier occupancy plus promotion/demotion flow
        # between HBM pool (tier 0), host shadow (tier 1), disk chunk
        # files (tier 2)
        self.metrics.gauge(
            "dli_kv_tier_entries",
            "KV blocks resident per cache tier (host = shadow DRAM, "
            "disk = persisted chunk files)", ("tier",),
        )
        self.metrics.gauge(
            "dli_kv_tier_bytes",
            "approximate bytes resident per KV cache tier", ("tier",),
        )
        self.metrics.counter(
            "dli_kv_tier_promotions_total",
            "KV blocks promoted up the tier hierarchy, by destination "
            "tier (host = disk->DRAM load, pool = scattered into HBM)",
            ("tier",),
        )
        self.metrics.counter(
            "dli_kv_tier_demotions_total",
            "KV blocks demoted down the tier hierarchy, by destination "
            "tier (disk = host-LRU spill or copier-backpressure spill)",
            ("tier",),
        )
        self.metrics.counter(
            "dli_kv_tier_disk_hits_total",
            "lookups served from the disk tier (chunk files loaded and "
            "verified on a read that missed the host tier)",
        )
        # wedge observability (engine._with_deadline): abandoned
        # deadline-overrun device calls still occupying the device — the
        # serving edge flips /ready 503 past --wedge-unready off the
        # same state, so the router tier ejects a wedged replica
        self._m_wedged = self.metrics.gauge(
            "dli_engine_wedged",
            "abandoned deadline-overrun device calls still running "
            "(nonzero = wedged; /ready reports 503 past --wedge-unready)",
        ).labels()
        # ragged-ingest families (engine/continuous.py labels them when
        # the ragged path is live): launch composition, padding-tile
        # overhead, exact-depth prefix reuse, and the compiled-program
        # gauge that makes the no-recompile-per-tail invariant observable
        self.metrics.counter(
            "dli_ragged_rows_total",
            "ragged-launch rows by kind (prefill chunk / decode token)",
            ("kind",),
        )
        self.metrics.counter(
            "dli_ragged_tiles_total",
            "ragged-launch query tiles by liveness (live / pad — pad "
            "tiles cost no DMA, only grid steps)", ("state",),
        )
        self.metrics.counter(
            "dli_ragged_launches_total",
            "ragged ingest launches", ("phase",),
        )
        self.metrics.counter(
            "dli_ragged_exact_prefix_hits_total",
            "prefix hits reused at exact chunk depth (no bucket "
            "degradation — the ragged path's planner win)",
        )
        self.metrics.gauge(
            "dli_ragged_compiled_programs",
            "compiled ragged ingest programs (flat after warmup = no "
            "per-tail-shape recompile)",
        )
        # SLO-aware chunked-prefill scheduler families (engine/
        # scheduler.py labels them when the chunked path is live): mixed-
        # launch composition plus per-class admission state — pre-
        # registered here so a scrape's schema is stable across configs
        self.metrics.counter(
            "dli_sched_step_tokens_total",
            "flat tokens launched by the chunked-prefill scheduler, by "
            "kind (decode rows / prefill chunk tokens)", ("kind",),
        )
        self.metrics.counter(
            "dli_sched_prefill_chunks_total",
            "prefill chunks interleaved into mixed scheduler launches",
        )
        self.metrics.counter(
            "dli_sched_decode_rows_total",
            "decode rows carried by mixed scheduler launches",
        )
        # fleet speculative-decoding families (engine/continuous.py
        # labels them when the mixed fleet speculates — ISSUE 13):
        # draft/accept/reject token flow, verify-row launches by draft
        # source, and the accepted-tokens-per-launch distribution the
        # bench leg's headline derives from
        self.metrics.counter(
            "dli_spec_drafted_tokens_total",
            "draft tokens submitted in mixed-launch verify rows",
        )
        self.metrics.counter(
            "dli_spec_accepted_tokens_total",
            "draft tokens accepted (matched the model's own argmax and "
            "were emitted)",
        )
        self.metrics.counter(
            "dli_spec_rejected_tokens_total",
            "draft tokens rejected by the traced verify",
        )
        self.metrics.counter(
            "dli_spec_launches_total",
            "verify rows launched inside mixed scheduler steps, by draft "
            "source", ("mode",),
        )
        self.metrics.histogram(
            "dli_spec_tokens_per_launch",
            "tokens emitted per verify row (accepted drafts + the "
            "correction token; > 1 is the speculation win)",
            buckets=DEFAULT_SIZE_BUCKETS,
        )
        # adaptive drafting (device-derived metadata, ISSUE 15): the
        # planned K per verify row and the fleet-mean per-slot
        # acceptance EWMA the adaptive throttle steers by
        self.metrics.histogram(
            "dli_spec_draft_len",
            "planned draft length K per verify row (after the adaptive "
            "per-slot throttle)",
            buckets=DEFAULT_SIZE_BUCKETS,
        )
        self.metrics.gauge(
            "dli_spec_accept_ewma",
            "fleet-mean per-slot draft acceptance-rate EWMA (0..1)",
        )
        self.metrics.gauge(
            "dli_slo_queue_depth",
            "queued requests per SLO class and tenant", ("slo_class", "tenant"),
        )
        self.metrics.counter(
            "dli_slo_shed_total",
            "requests shed with 429 by SLO admission control (class drain "
            "estimate over the TTFT target, or queue full)", ("slo_class",),
        )
        # multi-tenant adapter-serving families (engine/adapters.py pool +
        # the continuous engine's per-tenant quota shed): pool residency /
        # reserved HBM, page traffic, and tenant-level shedding
        self.metrics.gauge(
            "dli_adapter_pool_resident",
            "adapters resident in device pool pages (referenced + LRU)",
        )
        self.metrics.gauge(
            "dli_adapter_pool_bytes",
            "HBM bytes reserved by the paged adapter leaves (all pages, "
            "base page included)",
        )
        self.metrics.counter(
            "dli_adapter_loads_total",
            "adapter page writes into the device pool",
        )
        self.metrics.counter(
            "dli_adapter_evictions_total",
            "resident adapters dropped from their page (LRU reclaim; "
            "referenced pages are never evicted)",
        )
        self.metrics.counter(
            "dli_adapter_swaps_total",
            "page loads that displaced another adapter (evict + write on "
            "one page)",
        )
        self.metrics.counter(
            "dli_tenant_shed_total",
            "requests shed with 429 by per-tenant quota control (router "
            "inflight share or scheduler queue share)", ("tenant",),
        )
        # pp wire-format families (ops/wire_quant.py + the SPMD backends'
        # static per-launch accounting): inter-stage activation bytes per
        # ICI link by transfer family, and whether the int8 wire is on.
        # Byte counts are host-side arithmetic from program shapes at the
        # launch seams — nothing is traced, decode while_loops count
        # their full ring-pass upper bound.
        self.metrics.counter(
            "dli_pp_wire_bytes_total",
            "inter-stage activation bytes shipped on the pp/sp wire, by "
            "transfer family", ("path",),
        )
        self.metrics.gauge(
            "dli_pp_wire_quant",
            "1 when the int8 inter-stage wire format "
            "(EngineConfig.pp_wire_quant) is active on this backend",
        ).labels().set(
            1.0 if getattr(self.backend, "wire_quant", None) else 0.0
        )
        if hasattr(self.backend, "attach_wire_metrics"):
            self.backend.attach_wire_metrics(self.metrics)
        # Build identity (ISSUE 17 satellite): one always-1 gauge whose
        # LABELS carry the version/runtime/config identity — the standard
        # Prometheus build_info idiom, joinable against every other
        # dli_* series. Kept to 4 literal labels (metrics-labels rule):
        # the pp-wire/model-quant knobs collapse into one `knobs` string.
        from .. import __version__ as _dli_version
        self.metrics.gauge(
            "dli_build_info",
            "build/version identity (value is always 1; the labels are "
            "the payload — join against any dli_* series)",
            ("version", "jax", "replica_class", "knobs"),
        ).labels(
            version=_dli_version,
            jax=jax.__version__,
            replica_class=engine_cfg.replica_class,
            knobs=(
                f"quant={cfg.quant or 'none'}"
                f",kv={cfg.kv_quant or 'none'}"
                f",wire={engine_cfg.pp_wire_quant or 'none'}"
            ),
        ).set(1.0)
        # Fleet tracing (ISSUE 17): the per-process span store this
        # engine's serving edge records into (replica request spans,
        # stage-segment child spans, fabric pulls, sampled launch
        # attribution), and the control-plane flight recorder the
        # continuous supervisor dumps into crash reports. Both bounded,
        # both host-side only.
        self.trace_store = TraceStore(
            service=f"replica-{engine_cfg.replica_class}"
        )
        self.flight = FlightRecorder()
        # Paged runtime LoRA adapter pool (engine/adapters.AdapterPool) —
        # wired by create_engine (EngineConfig.adapter_slots > 0) or
        # adapters.attach_adapter_pool; None = base-only serving.
        self.adapters = None
        # Reusable KV cache buffer: allocated once, donated to prefill/decode
        # each request and replaced by the returned buffer. Stale contents
        # between requests are harmless — prefill rewrites slots [0, bucket)
        # and the causal mask hides every slot beyond the current position.
        self._cache = None
        # Same donate-and-restore pattern per batch bucket: without it every
        # batched request allocates (and drops) a Bb x max_seq cache — multi-
        # GB HBM churn on the hot batched path.
        self._batch_caches: dict[int, Any] = {}
        # Prefix KV snapshots (engine/prefix.py); disabled at 0 entries,
        # for backends that cannot resume ingestion at an offset (no
        # extend/prefill_at — snapshots could be stored but never
        # spliced), and auto-disabled for cache layouts that cannot
        # snapshot/splice (checked against the live buffer later).
        self._prefix = None
        if engine_cfg.prefix_cache_entries > 0:
            if hasattr(self.backend, "prefill_at"):
                self._prefix = PrefixCache(
                    engine_cfg.prefix_cache_entries, engine_cfg.prefix_chunk,
                    registry=self.metrics, scope="solo",
                )
            else:
                log.info("prefix_cache_disabled", reason="backend lacks prefill_at")
        # Two-model speculative decoding (set_draft): (dcfg, dparams) of a
        # smaller same-tokenizer model + its reusable donated KV cache
        self._draft = None
        self._draft_cache = None
        # Grammar-constraint compiled-artifact cache (constrain/): LRU by
        # canonical constraint hash. The token vocab + trie are built once
        # (lazily — tokenizer byte extraction is per-engine, not per-spec)
        # and shared by every compile; artifacts keep their device tables
        # warm so repeated constraints re-upload nothing.
        self._constraint_cache = collections.OrderedDict()
        self._constraint_vocab = None
        self._constraint_trie = None
        # own lock: the continuous worker thread and request threads both
        # compile (engine._lock is held for whole generations — a compile
        # must not queue behind a multi-second decode)
        self._constraint_lock = threading.Lock()
        # Abandoned (deadline-overrun) device calls still running on their
        # daemon threads: token -> {"what", "since"}. /health flips to
        # "degraded" while any exists (round-2 review weak #5 — on a flaky
        # tunnel this is THE failure mode), and the server's optional
        # --die-on-wedge reaper exits the process off max_wedged_age().
        self._wedged: dict = {}  # guarded-by: _wedged_lock
        self._wedged_lock = threading.Lock()

    def set_draft(self, dcfg: ModelConfig, dparams: Any = None,
                  seed: int = 1):
        """Attach a draft model for two-model speculative decoding.

        The draft must share the target's tokenizer/vocab (token ids are
        compared against the target's argmax); the single-device backend
        and the pp pipeline (replicated draft inside the ring) run the
        combined verify program.
        """
        if dparams is None:
            dparams = M.init_params(dcfg, jax.random.PRNGKey(seed))
        if dcfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {dcfg.vocab_size} != target vocab "
                f"{self.cfg.vocab_size}; draft and target must share a "
                f"tokenizer"
            )
        if not getattr(self.backend, "supports_draft", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support draft-model "
                f"speculation; serve on the single-device or pipeline backend"
            )
        self._draft = (dcfg, dparams)
        self._draft_cache = None

    # -- helpers ------------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _with_deadline(self, fn, what: str, deadline_s: Optional[float] = None,
                       exceeded_type: str = "timeout"):
        """Run fn() under the configured per-request deadline.

        TPU-native analogue of the reference's per-hop 30s timeout
        (orchestration.py:118,131): a request that overruns gets a timeout
        envelope (error_type "timeout" -> HTTP 503) while the stuck call is
        abandoned to a daemon thread. The engine lock frees when that
        thread finishes, so one wedged device call delays — but never
        permanently wedges — subsequent requests; they time out cleanly
        against the same deadline until the lock frees.

        deadline_s overrides the configured server-wide cap (the
        end-to-end deadline_ms surface passes the request's remaining
        budget); exceeded_type names the envelope's error_type —
        "deadline_exceeded" (HTTP 504, never router-retried) when the
        request's own budget is the binding constraint.
        """
        deadline = (
            deadline_s if deadline_s is not None
            else self.engine_cfg.request_deadline_s
        )
        if not deadline:
            return fn()
        box: dict = {}
        token = object()

        def run():
            try:
                box["result"] = fn()
            except BaseException as e:  # re-raised on the caller thread
                box["exc"] = e
            finally:
                # the abandoned call finally drained: /health un-degrades.
                # box["done"] is flipped under the SAME lock that guards
                # registration, so a call finishing exactly at the deadline
                # can never leave a permanent stale entry (Thread.is_alive
                # cannot arbitrate this — it stays True past this finally)
                with self._wedged_lock:
                    box["done"] = True
                    self._wedged.pop(token, None)
                    self._m_wedged.set(len(self._wedged))

        t = threading.Thread(target=run, daemon=True, name=f"engine-{what}")
        t.start()
        t.join(deadline)
        if t.is_alive():
            log.error("request_deadline_exceeded", what=what, deadline_s=deadline)
            with self._wedged_lock:
                if not box.get("done"):
                    # `since` = the moment of ABANDONMENT (not call start:
                    # the reported age — and --die-on-wedge's threshold —
                    # count time stuck PAST the deadline), on the monotonic
                    # clock (a wall-clock NTP step must never exit(17) a
                    # healthy process)
                    self._wedged[token] = {
                        "what": what, "since": time.monotonic(),
                    }
                    self._m_wedged.set(len(self._wedged))
            return {
                "error": f"Error: request exceeded the {deadline:g}s deadline",
                "status": "failed",
                "error_type": exceeded_type,
            }
        if "exc" in box:
            raise box["exc"]
        return box["result"]

    def wedged_info(self) -> list[dict]:
        """Abandoned deadline-overrun calls still occupying the device:
        [{"what", "age_s"}] — age counted from ABANDONMENT (deadline
        overrun), oldest first. Empty = not wedged."""
        now = time.monotonic()
        with self._wedged_lock:
            entries = [
                {"what": e["what"], "age_s": round(now - e["since"], 1)}
                for e in self._wedged.values()
            ]
        return sorted(entries, key=lambda e: -e["age_s"])

    def max_wedged_age(self) -> Optional[float]:
        info = self.wedged_info()
        return info[0]["age_s"] if info else None

    def _buckets(self):
        return tuple(b for b in self.engine_cfg.prefill_buckets if b <= self.cfg.max_seq_len)

    def _clamp_decode(
        self, frame: int, max_tokens: int, headroom: int = 0,
        capacity: Optional[int] = None,
    ) -> tuple[int, int]:
        """Cache-capacity discipline in ONE place: frame + generated (+
        `headroom` scratch slots, e.g. speculative drafts written past the
        last emitted token) must fit the cache capacity (update_kv_cache
        clamps silently out of range — never allow it), also bounded by the
        largest compiled decode bucket. capacity defaults to max_seq_len;
        the continuous engine passes its per-slot budget (a slot class
        smaller than the model's window). Returns (max_tokens,
        decode_bucket)."""
        cap = capacity if capacity is not None else self.cfg.max_seq_len
        max_tokens = max(
            1,
            min(
                int(max_tokens),
                cap - frame - 1 - headroom,
                DECODE_BUCKETS[-1],
            ),
        )
        return max_tokens, G.pick_bucket(DECODE_BUCKETS, max_tokens)

    def _plan(self, longest_prompt: int, max_tokens: int):
        """Bucketing/clamping for BATCHED requests (left-padded: the whole
        bucket is the position frame). Single requests plan through
        _plan_ingest. Returns (bucket, max_tokens, decode_bucket)."""
        buckets = self._buckets()
        if not buckets or longest_prompt > buckets[-1]:
            raise ValueError(
                f"prompt length {longest_prompt} exceeds max prefill bucket "
                f"{buckets[-1] if buckets else 0}"
            )
        bucket = G.pick_bucket(buckets, longest_prompt)
        max_tokens, decode_bucket = self._clamp_decode(bucket, max_tokens)
        return bucket, max_tokens, decode_bucket

    def _row_tokens(self, first_id: int, row_out, n: int) -> list:
        """Assemble one row's emitted ids (stop-token-as-first excluded,
        matching the reference's break-before-append,
        orchestration.py:181-186)."""
        head = [first_id] if first_id not in self.cfg.all_stop_ids else []
        return head + [int(t) for t in list(row_out[:n])]

    @staticmethod
    def _truncate_at_stop(text: str, stop) -> tuple:
        """Cut `text` at the EARLIEST occurrence of any stop string
        (OpenAI-style "stop" sequences — the stop text itself is excluded,
        matching the stop-token break-before-append discipline). Returns
        (text, hit: bool)."""
        if not stop:
            return text, False
        cut = min(
            (i for i in (text.find(s) for s in stop if s) if i >= 0),
            default=-1,
        )
        if cut < 0:
            return text, False
        return text[:cut], True

    def _record_sample(self, ttft: float, per_stream_tps: float, tokens: int,
                       elapsed: Optional[float] = None,
                       engine: str = "solo",
                       trace_id: Optional[str] = None):
        """Per-STREAM throughput sample (batch requests divide by B), so
        /stats percentiles stay comparable to the single-stream metric.

        The ONE seam feeding both observability views: the rolling deque
        (/stats percentiles) and the registry histograms (/metrics). Only
        recorded traffic reaches either — warmup never calls this, so it
        is excluded from both views identically.

        trace_id, when the request carried a fleet trace context, becomes
        the latency histograms' EXEMPLAR: each bucket remembers the most
        recent (trace_id, value) that landed in it, so a p99 bucket in
        the JSON snapshot links to one concrete inspectable trace."""
        with self._samples_lock:
            self._samples.append(
                {"ttft_s": ttft, "tokens_per_sec": per_stream_tps, "tokens": tokens}
            )
            self._samples_total += 1
        self._m_ttft.labels(engine=engine).observe(ttft, trace_id=trace_id)
        self._m_tokens.labels(engine=engine).inc(tokens)
        if elapsed is not None:
            self._m_duration.labels(engine=engine).observe(
                elapsed, trace_id=trace_id
            )
            if tokens > 1:
                # TPOT (inter-token time): decode wall over the tokens
                # after the first — the metric that exposes slow steps
                # independently of prompt length
                self._m_tpot.labels(engine=engine).observe(
                    max(0.0, elapsed - ttft) / (tokens - 1),
                    trace_id=trace_id,
                )

    # -- main entry ----------------------------------------------------------
    def generate(
        self,
        prompt: str,
        max_tokens: int = 20,
        temperature: float = 0.7,
        top_k: int = 50,
        top_p: float = 0.9,
        greedy: bool = False,
        chat: bool = True,
        seed: Optional[int] = None,
        debug: bool = False,
        speculative: bool = False,
        min_p: float = 0.0,
        repetition_penalty: float = 1.0,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        stop: Optional[list] = None,
        logprobs: bool = False,
        logit_bias: Optional[dict] = None,
        num_beams: int = 1,
        length_penalty: float = 1.0,
        early_stopping: bool = False,
        constraint: Optional[dict] = None,
        request_id: Optional[str] = None,
        slo_class: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        _trace: Optional[Trace] = None,
    ) -> dict:
        """Full generation; returns the reference-schema response dict.

        debug=True adds "top_predictions": the top-5 first-token
        candidates with probabilities (the reference prints these,
        orchestration.py:172-178; here they are response data, not stdout).
        speculative=True uses prompt-lookup self-speculation for GREEDY
        requests on capable backends (several tokens per forward on
        repetitive text; every emitted token is still an argmax — exact
        vs plain greedy in fp32, while bf16 may resolve numerical
        near-ties differently); ignored otherwise.
        min_p / repetition_penalty: HF-parity sampling extensions
        (MinPLogitsWarper / RepetitionPenaltyLogitsProcessor; 0.0 / 1.0 =
        off). A repetition penalty disables speculation: it changes the
        argmax the draft verification compares against.
        frequency_penalty / presence_penalty: the OpenAI penalties over
        GENERATED-token counts (logits -= fp*count + pp*(count>0); 0.0 =
        off, the usual [-2, 2] range accepted). Like the repetition
        penalty they ride the pre-warper slot, apply to greedy argmax
        too, and disable speculation.
        logit_bias: {token_id: bias} added to the raw logits at every
        sample (OpenAI semantics; -100/+100 ban/force). Also disables
        speculation (it changes the verify argmax), and reported
        token_logprobs stay the RAW model distribution.
        num_beams > 1: deterministic beam search (HF generate(num_beams=N,
        do_sample=False) semantics; length_penalty / early_stopping as in
        HF). Sampling params / speculation / logprobs / bias are ignored
        on the beam path — it is a pure max-score search (HF ignores them
        the same way) — EXCEPT the OpenAI penalties, which reject loudly:
        they alter which continuation wins, so dropping them would change
        results silently rather than fall back to documented semantics.
        """
        t_start = time.time()
        trace = _trace if _trace is not None else Trace(request_id)

        with request_id_context(trace.request_id):
            dl_s, dl_type = self._resolve_deadline(deadline_ms)
            if dl_s is not None and dl_s <= 0:
                # end-to-end budget already spent (queue/router hops ate
                # it): fail before touching the device
                self._m_deadline_exceeded.inc()
                result = {
                    "error": "Error: request exceeded its deadline_ms "
                    "budget before generation",
                    "status": "failed",
                    "error_type": "deadline_exceeded",
                }
                return self._finish_request(result, trace, engine="solo")
            result = self._generate_traced(
                prompt, max_tokens, temperature, top_k, top_p, greedy, chat,
                seed, debug, speculative, min_p, repetition_penalty,
                frequency_penalty, presence_penalty, stop, logprobs,
                logit_bias, num_beams, length_penalty, early_stopping,
                constraint, t_start, trace,
                deadline_s=dl_s, exceeded_type=dl_type,
            )
            if result.get("error_type") == "deadline_exceeded":
                self._m_deadline_exceeded.inc()
            if slo_class is not None:
                # admission priority is a fleet concept (the continuous
                # scheduler's SLO classes); the solo path serves directly
                # but accepts + echoes the class so fleet fallbacks and
                # class-tagged clients keep one request schema
                result.setdefault("slo_class", slo_class)
            return self._finish_request(result, trace, engine="solo")

    def _resolve_deadline(self, deadline_ms) -> tuple:
        """(deadline_s, exceeded_type) for a request carrying an
        end-to-end deadline_ms: the binding constraint is the smaller of
        the request's remaining budget and the server-wide
        request_deadline_s cap; the envelope's error_type follows the
        binding one ("deadline_exceeded" -> HTTP 504, never retried by
        the router — "timeout" -> 503 keeps its legacy semantics)."""
        cfg_s = self.engine_cfg.request_deadline_s
        if deadline_ms is None:
            return None if not cfg_s else cfg_s, "timeout"
        req_s = float(deadline_ms) / 1e3
        if cfg_s and cfg_s < req_s:
            return cfg_s, "timeout"
        return req_s, "deadline_exceeded"

    def _generate_traced(
        self, prompt, max_tokens, temperature, top_k, top_p, greedy, chat,
        seed, debug, speculative, min_p, repetition_penalty,
        frequency_penalty, presence_penalty, stop, logprobs, logit_bias,
        num_beams, length_penalty, early_stopping, constraint, t_start,
        trace, deadline_s=None, exceeded_type="timeout",
    ) -> dict:
        if constraint is not None and (num_beams > 1 or speculative):
            # grammar constraints do not compose with beam search (no
            # per-beam FSM state threads the beam reorder) nor with
            # speculative verify (the draft argmax comparison ignores the
            # mask) in this PR — reject loudly, never silently drop the
            # grammar (a "guaranteed-valid JSON" promise silently broken
            # is the worst possible failure mode)
            what = "num_beams > 1" if num_beams > 1 else "speculative"
            msg = f"constraint does not compose with {what}"
            log.warning("invalid_request", error=msg)
            return {"error": f"Error: {msg}", "status": "failed",
                    "error_type": "invalid_request"}

        if num_beams > 1 and (frequency_penalty != 0.0 or presence_penalty != 0.0):
            # the beam path is a pure max-score search with no per-beam
            # count tracking: reject loudly instead of silently returning
            # unpenalized output. (Sampling params / logprobs / bias stay
            # silently ignored on beams — HF-parity semantics the
            # docstring documents; the penalties have no such precedent.)
            msg = (
                "frequency_penalty/presence_penalty are not supported with "
                "num_beams > 1; drop the penalties or use sampling"
            )
            log.warning("invalid_request", error=msg)
            return {"error": f"Error: {msg}", "status": "failed",
                    "error_type": "invalid_request"}

        def locked():
            with self._lock:
                # lock wait = this engine's queueing delay (requests
                # arriving through serving/queue.py fold their dispatcher
                # wait into the same span via the shared trace)
                trace.checkpoint("queue_wait")
                if num_beams > 1:
                # jaxlint: disable=blocking-under-lock -- the engine lock IS the device-serialization point; a generation holds it end to end by design
                    return self._beam_locked(
                        prompt, max_tokens, num_beams, length_penalty,
                        early_stopping, chat, t_start, stop, trace,
                    )
                # jaxlint: disable=blocking-under-lock -- the engine lock IS the device-serialization point; a generation holds it end to end by design
                return self._generate_locked(
                    prompt, max_tokens, temperature, top_k, top_p, greedy, chat,
                    seed, t_start, debug, speculative, min_p,
                    repetition_penalty, stop, logprobs, logit_bias,
                    frequency_penalty, presence_penalty, constraint, trace,
                )

        try:
            return self._with_deadline(
                locked, "generate", deadline_s=deadline_s,
                exceeded_type=exceeded_type,
            )
        except ValueError as e:
            # caller-caused (e.g. prompt longer than the largest prefill
            # bucket): tagged so the serving edge can answer 400, not 500
            log.warning("invalid_request", error=str(e))
            return {"error": f"Error: {e}", "status": "failed",
                    "error_type": "invalid_request"}
        except Exception as e:  # error envelope (orchestration.py:220-228)
            log.error("generate_failed", exc_info=True, error=str(e))
            return {"error": f"Error: {e}", "status": "failed"}

    def _finish_request(self, result: dict, trace: Trace, engine: str,
                        record: bool = True) -> dict:
        """Attach the trace to the envelope, count it, and log ONE
        structured `request_done` event. Shared by the solo/batch/beam
        paths and the continuous engine's finalizer (record=False for
        warmup traffic — excluded from metrics exactly like /stats)."""
        result.setdefault("request_id", trace.request_id)
        result.setdefault("timings", trace.timings())
        if not record:
            return result
        status = result.get("status")
        if status == "success":
            self._m_requests.labels(engine=engine, model=self.cfg.name).inc()
            if result.get("speculative"):
                self._m_speculative.labels(engine=engine).inc()
        else:
            self._m_failures.labels(
                engine=engine,
                error_type=result.get("error_type", "internal"),
            ).inc()
        log.info(
            "request_done", request_id=trace.request_id, status=status,
            engine=engine, tokens=result.get("tokens_generated"),
            **result["timings"],
        )
        return result

    def _plan_ingest(self, prompt_len: int, p0: int, buckets: tuple,
                     capacity: Optional[int] = None):
        """Plan feeding ids[p0:] into the cache at offset p0.

        Returns (n_full, rem, bucket, chunk) — n_full full-`chunk`
        extend() calls then a final `bucket`-padded sampling chunk of
        `rem` valid tokens — or None when this backend/bucket layout
        cannot ingest from that offset (callers retry with p0=0 or
        raise). The final chunk is a PADDED bucket whose pads also write
        K/V: its end must stay inside the cache capacity (default
        max_seq_len; the continuous engine plans against its per-slot
        budget) or update_kv_cache's silent clamp would overwrite real
        prompt slots.
        """
        cap = capacity if capacity is not None else self.cfg.max_seq_len
        if not buckets:
            return None
        if prompt_len > cap - 2:
            # capacity guard on EVERY path (not just chunked): a prefix-
            # cache hit with a short tail must reject exactly the prompts
            # the cold path rejects, or acceptance becomes a function of
            # cache state and decode's first KV write can silently clamp
            return None
        tail = prompt_len - p0
        chunk = buckets[-1]
        n_full = max(0, (tail - 1) // chunk)  # leaves >= 1 sampling token
        rem = tail - n_full * chunk
        needs_offset_ops = p0 > 0 or n_full > 0
        if needs_offset_ops and not hasattr(self.backend, "extend"):
            return None
        fitting = [
            b for b in buckets
            if b >= rem and p0 + n_full * chunk + b <= cap
        ]
        if not fitting:
            return None
        return n_full, rem, fitting[0], chunk

    def _ingest(self, ids, p0, plan, cache, key, sampling, presence=None,
                bias=None, backend=None):
        """Feed ids[p0:] into `cache` per a `_plan_ingest` plan: n_full
        full-chunk extend() calls, then the final bucket-padded sampling
        chunk (prefill at offset 0, prefill_at otherwise). Shared by the
        solo engine, the continuous engine's admission path, AND the
        draft model's prompt ingest (backend override) — one copy of the
        ingest sequence to fix. Returns (first, logits, cache).
        presence: optional [1, V] repetition-penalty token set for the
        first-token sample."""
        be = backend if backend is not None else self.backend
        n_full, rem, bucket, chunk = plan
        pad = self.cfg.pad_token_id
        for c in range(n_full):
            chunk_tokens = jnp.asarray(
                [ids[p0 + c * chunk : p0 + (c + 1) * chunk]], jnp.int32
            )
            cache = be.extend(
                chunk_tokens, jnp.int32(p0 + c * chunk), cache
            )
        tail_start = p0 + n_full * chunk
        tokens = jnp.asarray(
            [ids[tail_start:] + [pad] * (bucket - rem)], jnp.int32
        )
        # bias passed only when set: backends without logit_bias support
        # (no `bias` kwarg) still serve the default path — non-None is
        # already rejected upstream by the supports_bias gate
        kw = {"presence": presence}
        if bias is not None:
            kw["bias"] = bias
        if tail_start == 0:
            return be.prefill(
                tokens, jnp.int32(len(ids)), cache, key, sampling, **kw
            )
        return be.prefill_at(
            tokens, jnp.int32(tail_start), jnp.int32(rem), cache, key,
            sampling, **kw,
        )

    def _prefix_plan(self, prefix, ids: list, capacity: Optional[int] = None,
                     ragged: bool = False, adapter: Optional[str] = None):
        """Prefix lookup + ingest planning, ONE copy for every serving
        path: lookup -> plan the tail -> cold fallback when no tail plan
        fits -> mark hit/miss on the PLANNED outcome (a lookup hit that
        fell back cold is a miss). Returns (p0, entry, plan).

        `prefix` is any PLANNER implementing the two-method protocol
          lookup(ids) -> (p0, entry, key)   # reusable depth + opaque entry
          mark(key, hit)                    # counters + LRU promotion
        — engine/prefix.PrefixCache (dense snapshots: entry is a KV
        pytree the caller splices) and engine/block_prefix.BlockPrefixIndex
        (paged fleets: entry is the shared physical block ids the caller
        maps into the request's block table) both satisfy it; None means
        a plain cold plan. What "reuse" physically does with `entry` is
        the caller's business — this helper owns only the depth/plan/mark
        discipline, which is identical across planners.

        ragged=True (paged admission through the ragged ingest,
        engine/paged.extend_ragged_paged): there is no bucket ladder to
        fit, so ANY tail length >= 1 is serveable and the deepest lookup
        depth is used AS IS — exact-chunk-depth reuse, never degraded.
        The plan is the ("ragged", tail_len) sentinel; only the capacity
        guard can reject (same bound as the cold path, so acceptance
        stays independent of cache state).

        adapter: runtime adapter name for content-keyed planners — the
        adapter changes the KV bytes, so BlockPrefixIndex keys chains
        under a per-adapter root and two adapters (or an adapter and the
        base) never share blocks even for identical prompts. Dense
        PrefixCache planners don't take it (adapter requests bypass them
        entirely — they run the paged fleet)."""
        buckets = self._buckets()
        prompt_len = len(ids)
        p0, entry, pkey = 0, None, None
        if prefix is not None:
            if adapter is not None:
                p0, entry, pkey = prefix.lookup(ids, adapter=adapter)
            else:
                p0, entry, pkey = prefix.lookup(ids)
        if ragged:
            cap = capacity if capacity is not None else self.cfg.max_seq_len
            ok = 1 <= prompt_len <= cap - 2
            plan = ("ragged", prompt_len - p0) if ok else None
            if plan is None or not p0:
                entry = None
                if plan is None:
                    p0 = 0
            if prefix is not None:
                prefix.mark(pkey, hit=bool(p0), depth=p0)
            return p0, entry, plan
        plan = self._plan_ingest(prompt_len, p0, buckets, capacity)
        # Depth degradation (BUCKETED fallback path only — the ragged
        # branch above never degrades): the deepest reuse offset can
        # leave a tail no prefill bucket fits inside the capacity (e.g. a
        # hit at offset 96 in a 128-token window with a 64-token smallest
        # bucket). Both reuse mechanisms serve ANY aligned depth (a
        # snapshot splices its first p0 slots; a block chain maps its
        # first p0/bs blocks), so walk down one planner granule at a time
        # before giving the whole prefix up — partial reuse beats cold.
        step = getattr(prefix, "chunk", 0)
        while plan is None and p0 > step > 0:
            p0 -= step
            plan = self._plan_ingest(prompt_len, p0, buckets, capacity)
        if plan is None and p0:
            p0 = 0
            plan = self._plan_ingest(prompt_len, 0, buckets, capacity)
        if not p0:
            entry = None
        if prefix is not None:
            prefix.mark(pkey, hit=bool(p0) and plan is not None, depth=p0)
        return p0, entry, plan

    def _ingest_with_prefix(
        self, prefix, ids, p0, entry, plan, cache, key, sampling,
        presence=None, bias=None,
    ):
        """Splice a prefix hit, run the shared ingest sequence, store the
        (now complete) prompt KV back into the prefix cache. The
        splice-before-ingest / store-after-ingest ordering is correctness-
        critical (the stored snapshot must cover the whole prompt)."""
        if entry is not None:
            cache = prefix.splice(entry, cache, p0)
        first, logits, cache = self._ingest(
            ids, p0, plan, cache, key, sampling, presence=presence, bias=bias
        )
        if prefix is not None:
            prefix.store(ids, len(ids), cache)
        return first, logits, cache

    def _draft_ingest(self, ids: list, dcache):
        """Prefill the whole prompt into the DRAFT model's cache (two-model
        speculation): the SAME _ingest sequence as the target, driven
        through a single-device backend view over (dcfg, dparams) — one
        ingest copy to fix. No prefix cache (correctness over draft-side
        TTFT); the draft's sampled first token is discarded, only its KV
        matters."""
        dcfg, dparams = self._draft
        plan = self._plan_ingest(len(ids), 0, self._buckets())
        if plan is None:  # main path already accepted this prompt
            raise ValueError(
                f"prompt length {len(ids)} exceeds draft ingest capacity"
            )
        _, _, dcache = self._ingest(
            ids, 0, plan, dcache, jax.random.PRNGKey(0),
            G.default_sampling(greedy=True),
            backend=SingleDeviceBackend(dcfg, dparams),
        )
        return dcache

    # guarded-by: _lock
    def _beam_locked(self, prompt, max_tokens, num_beams, length_penalty,
                     early_stopping, chat, t_start, stop, trace=None):
        """Deterministic beam search (engine side): prefill the prompt
        ONCE (batch 1), tile the prompt KV and first-position logits to
        [num_beams] rows, then G.decode_beam. Tiling instead of an
        [num_beams]-row prefill saves (num_beams-1) prompt forwards AND
        keeps the logits contract backend-independent — a fleet-granular
        backend's fleet prefill returns zero-width logits by design, which
        an [num_beams]-row prefill would hand decode_beam whenever
        num_beams lands on the fleet granularity."""
        cfg = self.cfg
        self.request_count += 1
        if not getattr(self.backend, "supports_beam", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support beam "
                f"search; serve num_beams > 1 on the single-device or pipeline backend"
            )
        if not 2 <= num_beams <= 16:
            raise ValueError("num_beams must be between 2 and 16")
        text = self.render_chat(prompt) if chat else prompt
        ids = self.tokenizer.encode(text)
        prompt_len = len(ids)
        buckets = self._buckets()
        if not buckets or prompt_len > buckets[-1]:
            raise ValueError(
                f"prompt length {prompt_len} exceeds max prefill bucket "
                f"{buckets[-1] if buckets else 0} (beam search prefills in "
                f"one bucket)"
            )
        bucket = G.pick_bucket(buckets, prompt_len)
        max_tokens, decode_bucket = self._clamp_decode(prompt_len, max_tokens)
        pad = cfg.pad_token_id
        row = ids + [pad] * (bucket - prompt_len)
        tokens = jnp.asarray([row], jnp.int32)
        cache1 = self._cache or self.backend.init_cache(1, cfg.max_seq_len)
        self._cache = None  # donated into prefill; restored below
        sampling = G.default_sampling(greedy=True)
        _, logits, cache1 = self.backend.prefill(
            tokens, jnp.int32(prompt_len), cache1, jax.random.PRNGKey(0),
            sampling,
        )
        # every beam starts from the same prompt: tile batch axis 1 of
        # each cache leaf (KVQuant scale leaves ride the same recipe one
        # rank down) and the [1, V] first-position logits
        cache = jax.tree.map(
            lambda x: jnp.tile(x, (1, num_beams) + (1,) * (x.ndim - 2)),
            cache1,
        )
        logits = jnp.tile(logits, (num_beams, 1))
        ttft = time.time() - t_start
        if trace is not None:
            trace.checkpoint("prefill")
        out, n_gen, scores, cache = self.backend.decode_beam(
            logits, cache, jnp.int32(prompt_len), jnp.int32(max_tokens),
            jnp.float32(length_penalty), max_steps=decode_bucket,
            num_beams=num_beams, early_stopping=early_stopping,
        )
        out = jax.block_until_ready(out)
        self._cache = cache1  # the batch-1 scratch, stale rows masked
        if trace is not None:
            trace.checkpoint("decode")

        beams = []
        for b in range(num_beams):
            n = int(n_gen[b])
            txt = self.tokenizer.decode(
                [int(t) for t in np.asarray(out[b][:n])],
                skip_special_tokens=True,
            )
            txt, b_stopped = self._truncate_at_stop(txt, stop)
            beams.append({
                "text": txt, "score": round(float(scores[b]), 6),
                "tokens": n, "stopped": b_stopped,
            })
        best = beams[0]
        if trace is not None:
            trace.checkpoint("detokenize")
        elapsed = time.time() - t_start
        n = best["tokens"]
        tps = n / elapsed if elapsed > 0 else 0.0
        self._record_sample(ttft, tps, n, elapsed=elapsed)
        log.info(
            "beam_request", model=cfg.name, backend=self.backend.name,
            num_beams=num_beams, tokens=n, elapsed_s=round(elapsed, 3),
        )
        result = {
            "prompt": prompt,
            "response": best["text"],
            "status": "success",
            "time_taken": f"{elapsed:.2f}s",
            "tokens_generated": n,
            "prompt_tokens": prompt_len,
            "tokens_per_sec": f"{tps:.2f}",
            "ttft_s": round(ttft, 4),
            "backend": self.backend.name,
            "num_beams": num_beams,
            "beams": beams,
            "finish_reason": (
                "stop" if best["stopped"] or n < max_tokens else "length"
            ),
        }
        if best["stopped"]:
            result["stopped"] = True
        return result

    def score(self, prompt: str, top_n: int = 0) -> dict:
        """Teacher-forced per-token log-probabilities of `prompt` itself
        (no generation): the OpenAI echo+logprobs+max_tokens=0 pattern
        that evaluation harnesses use for loglikelihood scoring. top_n
        (0..5): also return each position's top-N alternatives (lm-eval
        reads them for its is_greedy check)."""
        t_start = time.time()

        def locked():
            with self._lock:
                return self._score_locked(prompt, int(top_n), t_start)

        try:
            return self._with_deadline(locked, "score")
        except ValueError as e:
            log.warning("invalid_request", error=str(e))
            return {"error": f"Error: {e}", "status": "failed",
                    "error_type": "invalid_request"}
        except Exception as e:  # noqa: BLE001 - envelope discipline
            log.error("score_failed", exc_info=True, error=str(e))
            return {"error": f"Error: {e}", "status": "failed"}

    # guarded-by: _lock
    def _score_locked(self, prompt: str, top_n: int, t_start: float) -> dict:
        cfg = self.cfg
        self.request_count += 1
        if not getattr(self.backend, "supports_score", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support scoring; "
                f"serve echo/logprobs scoring on the single-device or pipeline backend"
            )
        if not 0 <= top_n <= 5:
            raise ValueError("top_n must be between 0 and 5")
        ids = self.tokenizer.encode(prompt)
        if len(ids) < 2:
            raise ValueError("scoring needs at least 2 tokens")
        buckets = self._buckets()
        if not buckets or len(ids) > cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(ids)} exceeds max_seq_len "
                f"{cfg.max_seq_len}"
            )
        # chunk plan, mirroring chunked prefill: full chunks of the
        # largest bucket, then a padded final bucket; the KV cache chains
        # the chunks and each chunk's LAST distribution scores the next
        # chunk's first token across the boundary
        chunk = buckets[-1]
        n_full = max(0, (len(ids) - 1) // chunk)
        rem = len(ids) - n_full * chunk
        fitting = [b for b in buckets if b >= rem]
        if not fitting or n_full * chunk + fitting[0] > cfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(ids)} cannot be chunk-scored within "
                f"max_seq_len {cfg.max_seq_len}"
            )
        bucket = fitting[0]

        cache = self._cache or self.backend.init_cache(1, cfg.max_seq_len)
        self._cache = None  # donated scratch; restored below
        pad = cfg.pad_token_id
        lps: list = []
        tops: list = []
        prev_last = None  # np [V]: last distribution of the previous chunk

        def _top_dict(values, ids_):
            # distinct token ids can decode to the SAME string (byte-level
            # tokenizers); keep the best (first, descending) logprob per
            # string — the OpenAI dict format can't carry both
            d: dict = {}
            for v, i in zip(values, ids_):
                s = self.tokenizer.decode([int(i)])
                if s not in d:
                    d[s] = round(float(v), 6)
            return d

        def _boundary(tok: int):
            # score a chunk's first token from the PREVIOUS chunk's last
            # position (host-side: one [V] row per chunk)
            lps.append(float(prev_last[tok]))
            if top_n:
                idx = np.argpartition(-prev_last, top_n - 1)[:top_n]
                idx = idx[np.argsort(-prev_last[idx])]
                tops.append(_top_dict(prev_last[idx], idx))

        for c in range(n_full + 1):
            if c < n_full:
                rows = ids[c * chunk : (c + 1) * chunk]
                toks = jnp.asarray([rows], jnp.int32)
            else:
                rows = ids[n_full * chunk :]
                toks = jnp.asarray(
                    [rows + [pad] * (bucket - rem)], jnp.int32
                )
            within, top_v, top_i, last_lp, cache = self.backend.score_chunk(
                toks, jnp.int32(c * chunk), cache, top_n=top_n
            )
            within = np.asarray(within[0])
            top_v_np = np.asarray(top_v[0])
            top_i_np = np.asarray(top_i[0])
            if c > 0:
                _boundary(rows[0])
            valid = (len(rows) if c < n_full else rem) - 1
            lps.extend(float(x) for x in within[:valid])
            if top_n:
                for t in range(valid):
                    tops.append(_top_dict(top_v_np[t], top_i_np[t]))
            prev_last = np.asarray(last_lp[0])
        self._cache = cache

        lps = [round(x, 6) for x in lps]
        elapsed = time.time() - t_start
        result = {
            "prompt": prompt,
            "status": "success",
            "prompt_tokens": len(ids),
            # OpenAI convention: the first token has no conditional
            "token_logprobs": [None] + lps,
            "token_strings": [self.tokenizer.decode([t]) for t in ids],
            "logprob_sum": round(sum(lps), 6),
            "time_taken": f"{elapsed:.2f}s",
            "backend": self.backend.name,
        }
        if top_n:
            result["top_logprobs"] = [None] + tops
        return result

    def render_chat(self, prompt_or_messages) -> str:
        """Chat-format a user prompt string (or a full OpenAI-style
        message list) with the model's template. ONE copy of the
        template dispatch for the solo / batch / beam / continuous /
        OpenAI paths. cfg.chat_template == "hf" renders through the
        serving tokenizer's own jinja template (the one the checkpoint
        shipped with) — requires an HF tokenizer carrying one."""
        from .chat import format_chat_messages

        messages = (
            [{"role": "user", "content": prompt_or_messages}]
            if isinstance(prompt_or_messages, str)
            else prompt_or_messages
        )
        if self.cfg.chat_template == "hf":
            if not getattr(self.tokenizer, "has_chat_template", False):
                raise ValueError(
                    "chat_template='hf' needs an HF tokenizer with a chat "
                    "template; the serving tokenizer has none"
                )
            return self.tokenizer.apply_chat_template(messages)
        return format_chat_messages(
            messages, arch=self.cfg.arch, template=self.cfg.chat_template
        )

    def _compile_constraint(self, raw: dict):
        """Wire-format constraint -> CompiledConstraint through the engine
        LRU (engine_cfg.constraint_cache_entries). ValueError (malformed
        spec / unsupported schema / oversized DFA) propagates to the
        caller's invalid_request envelope."""
        from .. import constrain as C

        if not getattr(self.backend, "supports_constrain", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support "
                f"constrained decoding; serve constrained requests on the "
                f"single-device or pipeline backend"
            )
        spec = C.parse_constraint_spec(raw)
        key = C.constraint_key(spec)
        with self._constraint_lock:
            art = self._constraint_cache.get(key)
            if art is not None:
                self._constraint_cache.move_to_end(key)
                return art
            if self._constraint_vocab is None:
                self._constraint_vocab = C.TokenVocab.from_tokenizer(
                    self.tokenizer, self.cfg.vocab_size,
                    eos_ids=self.cfg.all_stop_ids,
                    special_ids=(self.cfg.pad_token_id, self.cfg.bos_token_id),
                )
                from ..constrain.tables import _build_trie

                self._constraint_trie = _build_trie(self._constraint_vocab)
            art = C.compile_constraint(
                spec, self._constraint_vocab, self._constraint_trie
            )
            self._constraint_cache[key] = art
            while len(self._constraint_cache) > max(
                1, self.engine_cfg.constraint_cache_entries
            ):
                self._constraint_cache.popitem(last=False)
            return art

    @staticmethod
    def _constraint_bias(art, bias):
        """Fold the start-state mask into the (possibly absent) logit_bias
        operand for the FIRST token (sampled by prefill, before any decode
        fsm exists): -1e9 on banned tokens can never be resurrected by a
        +100 user bias, and the constrained prefill reuses the compiled
        bias program variants instead of growing new ones."""
        mask_bias = jnp.asarray(art.start_bias())
        return mask_bias if bias is None else bias + mask_bias

    def _bias_array(self, logit_bias):
        """{token_id: bias} -> dense [V] f32 on validated ids, or None.

        Dense because the sampler adds it to the logits row every step
        (a scatter of a handful of floats — the [V] array is tiny next
        to one decode step's weight traffic)."""
        if not logit_bias:
            return None
        if not getattr(self.backend, "supports_bias", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support logit_bias; "
                f"serve biased requests on the single-device or pipeline backend"
            )
        import numpy as np

        b = np.zeros((self.cfg.vocab_size,), np.float32)
        for tid, v in logit_bias.items():
            t = int(tid)
            if not 0 <= t < self.cfg.vocab_size:
                raise ValueError(
                    f"logit_bias token id {t} outside vocab "
                    f"[0, {self.cfg.vocab_size})"
                )
            b[t] = float(v)
        return jnp.asarray(b)

    def _presence_rows(self, rows: list) -> jnp.ndarray:
        """[len(rows), V] bool: each row's token-id set, built host-side in
        numpy (the full prompt is already a host list — no device pass
        needed, and chunked prefill / prefix-cache hits see every token)."""
        import numpy as np

        out = np.zeros((len(rows), self.cfg.vocab_size), bool)
        for b, ids in enumerate(rows):
            out[b, np.asarray(ids, dtype=np.int64)] = True
        return jnp.asarray(out)

    def _decode_textual_stop_chunks(
        self, first, cache, prompt_len, max_tokens, key_dec, sampling, dkw,
        logprobs, stop, cart=None,
    ):
        """Bounded-chunk decode when textual `stop` sequences are set
        (round-2 review weak #4: the post-hoc check decoded the full
        budget — a 512-token request hitting its stop at token 5 burned
        507 wasted steps on device).

        Decodes chunks that ESCALATE up the DECODE_BUCKETS ladder (16, 32,
        64, ... — every rung a program --warmup already compiled): a stop
        matching early costs one small chunk, while a stop that never
        matches costs O(log budget) round-trips instead of budget/16.
        Checks the accumulated text between chunks and stops the moment a
        stop sequence appears; the caller's existing _truncate_at_stop
        does the exact final truncation. Stop-less requests never enter
        this path, so their device-call count is unchanged. Sampled
        (non-greedy) requests draw from a per-chunk key stream —
        deterministic for a fixed seed, but a different stream than the
        single-call path (greedy output is identical).

        Returns (out [1, N] np.int32, n_gen [1] np.int32, step_lps
        [1, N] np.float32 or None, cache).
        """
        import numpy as np

        budget = max_tokens - 1  # first token already sampled by prefill
        collected: list = []
        lps: list = []
        token = first
        pos = int(prompt_len)
        first_id = int(first[0])
        finished = first_id in self.cfg.all_stop_ids
        rung = 0
        while budget > 0 and not finished:
            chunk_bucket = DECODE_BUCKETS[min(rung, len(DECODE_BUCKETS) - 1)]
            rung += 1
            limit = min(budget, chunk_bucket)
            key_dec, sub = jax.random.split(key_dec)
            if logprobs:
                out_i, n_i, cache, lps_i = self.backend.decode(
                    token, cache, jnp.int32(pos), jnp.int32(limit), sub,
                    sampling, max_steps=chunk_bucket, with_logprobs=True,
                    **dkw,
                )
            else:
                lps_i = None
                out_i, n_i, cache = self.backend.decode(
                    token, cache, jnp.int32(pos), jnp.int32(limit), sub,
                    sampling, max_steps=chunk_bucket, **dkw,
                )
            n = int(n_i[0])
            row = [int(t) for t in np.asarray(out_i[0][:n])]
            collected += row
            if lps_i is not None:
                lps += [float(x) for x in np.asarray(lps_i[0][:n])]
            if n < limit:  # EOS early-exit inside the chunk
                finished = True
                break
            budget -= n
            pos += n
            # presence chunks: mark this chunk's tokens before the next
            if dkw.get("presence") is not None and row:
                pres = dkw["presence"]
                pres = pres.at[0, jnp.asarray(row, jnp.int32)].set(True)
                dkw = dict(dkw, presence=pres)
            if dkw.get("counts") is not None and row:
                # scatter-add accumulates duplicate ids within the chunk
                cnt = dkw["counts"]
                cnt = cnt.at[0, jnp.asarray(row, jnp.int32)].add(1)
                dkw = dict(dkw, counts=cnt)
            if cart is not None and row:
                # re-walk the chunk's tokens through the host transition
                # table so the next chunk resumes at the right FSM state
                # (a handful of numpy lookups per chunk, not per token)
                fsm_host = int(np.asarray(dkw["constraint"][0])[0])
                for t in row:
                    fsm_host = cart.advance(fsm_host, t)
                dkw = dict(dkw, constraint=(
                    jnp.asarray([fsm_host], jnp.int32),
                ) + dkw["constraint"][1:])
            text = self.tokenizer.decode(
                ([first_id] if first_id not in self.cfg.all_stop_ids else [])
                + collected,
                skip_special_tokens=True,
            )
            if any(s in text for s in stop):
                break
            token = jnp.asarray([row[-1]], jnp.int32) if row else token
        out = np.asarray([collected], np.int32)
        n_gen = np.asarray([len(collected)], np.int32)
        step_lps = np.asarray([lps], np.float32) if logprobs else None
        return out, n_gen, step_lps, cache

    # guarded-by: _lock
    def _generate_locked(
        self, prompt, max_tokens, temperature, top_k, top_p, greedy, chat,
        seed, t_start, debug=False, speculative=False, min_p=0.0,
        repetition_penalty=1.0, stop=None, logprobs=False, logit_bias=None,
        frequency_penalty=0.0, presence_penalty=0.0, constraint=None,
        trace=None,
    ):
        # chaos hook (utils/faults.py point "solo"): inside the deadline
        # wrapper, so a wedge_s > deadline rule exercises the abandoned-
        # call path — engine._wedged fills, /ready flips 503 past
        # --wedge-unready, and the router ejects the replica until the
        # sleep drains (the DLI_FAULTS wedge drill in tests/test_router)
        faults.check("solo", tag=prompt)
        cfg = self.cfg
        self.request_count += 1
        bias = self._bias_array(logit_bias)
        cart = self._compile_constraint(constraint) if constraint else None
        if cart is not None:
            bias = self._constraint_bias(cart, bias)
            if trace is not None:
                trace.checkpoint("constraint_compile")
        text = self.render_chat(prompt) if chat else prompt
        ids = self.tokenizer.encode(text)
        prompt_len = len(ids)

        buckets = self._buckets()
        if self._cache is None:
            self._cache = self.backend.init_cache(1, cfg.max_seq_len)
        if self._prefix is not None and not PrefixCache.compatible(self._cache):
            # e.g. the context-parallel backend's slot-tagged cache; checked
            # against the live buffer so a warmup()-initialized cache is
            # covered too
            log.info("prefix_cache_disabled", reason="cache layout")
            self._prefix = None

        # prefix-cache lookup + ingest plan (shared helper; engine/prefix.py)
        p0, entry, plan = self._prefix_plan(self._prefix, ids)
        if plan is None:
            if prompt_len > cfg.max_seq_len - 2:
                raise ValueError(
                    f"prompt length {prompt_len} exceeds the cache capacity "
                    f"(max_seq_len {cfg.max_seq_len} less decode headroom)"
                )
            if (
                buckets
                and prompt_len > buckets[-1]
                and hasattr(self.backend, "extend")
            ):
                raise ValueError(
                    f"prompt length {prompt_len} cannot be chunk-prefilled: "
                    f"no prefill bucket fits the final chunk within "
                    f"max_seq_len {cfg.max_seq_len}"
                )
            raise ValueError(
                f"prompt length {prompt_len} exceeds max prefill bucket "
                f"{buckets[-1] if buckets else 0}"
            )
        n_full, rem, bucket, chunk = plan
        if logprobs and not getattr(self.backend, "supports_logprobs", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support per-token "
                f"logprobs; serve logprobs requests on the single-device or "
                f"pipeline backend"
            )
        spec_ok = (
            speculative
            and greedy
            # a repetition/OpenAI penalty or logit bias changes the argmax
            # the draft verification compares against — plain decode
            # instead; and the speculative loop records no per-step
            # logprobs
            and repetition_penalty == 1.0
            and frequency_penalty == 0.0
            and presence_penalty == 0.0
            and bias is None
            and not logprobs
        )
        # draft-model speculation wins over prompt-lookup when a draft is
        # attached (helps arbitrary text, not just self-repeating text)
        use_draft = (
            spec_ok
            and self._draft is not None
            and getattr(self.backend, "supports_draft", False)
        )
        use_spec = (
            spec_ok
            and not use_draft
            and getattr(self.backend, "supports_speculative", False)
        )
        max_tokens, decode_bucket = self._clamp_decode(
            prompt_len, max_tokens,
            headroom=SPEC_DRAFT_LEN if (use_spec or use_draft) else 0,
        )

        sampling = G.default_sampling(
            temperature, top_k, top_p, greedy, min_p, repetition_penalty,
            frequency_penalty, presence_penalty,
        )
        # presence (repetition-penalty token set): only materialized when
        # the penalty is on, so the reference-parity path keeps its exact
        # compiled programs
        if repetition_penalty != 1.0 and not getattr(
            self.backend, "supports_presence", False
        ):
            raise ValueError(
                f"backend {self.backend.name!r} does not support "
                f"repetition_penalty; serve penalized requests on the "
                f"single-device or pipeline backend"
            )
        oai_pen = frequency_penalty != 0.0 or presence_penalty != 0.0
        if oai_pen and not getattr(self.backend, "supports_counts", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support "
                f"frequency_penalty/presence_penalty; serve penalized "
                f"requests on the single-device or pipeline backend"
            )
        presence = (
            self._presence_rows([ids]) if repetition_penalty != 1.0 else None
        )
        key = jax.random.PRNGKey(seed) if seed is not None else self._next_key()
        key_pre, key_dec = jax.random.split(key)

        cache = self._cache
        self._cache = None  # donated below; restored from the decode result
        first, logits, cache = self._ingest_with_prefix(
            self._prefix, ids, p0, entry, plan, cache, key_pre, sampling,
            presence=presence, bias=bias,
        )
        first = jax.block_until_ready(first)
        ttft = time.time() - t_start
        if trace is not None:
            trace.checkpoint("prefill")

        if use_draft:
            dcfg, dparams = self._draft
            dcache = self._draft_cache
            self._draft_cache = None
            if dcache is None:
                dcache = M.init_kv_cache(dcfg, 1, max_seq=cfg.max_seq_len)
            dcache = self._draft_ingest(ids, dcache)
            out, n_gen, cache, dcache = self.backend.decode_draft_speculative(
                dcfg, dparams, first, cache, dcache, jnp.int32(prompt_len),
                jnp.int32(max_tokens - 1), max_steps=decode_bucket,
                draft_len=SPEC_DRAFT_LEN,
            )
            self._draft_cache = dcache
        elif use_spec:
            # H is static per model so the program compiles once
            H = cfg.max_seq_len + SPEC_DRAFT_LEN + 2
            hist = jnp.zeros((1, H), jnp.int32)
            hist = jax.lax.dynamic_update_slice(
                hist, jnp.asarray([ids], jnp.int32), (jnp.int32(0), jnp.int32(0))
            )
            out, n_gen, cache = self.backend.decode_speculative(
                first, cache, hist, jnp.int32(prompt_len),
                jnp.int32(max_tokens - 1), max_steps=decode_bucket,
                draft_len=SPEC_DRAFT_LEN,
            )
        else:
            if presence is not None:
                presence = G.presence_update(presence, first.reshape(1))
            step_lps = None
            dkw = {"presence": presence}
            if oai_pen:
                # OpenAI-penalty state: GENERATED counts only, seeded with
                # the (generated) first token — prompt tokens excluded
                dkw["counts"] = G.count_update(
                    jnp.zeros((1, cfg.vocab_size), jnp.int32),
                    first.reshape(1),
                )
            if bias is not None:  # backends without the kwarg stay untouched
                dkw["bias"] = bias
            if cart is not None:
                # FSM state after the (bias-masked) first token, computed
                # host-side off the already-fetched first id — the decode
                # loop then advances it on device, zero host syncs/token
                fsm0 = cart.advance(cart.start, int(first[0]))
                cm, ct = cart.device_tables()
                dkw["constraint"] = (
                    jnp.asarray([fsm0], jnp.int32), cm, ct
                )
            if stop:
                # textual stops: decode in bounded chunks and quit at the
                # first match instead of burning the full budget on device
                out, n_gen, step_lps, cache = self._decode_textual_stop_chunks(
                    first, cache, prompt_len, max_tokens, key_dec, sampling,
                    dkw, logprobs, stop, cart=cart,
                )
            elif logprobs:
                out, n_gen, cache, step_lps = self.backend.decode(
                    first, cache, jnp.int32(prompt_len),
                    jnp.int32(max_tokens - 1), key_dec, sampling,
                    max_steps=decode_bucket, with_logprobs=True, **dkw,
                )
            else:
                out, n_gen, cache = self.backend.decode(
                    first, cache, jnp.int32(prompt_len),
                    jnp.int32(max_tokens - 1), key_dec, sampling,
                    max_steps=decode_bucket, **dkw,
                )
        out = jax.block_until_ready(out)
        self._cache = cache
        if trace is not None:
            trace.checkpoint("decode")

        gen_ids = self._row_tokens(int(first[0]), out[0], int(n_gen[0]))
        response = self.tokenizer.decode(gen_ids, skip_special_tokens=True)
        response, stopped = self._truncate_at_stop(response, stop)
        if trace is not None:
            trace.checkpoint("detokenize")

        token_logprobs = None
        token_strings = None
        if logprobs:
            # first token: log_softmax of the prefill logits (raw model
            # distribution, OpenAI convention); decode steps recorded by
            # the with_logprobs decode variant. Covers every GENERATED
            # token (textual stop truncation cuts text, not this list).
            import numpy as np

            token_logprobs = []
            if int(first[0]) not in self.cfg.all_stop_ids:
                lp0 = jax.nn.log_softmax(logits[0].astype(jnp.float32))
                token_logprobs.append(round(float(lp0[int(first[0])]), 6))
            if step_lps is not None:
                token_logprobs += [
                    round(float(x), 6)
                    for x in np.asarray(step_lps[0][: int(n_gen[0])])
                ]
            # per-position token text alongside the logprobs (OpenAI's
            # logprobs objects carry both); zip-truncated defensively —
            # gen_ids excludes a terminal EOS exactly when its logprob
            # entry was skipped above
            token_strings = [
                self.tokenizer.decode([t])
                for t, _ in zip(gen_ids, token_logprobs)
            ]

        top_predictions = None
        if debug and logits.shape[-1] > 0:  # 1F1B may return 0-width logits
            from ..ops.sampling import top_n_probs

            probs, tids = top_n_probs(logits, 5)
            top_predictions = [
                {
                    "token": self.tokenizer.decode([int(t)]),
                    "id": int(t),
                    "prob": round(float(p), 5),
                }
                for p, t in zip(probs[0], tids[0])
            ]

        elapsed = time.time() - t_start
        n = len(gen_ids)
        tps = n / elapsed if elapsed > 0 else 0.0
        self._record_sample(ttft, tps, n, elapsed=elapsed)
        log.info(
            "request", model=cfg.name, backend=self.backend.name,
            prompt_len=prompt_len, bucket=bucket, tokens=n,
            ttft_s=round(ttft, 4), tokens_per_sec=round(tps, 2),
            elapsed_s=round(elapsed, 3),
        )
        result = {
            "prompt": prompt,
            "response": response,
            "status": "success",
            "time_taken": f"{elapsed:.2f}s",
            "tokens_generated": n,
            "prompt_tokens": prompt_len,
            "tokens_per_sec": f"{tps:.2f}",
            "ttft_s": round(ttft, 4),
            "backend": self.backend.name,
            # why generation ended, judged against the CLAMPED budget (the
            # requested max_tokens may have been lowered near max_seq_len —
            # the serving edge cannot reconstruct that)
            "finish_reason": (
                "stop" if stopped or n < max_tokens else "length"
            ),
        }
        if p0:
            result["prefix_cached_tokens"] = p0
        if stopped:
            result["stopped"] = True  # a textual stop sequence fired
        if token_logprobs is not None:
            result["token_logprobs"] = token_logprobs
            result["token_strings"] = token_strings
        if use_spec or use_draft:
            result["speculative"] = True
            # which path served (the continuous mixed fleet reports
            # "fleet" with spec_drafted/spec_accepted counts; the solo
            # loops keep acceptance entirely on device and report counts
            # only through tokens_generated)
            result["spec_path"] = "solo"
        if cart is not None:
            result["constrained"] = True
        if use_draft:
            result["draft_model"] = self._draft[0].name
        if top_predictions is not None:
            result["top_predictions"] = top_predictions
        return result

    # -- warmup --------------------------------------------------------------
    def warmup(self, decode_buckets=None, batch_buckets=None) -> dict:
        """Pre-compile every serving program so no request pays jit latency.

        BASELINE.json's target is p50 TTFT — that requires warm-compiled
        caches for every (prefill bucket, decode bucket) shape, not
        compile-on-first-request (SURVEY.md §7 'TTFT < 500 ms' note).
        Covers:
          * one single-stream prefill program per prefill bucket (shared
            with the chunked-prefill final chunk — `pos` is traced);
          * the extend() chunk program when the backend supports chunking
            (single-device AND the SPMD pipeline);
          * one single-stream decode program per decode bucket;
          * the batched/ragged programs — (batch bucket x prefill bucket)
            prefills with a valid_start operand and (batch bucket x decode
            bucket) decodes — when the backend supports ragged batches
            (round-1 gap: the first batched request on a warm server still
            paid a full compile).
        Sampling params are traced scalars, so one program covers every
        temperature/top-k/top-p/greedy combination.

        batch_buckets: None = auto (all of BATCH_BUCKETS when the
        model/backend can serve batches, else none); pass () to skip
        batched warming or a tuple to warm specific batch sizes.

        Returns {"programs": N, "seconds": wall}.
        """
        t0 = time.time()
        decode_buckets = tuple(decode_buckets or DECODE_BUCKETS)
        gran = getattr(self.backend, "batch_granularity", 1)
        if batch_buckets is None:
            can_batch = (
                self.cfg.arch == "llama"
                and getattr(self.backend, "supports_ragged", False)
            )
            # the SAME ladder the request path picks from — fleet-granular
            # backends (gran > 1, always llama: create_backend rejects the
            # rest) warm (g, 2g, ...) instead of the power-of-two buckets
            batch_buckets = batch_buckets_for(gran) if can_batch else ()
        sampling = G.default_sampling(greedy=True)
        key = jax.random.PRNGKey(0)
        n = 0
        buckets = self._buckets()
        if not buckets:
            # an empty bucket layout would leave `first` unset below and
            # crash the decode warm loop with an opaque TypeError
            raise ValueError(
                f"warmup needs at least one prefill bucket <= max_seq_len "
                f"{self.cfg.max_seq_len}; got prefill_buckets="
                f"{self.engine_cfg.prefill_buckets}"
            )
        pad = self.cfg.pad_token_id
        with self._lock:
            # single-stream programs: EVERY backend serves solo requests
            # batch-1 (fleet-granular backends dispatch solo rows to their
            # inherited plain-ring programs), so warm them everywhere
            cache = self._cache or self.backend.init_cache(1, self.cfg.max_seq_len)
            self._cache = None
            first = None
            for bucket in buckets:
                tokens = jnp.full((1, bucket), pad, jnp.int32)
                first, _, cache = self.backend.prefill(
                    tokens, jnp.int32(1), cache, key, sampling
                )
                n += 1
            if hasattr(self.backend, "extend"):
                chunk_tokens = jnp.full((1, buckets[-1]), pad, jnp.int32)
                cache = self.backend.extend(chunk_tokens, jnp.int32(0), cache)
                n += 1
            for db in decode_buckets:
                # limit=0: compiles the while_loop program, executes 0 steps
                _, _, cache = self.backend.decode(
                    first, cache, jnp.int32(1), jnp.int32(0), key, sampling,
                    max_steps=db,
                )
                n += 1
            if getattr(self.backend, "supports_presence", False):
                # repetition-penalty (presence) program variants — 'no
                # request pays jit latency' covers penalized requests too.
                # Single-stream only: batched penalized programs compile on
                # first use (rarer path; the grid would double warmup).
                pres1 = jnp.zeros((1, self.cfg.vocab_size), bool)
                for bucket in buckets:
                    tokens = jnp.full((1, bucket), pad, jnp.int32)
                    first, _, cache = self.backend.prefill(
                        tokens, jnp.int32(1), cache, key, sampling,
                        presence=pres1,
                    )
                    n += 1
                for db in decode_buckets:
                    _, _, cache = self.backend.decode(
                        first, cache, jnp.int32(1), jnp.int32(0), key,
                        sampling, presence=pres1, max_steps=db,
                    )
                    n += 1
            if getattr(self.backend, "supports_logprobs", False):
                # the with_logprobs decode variant compiles separately
                # (static flag adds a logprob buffer to the loop carry)
                for db in decode_buckets:
                    _, _, cache, _ = self.backend.decode(
                        first, cache, jnp.int32(1), jnp.int32(0), key,
                        sampling, max_steps=db, with_logprobs=True,
                    )
                    n += 1
            if self._draft is not None and getattr(
                self.backend, "supports_draft", False
            ):
                # speculative requests route to the DRAFT path when a
                # draft is attached — warm ITS programs (ingest per
                # bucket + the chunked-extend variant + the combined
                # verify loop per decode bucket); the prompt-lookup
                # program would be dead weight
                dcfg, dparams = self._draft
                dcache = self._draft_cache
                self._draft_cache = None
                if dcache is None:
                    dcache = M.init_kv_cache(
                        dcfg, 1, max_seq=self.cfg.max_seq_len
                    )
                for bucket in buckets:
                    dcache = self._draft_ingest([pad] * bucket, dcache)
                    n += 1
                chunked_len = buckets[-1] + 1
                if self._plan_ingest(chunked_len, 0, buckets) is not None:
                    dcache = self._draft_ingest([pad] * chunked_len, dcache)
                    n += 1
                for db in decode_buckets:
                    _, _, cache, dcache = self.backend.decode_draft_speculative(
                        dcfg, dparams, first, cache, dcache, jnp.int32(1),
                        jnp.int32(0), max_steps=db,
                        draft_len=SPEC_DRAFT_LEN,
                    )
                    n += 1
                self._draft_cache = dcache
            elif getattr(self.backend, "supports_speculative", False):
                # speculative programs too — 'no request pays jit latency'
                # includes speculative=true requests
                H = self.cfg.max_seq_len + SPEC_DRAFT_LEN + 2
                hist = jnp.zeros((1, H), jnp.int32)
                for db in decode_buckets:
                    _, _, cache = self.backend.decode_speculative(
                        first, cache, hist, jnp.int32(1), jnp.int32(0),
                        max_steps=db, draft_len=SPEC_DRAFT_LEN,
                    )
                    n += 1
            # jaxlint: disable=blocking-under-lock -- warmup compiles under the engine lock on purpose: no request may interleave half-warmed programs
            jax.block_until_ready(cache)
            self._cache = cache  # first real request reuses the buffer

            # batched/ragged programs. Only the LARGEST warmed bucket's
            # cache is retained afterwards: keeping one per bucket would
            # pin sum(BATCH_BUCKETS) x max_seq of KV in HBM (multi-GB for
            # an 8B-class model) whether or not batched traffic ever
            # arrives — the compile warmth is what matters; reallocating a
            # zeroed cache is cheap next to a compile.
            for Bb in batch_buckets:
                bcache = self._batch_caches.pop(Bb, None)
                if bcache is None:
                    bcache = self.backend.init_cache(Bb, self.cfg.max_seq_len)
                valid_start = jnp.zeros((Bb,), jnp.int32)
                bfirst = None
                for bucket in buckets:
                    tokens = jnp.full((Bb, bucket), pad, jnp.int32)
                    bfirst, _, bcache = self.backend.prefill(
                        tokens, jnp.int32(bucket), bcache, key, sampling,
                        valid_start,
                    )
                    n += 1
                for db in decode_buckets:
                    _, _, bcache = self.backend.decode(
                        bfirst, bcache, jnp.int32(buckets[-1]), jnp.int32(0),
                        key, sampling, valid_start, max_steps=db,
                    )
                    n += 1
                # jaxlint: disable=blocking-under-lock -- warmup compiles under the engine lock on purpose: no request may interleave half-warmed programs
                jax.block_until_ready(bcache)
                self._batch_caches[Bb] = bcache
            for Bb in sorted(batch_buckets)[:-1]:
                self._batch_caches.pop(Bb, None)
        out = {"programs": n, "seconds": round(time.time() - t0, 2)}
        log.info("warmup", **out)
        return out

    # -- batched entry -------------------------------------------------------
    def generate_batch(
        self,
        prompts: list,
        max_tokens: int = 20,
        temperature: float = 0.7,
        top_k: int = 50,
        top_p: float = 0.9,
        greedy: bool = False,
        chat: bool = True,
        seed: Optional[int] = None,
        min_p: float = 0.0,
        repetition_penalty: float = 1.0,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        stop: Optional[list] = None,
        constraint: Optional[dict] = None,
        request_id: Optional[str] = None,
        slo_class: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        _trace: Optional[Trace] = None,
    ) -> dict:
        """One forward fleet for N prompts (shared sampling params).

        Ragged prompts are LEFT-padded to a shared bucket: every row then
        shares one position frame (prefill length == bucket, decode starts
        at bucket), and per-row pad slots are masked via valid_start. RoPE
        is relative, so the uniform per-row shift is harmless — which is
        also why this is llama-family only (GPT-2's learned absolute
        positions are not shift-invariant). The reference can't batch at
        all: one request at a time, batch dim hardcoded to 1
        (/root/reference/orchestration.py:98,144).
        """
        t_start = time.time()
        trace = _trace if _trace is not None else Trace(request_id)

        def locked():
            with self._lock:
                trace.checkpoint("queue_wait")
                # jaxlint: disable=blocking-under-lock -- the engine lock IS the device-serialization point; a generation holds it end to end by design
                return self._generate_batch_locked(
                    prompts, max_tokens, temperature, top_k, top_p, greedy,
                    chat, seed, t_start, min_p, repetition_penalty, stop,
                    frequency_penalty, presence_penalty, constraint, trace,
                )

        with request_id_context(trace.request_id):
            dl_s, dl_type = self._resolve_deadline(deadline_ms)
            if dl_s is not None and dl_s <= 0:
                self._m_deadline_exceeded.inc()
                return self._finish_request(
                    {
                        "error": "Error: request exceeded its deadline_ms "
                        "budget before generation",
                        "status": "failed",
                        "error_type": "deadline_exceeded",
                    },
                    trace, engine="batch",
                )
            try:
                result = self._with_deadline(
                    locked, "generate_batch", deadline_s=dl_s,
                    exceeded_type=dl_type,
                )
                if result.get("error_type") == "deadline_exceeded":
                    self._m_deadline_exceeded.inc()
            except ValueError as e:
                log.warning("invalid_batch_request", error=str(e))
                result = {"error": f"Error: {e}", "status": "failed",
                          "error_type": "invalid_request"}
            except Exception as e:
                log.error("generate_batch_failed", exc_info=True, error=str(e))
                result = {"error": f"Error: {e}", "status": "failed"}
            return self._finish_request(result, trace, engine="batch")

    # guarded-by: _lock
    def _generate_batch_locked(
        self, prompts, max_tokens, temperature, top_k, top_p, greedy, chat,
        seed, t_start, min_p=0.0, repetition_penalty=1.0, stop=None,
        frequency_penalty=0.0, presence_penalty=0.0, constraint=None,
        trace=None,
    ):
        cfg = self.cfg
        if not prompts or not all(isinstance(p, str) and p for p in prompts):
            raise ValueError("prompts must be a non-empty list of non-empty strings")
        if cfg.arch != "llama":
            raise ValueError(
                f"batched generation is llama-family only (left-padding needs "
                f"relative positions); model arch is {cfg.arch!r}"
            )
        if not getattr(self.backend, "supports_ragged", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support ragged "
                f"batches; serve batches on a ragged-capable backend"
            )
        self.request_count += 1
        B = len(prompts)
        if B > BATCH_BUCKETS[-1]:
            raise ValueError(
                f"batch size {B} exceeds the maximum {BATCH_BUCKETS[-1]}; "
                f"split the request"
            )
        texts = [self.render_chat(p) if chat else p for p in prompts]
        ids = [self.tokenizer.encode(t) for t in texts]
        plens = [len(i) for i in ids]
        bucket, max_tokens, decode_bucket = self._plan(max(plens), max_tokens)

        # pad the batch up to a bucketed size so XLA compiles one program
        # per (B-bucket, prefill-bucket, decode-bucket) triple, not per
        # client batch size; dummy rows are single-pad prompts, sliced off
        # the results below. Fleet-granular backends (1F1B: rows % dp*M
        # == 0) use the granularity ladder — the same one warmup compiles.
        gran = getattr(self.backend, "batch_granularity", 1)
        Bb = G.pick_bucket(batch_buckets_for(gran), B)
        pad = cfg.pad_token_id
        rows = ids + [[pad]] * (Bb - B)
        row_lens = plens + [1] * (Bb - B)
        tokens = jnp.asarray(
            [[pad] * (bucket - n) + row for row, n in zip(rows, row_lens)],
            jnp.int32,
        )
        valid_start = jnp.asarray([bucket - n for n in row_lens], jnp.int32)
        sampling = G.default_sampling(
            temperature, top_k, top_p, greedy, min_p, repetition_penalty,
            frequency_penalty, presence_penalty,
        )
        if repetition_penalty != 1.0 and not getattr(
            self.backend, "supports_presence", False
        ):
            raise ValueError(
                f"backend {self.backend.name!r} does not support "
                f"repetition_penalty; serve penalized requests on the "
                f"single-device or pipeline backend"
            )
        oai_pen = frequency_penalty != 0.0 or presence_penalty != 0.0
        if oai_pen and not getattr(self.backend, "supports_counts", False):
            raise ValueError(
                f"backend {self.backend.name!r} does not support "
                f"frequency_penalty/presence_penalty; serve penalized "
                f"requests on the single-device or pipeline backend"
            )
        presence = (
            self._presence_rows(rows) if repetition_penalty != 1.0 else None
        )
        # shared grammar constraint: all rows decode under the SAME tables
        # (one [S, V] pair broadcast), each row walking its own FSM state
        cart = self._compile_constraint(constraint) if constraint else None
        key = jax.random.PRNGKey(seed) if seed is not None else self._next_key()
        key_pre, key_dec = jax.random.split(key)

        # reusable batch-bucket cache (donated below, restored after decode);
        # stale rows are invisible behind the ragged causal mask
        cache = self._batch_caches.pop(Bb, None)
        if cache is None:
            cache = self.backend.init_cache(Bb, cfg.max_seq_len)
        pkw = {"presence": presence}
        if cart is not None:
            # first-token mask rides the bias operand ([V] broadcasts
            # row-wise), exactly like the solo path
            pkw["bias"] = self._constraint_bias(cart, None)
        if cart is not None and trace is not None:
            trace.checkpoint("constraint_compile")
        first, logits, cache = self.backend.prefill(
            tokens, jnp.int32(bucket), cache, key_pre, sampling, valid_start,
            **pkw,
        )
        first = jax.block_until_ready(first)
        ttft = time.time() - t_start
        if trace is not None:
            trace.checkpoint("prefill")

        # dummy padding rows start "finished" (first token forced to EOS),
        # so the decode loop's all-finished early exit still fires when the
        # real rows are done
        if Bb > B:
            first = first.at[B:].set(cfg.eos_token_id)
        if presence is not None:
            presence = G.presence_update(presence, first)
        counts = None
        if oai_pen:
            # generated-count rows seeded with each row's first token
            # (dummy pad rows got EOS firsts above — they never emit)
            counts = G.count_update(
                jnp.zeros((Bb, cfg.vocab_size), jnp.int32), first
            )
        bkw = {}
        if cart is not None:
            # per-row FSM states after each row's first token (host numpy
            # walk off the already-fetched firsts; dummy pad rows got EOS
            # firsts above — they start finished, their state is inert)
            firsts = np.asarray(first)
            fsm0 = np.asarray(
                [cart.advance(cart.start, int(t)) for t in firsts], np.int32
            )
            cm, ct = cart.device_tables()
            bkw["constraint"] = (jnp.asarray(fsm0), cm, ct)
        out, n_gen, cache = self.backend.decode(
            first, cache, jnp.int32(bucket), jnp.int32(max_tokens - 1),
            key_dec, sampling, valid_start, presence, counts,
            max_steps=decode_bucket, **bkw,
        )
        out = jax.block_until_ready(out)
        if trace is not None:
            trace.checkpoint("decode")
        # keep at most ONE batch cache (the bucket just used): an entry per
        # bucket would re-pin sum(BATCH_BUCKETS) x max_seq of KV in HBM —
        # the footprint warmup's keep-only-largest eviction exists to avoid
        self._batch_caches.clear()
        self._batch_caches[Bb] = cache

        results = []
        total_tokens = 0
        for b in range(B):  # dummy pad rows [B, Bb) sliced off here
            row = self._row_tokens(int(first[b]), out[b], int(n_gen[b]))
            total_tokens += len(row)
            text = self.tokenizer.decode(row, skip_special_tokens=True)
            text, row_stopped = self._truncate_at_stop(text, stop)
            entry = {
                "prompt": prompts[b],
                "response": text,
                "tokens_generated": len(row),
                "prompt_tokens": plens[b],
                "status": "success",
                "finish_reason": (
                    "stop" if row_stopped or len(row) < max_tokens
                    else "length"
                ),
            }
            if row_stopped:
                entry["stopped"] = True
            results.append(entry)
        if trace is not None:
            trace.checkpoint("detokenize")
        elapsed = time.time() - t_start
        tps = total_tokens / elapsed if elapsed > 0 else 0.0
        self._record_sample(ttft, tps / B, total_tokens, elapsed=elapsed,
                            engine="batch")
        self._m_batch_size.labels(engine="batch").observe(B)
        log.info(
            "batch_request", model=cfg.name, backend=self.backend.name,
            batch=B, batch_bucket=Bb, bucket=bucket, tokens=total_tokens,
            ttft_s=round(ttft, 4), aggregate_tokens_per_sec=round(tps, 2),
            elapsed_s=round(elapsed, 3),
        )
        result = {
            "results": results,
            "status": "success",
            "batch_size": B,
            "time_taken": f"{elapsed:.2f}s",
            "tokens_generated": total_tokens,
            "tokens_per_sec": f"{tps:.2f}",
            "ttft_s": round(ttft, 4),
            "backend": self.backend.name,
        }
        if cart is not None:
            result["constrained"] = True
        return result

    # -- perf stats ----------------------------------------------------------
    def stats(self) -> dict:
        """Rolling p50/p90/p99 over recent requests (TTFT seconds,
        tokens/sec) plus the lifetime sample count.

        Snapshot under the samples lock: /stats and /health are served from
        other threads while a generate() may be appending to the deque.
        The percentile formula is utils.metrics.percentile — the SAME one
        the registry histograms use for their window percentiles, and both
        are fed by the one _record_sample seam, so this JSON view and the
        /metrics view agree by construction.
        """
        from ..utils.metrics import percentile as pct

        with self._samples_lock:
            samples = list(self._samples)
            samples_total = self._samples_total

        ttfts = [s["ttft_s"] for s in samples]
        tpss = [s["tokens_per_sec"] for s in samples]
        out = {
            "window": len(samples),
            "samples_total": samples_total,
            "ttft_p50_s": pct(ttfts, 0.5),
            "ttft_p90_s": pct(ttfts, 0.9),
            "ttft_p99_s": pct(ttfts, 0.99),
            "tokens_per_sec_p50": pct(tpss, 0.5),
            "tokens_per_sec_p90": pct(tpss, 0.9),
            "tokens_per_sec_p99": pct(tpss, 0.99),
            "tokens_total": sum(s["tokens"] for s in samples),
        }
        if self._prefix is not None:
            out["prefix_cache"] = self._prefix.stats()
        # exemplars: the metrics -> traces pivot (ISSUE 17). Each latency
        # bucket names the most recent traced request that landed in it,
        # so a p99 outlier in this JSON view links straight to one
        # assembled trace at GET /debug/traces/{trace_id}.
        snap = self.metrics.snapshot()
        exemplars: dict = {}
        for fam in ("dli_ttft_seconds", "dli_tpot_seconds",
                    "dli_request_duration_seconds"):
            for series in snap.get(fam, {}).get("series", []):
                if series.get("exemplars"):
                    exemplars.setdefault(fam, {}).update(
                        series["exemplars"]
                    )
        if exemplars:
            out["exemplars"] = exemplars
        return out

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Wait for any in-flight generation to finish (the engine lock is
        held for a whole request). The solo engine has no queue of its own
        — the serving drain path rejects NEW work at the HTTP edge first,
        so once the lock frees the engine is idle. Returns False when the
        deadline expired with a request still running."""
        t0 = time.time()
        while self._lock.locked():
            if deadline_s is not None and time.time() - t0 > deadline_s:
                return False
            time.sleep(0.05)
        return True

    # -- health (reference /health + /workers, orchestration.py:297-329) ----
    def health(self) -> dict:
        out = {
            "status": "healthy",
            "model": self.cfg.name,
            "backend": self.backend.name,
            "n_stages": getattr(self.backend, "n_stages", 1),
            "requests_served": self.request_count,
            "stats": self.stats(),
        }
        wedged = self.wedged_info()
        if wedged:
            # an abandoned device call is still holding the backend: new
            # requests will burn their deadline and 503 until it drains —
            # tell the monitor the truth (and how long it has been stuck)
            out["status"] = "degraded"
            out["wedged"] = wedged
        return out

    def workers(self) -> dict:
        stages = self.backend.health()
        if self._lock.locked():
            # a generation holds the device(s): a timed-out probe means
            # "queued behind real work", not unreachable — report busy so
            # monitoring doesn't flap to offline exactly when loaded
            for s in stages:
                if s.get("status") == "offline":
                    s["status"] = "busy"
                    s["error"] = "probe queued behind an in-flight generation"
        return {
            "workers": {f"stage_{s['stage']}": s for s in stages},
            "total": len(stages),
        }
