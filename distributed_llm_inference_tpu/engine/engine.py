"""InferenceEngine: the request-level decode engine (reference L3).

Replaces `Orchestrator.generate_with_sampling`
(/root/reference/orchestration.py:69-228): tokenize → chat-template →
prefill (TTFT) → decode loop → detokenize → perf stats, with the same
response schema (`prompt`, `response`, `status`, `time_taken`,
`tokens_generated`, `tokens_per_sec` — orchestration.py:211-218) plus
first-class `ttft_s` (BASELINE.json's p50-TTFT metric is a measurement, not
a print).

Single-owner by construction: one lock serializes generations — the
reference's shared-global Flask state would interleave worker calls across
concurrent requests with no locking (SURVEY.md §5 race note).

The compute backend is pluggable: `SingleDeviceBackend` (this file) runs
the whole model on one chip; `parallel.pipeline.PipelineBackend` runs
N stages over a mesh with the same (prefill, decode) interface.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..config import EngineConfig, ModelConfig
from ..models import api as M
from ..utils.tokenizer import load_tokenizer
from . import generate as G
from .chat import format_chat_prompt

DECODE_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)


class SingleDeviceBackend:
    """Whole model on one device: prefill + while-loop decode, both jitted."""

    name = "single-device"
    n_stages = 1

    def __init__(self, cfg: ModelConfig, params):
        self.cfg = cfg
        self.params = params

    def init_cache(self, batch: int, max_seq: int):
        return M.init_kv_cache(self.cfg, batch, max_seq=max_seq)

    def prefill(self, tokens, prompt_len, cache, key, sampling):
        return G.prefill(self.cfg, self.params, tokens, prompt_len, cache, key, sampling)

    def decode(self, first_token, cache, start_pos, limit, key, sampling, *, max_steps):
        return G.decode(
            self.cfg, self.params, first_token, cache, start_pos, limit, key,
            sampling, max_steps=max_steps,
        )

    def health(self) -> list[dict]:
        """Per-device health (reference /workers sweep, orchestration.py:306-329)."""
        devs = jax.devices()
        return [
            {"stage": 0, "devices": [str(d) for d in devs[:1]], "status": "online"}
        ]


class InferenceEngine:
    def __init__(
        self,
        cfg: ModelConfig,
        params: Any = None,
        backend: Any = None,
        tokenizer: Any = None,
        engine_cfg: EngineConfig = EngineConfig(),
        seed: int = 0,
    ):
        if backend is None:
            if params is None:
                params = M.init_params(cfg, jax.random.PRNGKey(seed))
            backend = SingleDeviceBackend(cfg, params)
        self.cfg = cfg
        self.backend = backend
        self.engine_cfg = engine_cfg
        self.tokenizer = tokenizer or load_tokenizer(
            None, pad_id=cfg.pad_token_id, bos_id=cfg.bos_token_id, eos_id=cfg.eos_token_id
        )
        self._lock = threading.Lock()
        self._key = jax.random.PRNGKey(seed)
        self.request_count = 0
        # Rolling per-request perf samples for p50/p90 TTFT + throughput
        # (BASELINE.json's metric is p50 TTFT — a measurement, not a print).
        # Own lock, NOT self._lock: that one is held for a whole generation,
        # and /health must not block behind a multi-second decode.
        self._samples = collections.deque(maxlen=256)
        self._samples_lock = threading.Lock()
        # Reusable KV cache buffer: allocated once, donated to prefill/decode
        # each request and replaced by the returned buffer. Stale contents
        # between requests are harmless — prefill rewrites slots [0, bucket)
        # and the causal mask hides every slot beyond the current position.
        self._cache = None

    # -- helpers ------------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _buckets(self):
        return tuple(b for b in self.engine_cfg.prefill_buckets if b <= self.cfg.max_seq_len)

    # -- main entry ----------------------------------------------------------
    def generate(
        self,
        prompt: str,
        max_tokens: int = 20,
        temperature: float = 0.7,
        top_k: int = 50,
        top_p: float = 0.9,
        greedy: bool = False,
        chat: bool = True,
        seed: Optional[int] = None,
    ) -> dict:
        """Full generation; returns the reference-schema response dict."""
        t_start = time.time()
        try:
            with self._lock:
                return self._generate_locked(
                    prompt, max_tokens, temperature, top_k, top_p, greedy, chat,
                    seed, t_start,
                )
        except ValueError as e:
            # caller-caused (e.g. prompt longer than the largest prefill
            # bucket): tagged so the serving edge can answer 400, not 500
            return {"error": f"Error: {e}", "status": "failed",
                    "error_type": "invalid_request"}
        except Exception as e:  # error envelope (orchestration.py:220-228)
            return {"error": f"Error: {e}", "status": "failed"}

    def _generate_locked(
        self, prompt, max_tokens, temperature, top_k, top_p, greedy, chat, seed, t_start
    ):
        cfg = self.cfg
        self.request_count += 1
        text = format_chat_prompt(prompt, arch=cfg.arch) if chat else prompt
        ids = self.tokenizer.encode(text)
        prompt_len = len(ids)

        buckets = self._buckets()
        if not buckets or prompt_len > buckets[-1]:
            raise ValueError(
                f"prompt length {prompt_len} exceeds max prefill bucket "
                f"{buckets[-1] if buckets else 0}"
            )
        bucket = G.pick_bucket(buckets, prompt_len)

        # cache capacity bound: prompt + generated must fit max_seq
        # (update_kv_cache clamps silently out of range — never allow it);
        # also bounded by the largest compiled decode bucket
        max_tokens = max(
            1,
            min(int(max_tokens), cfg.max_seq_len - prompt_len - 1, DECODE_BUCKETS[-1]),
        )
        decode_bucket = G.pick_bucket(DECODE_BUCKETS, max_tokens)

        pad = cfg.pad_token_id
        tokens = jnp.asarray([ids + [pad] * (bucket - prompt_len)], jnp.int32)
        sampling = G.default_sampling(temperature, top_k, top_p, greedy)
        key = jax.random.PRNGKey(seed) if seed is not None else self._next_key()
        key_pre, key_dec = jax.random.split(key)

        if self._cache is None:
            self._cache = self.backend.init_cache(1, cfg.max_seq_len)
        cache = self._cache
        self._cache = None  # donated below; restored from the decode result
        first, logits, cache = self.backend.prefill(
            tokens, jnp.int32(prompt_len), cache, key_pre, sampling
        )
        first = jax.block_until_ready(first)
        ttft = time.time() - t_start

        out, n_gen, cache = self.backend.decode(
            first, cache, jnp.int32(prompt_len), jnp.int32(max_tokens - 1),
            key_dec, sampling, max_steps=decode_bucket,
        )
        out = jax.block_until_ready(out)
        self._cache = cache

        first_id = int(first[0])
        first_ok = first_id != cfg.eos_token_id
        gen_ids = ([first_id] if first_ok else []) + [
            int(t) for t in list(out[0][: int(n_gen[0])])
        ]
        response = self.tokenizer.decode(gen_ids, skip_special_tokens=True)

        elapsed = time.time() - t_start
        n = len(gen_ids)
        tps = n / elapsed if elapsed > 0 else 0.0
        with self._samples_lock:
            self._samples.append({"ttft_s": ttft, "tokens_per_sec": tps, "tokens": n})
        return {
            "prompt": prompt,
            "response": response,
            "status": "success",
            "time_taken": f"{elapsed:.2f}s",
            "tokens_generated": n,
            "tokens_per_sec": f"{tps:.2f}",
            "ttft_s": round(ttft, 4),
            "backend": self.backend.name,
        }

    # -- perf stats ----------------------------------------------------------
    def stats(self) -> dict:
        """Rolling p50/p90 over recent requests (TTFT seconds, tokens/sec).

        Snapshot under the samples lock: /stats and /health are served from
        other threads while a generate() may be appending to the deque.
        """
        with self._samples_lock:
            samples = list(self._samples)

        def pct(vals, q):
            if not vals:
                return None
            vals = sorted(vals)
            idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
            return round(vals[idx], 4)

        ttfts = [s["ttft_s"] for s in samples]
        tpss = [s["tokens_per_sec"] for s in samples]
        return {
            "window": len(samples),
            "ttft_p50_s": pct(ttfts, 0.5),
            "ttft_p90_s": pct(ttfts, 0.9),
            "tokens_per_sec_p50": pct(tpss, 0.5),
            "tokens_per_sec_p90": pct(tpss, 0.9),
            "tokens_total": sum(s["tokens"] for s in samples),
        }

    # -- health (reference /health + /workers, orchestration.py:297-329) ----
    def health(self) -> dict:
        return {
            "status": "healthy",
            "model": self.cfg.name,
            "backend": self.backend.name,
            "n_stages": getattr(self.backend, "n_stages", 1),
            "requests_served": self.request_count,
            "stats": self.stats(),
        }

    def workers(self) -> dict:
        stages = self.backend.health()
        return {
            "workers": {f"stage_{s['stage']}": s for s in stages},
            "total": len(stages),
        }
