"""SLO-aware chunked-prefill scheduling for the continuous paged fleet.

The admit-then-prefill-whole flow (engine/continuous.py's original
admission) prefills a request's entire prompt before any decoding slot
advances again: one long prompt stalls every in-flight request's TPOT for
the full prefill duration — the classic Sarathi/Orca observation, and the
ROADMAP's top open item. The ragged kernel (ops/paged_attention) already
serves mixed prefill+decode rows in one launch; what stopped at
per-admission prefill entries was the HOST-side planning. This module is
that planning:

  * TOKEN-BUDGET STEPS: every scheduler step assembles ONE mixed ragged
    launch (engine/paged.mixed_step_ragged) containing a decode row for
    every active slot plus PREFILL chunks of pending admissions, sliced
    to `engine_cfg.step_token_budget` flat tokens. Decode rows are
    reserved FIRST (prefill can never starve decode — the TPOT
    guarantee); the remaining query tiles are the per-step prefill
    budget. A prompt of any length therefore costs each decode step at
    most `budget - n_slots` extra flat tokens instead of a whole-prompt
    stall, and TTFT degrades gracefully (the prompt lands over several
    steps) instead of TPOT collapsing.
  * SLO CLASSES: requests carry an `slo_class` (serving/queue.py field,
    surfaced on /generate and the OpenAI routes) with per-class TTFT /
    TPOT targets from config (engine_cfg.slo_classes). The prefill
    budget is apportioned across classes by weight x URGENCY, where
    urgency is the class's oldest pending prefill's wait measured
    against its TTFT target — the feedback signal the observability
    layer's timing histograms established (the same samples feed the
    per-class EWMAs here). When any decoding class's observed TPOT runs
    over its target, the whole prefill budget is halved for the step
    (decode protection), never below one tile (prefill liveness).
  * TENANCY: requests additionally carry a `tenant` (the multi-tenant
    adapter-serving surface, engine/adapters.py). Within each class's
    tile grant the budget is re-apportioned ACROSS TENANTS by the
    operator-configured tenant weight (engine_cfg.tenant_weights,
    default 1.0 — equal shares), FIFO within a tenant, so one tenant's
    prompt flood cannot monopolise a class's prefill budget. Per-tenant
    TTFT/TPOT EWMAs (`observe_tenant`) give the operator the same
    feedback signal per tenant the class loop has per class, and the
    queue-depth gauge carries a tenant label. The tenant QUOTA shed
    (429 before other tenants starve) lives at the enqueue edge in
    engine/continuous.py — this module only supplies the weights.
  * ADMISSION CONTROL: the head-of-queue evictable-block check grew into
    a policy object — a class whose queue drain ESTIMATE (class depth x
    observed per-request service time) already overruns its TTFT target
    is shed at enqueue with a 429 whose Retry-After derives from THAT
    class's drain estimate, never the global queue depth; non-sheddable
    classes only queue.

Everything here is host-side planning over plain Python/numpy state —
strictly decode-UNREACHABLE (pinned in the test_analysis.py callgraph
fixture, like engine/paged.build_ragged_meta); the device work happens in
the one mixed program the continuous engine launches per step.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Optional

from ..utils.logging import get_logger
from ..utils.retry import BACKOFF_CAP_S, overload_retry_after

log = get_logger("scheduler")

# shed when the class drain estimate exceeds grace x its TTFT target
SHED_GRACE = 4.0
# never shed a backlog smaller than this many requests per class — the
# estimate is too noisy at tiny depths to refuse work over it
MIN_SHED_DEPTH = 4
# ceiling for a class-derived Retry-After hint (seconds)
RETRY_AFTER_CAP_S = 30.0

# how far back the n-gram draft planner scans a slot's token history for
# the current bigram (host Python per slot per launch — bounded so a
# max-window chat history cannot stretch the launch-planning hot loop)
NGRAM_SCAN_WINDOW = 1024

# Adaptive per-slot drafting (rides the device-derived-metadata unfrozen
# loop, ISSUE 15): each slot's draft acceptance rate feeds an EWMA that
# sizes its NEXT draft between 0 and spec_draft_len — repetitive streams
# keep long drafts, incompressible ones degrade to plain decode without
# burning verify tiles.
SPEC_EWMA_ALPHA = 0.35
# below this acceptance EWMA a slot stops speculating entirely (K = 0:
# a verify row that mostly rejects still costs its extra flat tokens)...
SPEC_MIN_RATE = 0.2
# ...and re-probes with a 1-token draft after this many skipped plans,
# so a stream that turns repetitive later is not locked out forever
SPEC_REPROBE = 16


# jaxlint: decode-unreachable -- host-side launch planning over Python lists (scheduler worker thread only)
def ngram_draft(hist: list, k: int) -> list:
    """Prompt-lookup draft for one decode slot: the (up to) `k` tokens
    that followed the most recent earlier occurrence of the current
    bigram in `hist` (prompt + emitted tokens, fetched so far).

    The host twin of the traced rule in engine/generate.spec_loop, with
    one scheduler-grade difference: where the traced loop runs a junk
    draft when no bigram matches (the forward is already paid for), this
    planner returns [] so the slot submits a PLAIN decode row instead —
    a draft only spends step_token_budget when the history actually
    offers one, and non-repetitive streams pay nothing. A wrong draft is
    never a correctness hazard either way: the verify row accepts a
    token only where it equals the model's own argmax."""
    n = len(hist)
    if k <= 0 or n < 3:
        return []
    c0, c1 = hist[-2], hist[-1]
    lo = max(0, n - 2 - NGRAM_SCAN_WINDOW)
    # the match must be strictly earlier than the current bigram; prefer
    # the most recent match, but keep scanning while it cannot supply a
    # full k-token draft (a short-period repetition's latest match sits
    # so close to the end that its follower slice truncates — an earlier
    # occurrence of the same bigram drafts the whole period)
    best: list = []
    for i in range(n - 3, lo - 1, -1):
        if hist[i] == c0 and hist[i + 1] == c1:
            cand = list(hist[i + 2 : i + 2 + k])
            if len(cand) > len(best):
                best = cand
                if len(best) == k:
                    break
    return best


# jaxlint: decode-unreachable -- host-side launch planning arithmetic (scheduler worker thread only)
def spec_block_cap(n_blocks: int, block_size: int, frontier: int) -> int:
    """Max draft length a slot at `frontier` can verify-write without
    the kernel's lblk clamp folding positions past its allocation into
    its own last LIVE block (engine/paged.make_ragged_fill_hook). In
    device-meta mode `frontier` must be the PESSIMISTIC bound — the
    lagged host position plus every pending verify launch's maximum
    advance — because the device may already sit that far ahead."""
    return n_blocks * block_size - 1 - frontier


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One service class: latency targets + its share of the prefill
    budget. `sheddable=False` (bulk/batch traffic) means admission only
    ever queues it — capacity pressure sheds the latency-sensitive
    classes first, because those are the requests whose SLO a deep queue
    has already broken."""

    name: str
    ttft_target_s: float
    tpot_target_s: float
    weight: float = 1.0
    sheddable: bool = True


def parse_slo_classes(engine_cfg) -> "collections.OrderedDict[str, SLOClass]":
    """engine_cfg.slo_classes tuples -> name-keyed SLOClass map (insertion
    order preserved — it is the display/apportionment order)."""
    out = collections.OrderedDict()
    for entry in engine_cfg.slo_classes:
        c = SLOClass(*entry)
        if c.ttft_target_s <= 0 or c.tpot_target_s <= 0 or c.weight <= 0:
            raise ValueError(
                f"slo class {c.name!r} needs positive targets and weight"
            )
        out[c.name] = c
    if engine_cfg.slo_default_class not in out:
        raise ValueError(
            f"slo_default_class {engine_cfg.slo_default_class!r} is not in "
            f"slo_classes {tuple(out)}"
        )
    return out


class PrefillJob:
    """Host state of one chunked admission: the prompt tail past the
    prefix-reuse depth is fed into the pool CHUNK BY CHUNK across mixed
    launches. `done` counts tail tokens already launched — always a whole
    number of chunks, so a crash between launches loses only whole chunks
    (the chunk-boundary salvage contract; the rebuilt pool means recovery
    re-plans from zero, and prefill determinism keeps greedy output
    bit-identical)."""

    __slots__ = (
        "req", "ids", "p0", "done", "prompt_len", "max_tokens", "slot",
        "sampling", "presence_row", "table_row", "cls",
    )

    def __init__(self, req, ids, p0, prompt_len, max_tokens, slot, sampling,
                 presence_row, table_row, cls):
        self.req = req
        self.ids = ids  # full token list (salvaged continuation included)
        self.p0 = p0  # prefix-reuse depth (mapped shared blocks)
        self.done = 0  # tail tokens already launched
        self.prompt_len = prompt_len
        self.max_tokens = max_tokens
        self.slot = slot
        self.sampling = sampling  # host-side scalar tuple (SamplingParams)
        self.presence_row = presence_row  # np bool [V] prompt token set
        self.table_row = table_row
        self.cls = cls  # SLOClass

    @property
    def remaining(self) -> int:
        """Tail tokens not yet launched (>= 1 until the final chunk —
        which must carry the sampling token — has gone out)."""
        return len(self.ids) - self.p0 - self.done


class _ClassFeedback:
    """Per-class rolling latency observations (the feedback half of the
    SLO loop): EWMA TTFT — the class drain-estimate unit — and EWMA TPOT
    — the decode-protection signal. Fed from the same per-request samples
    the dli_ttft/dli_tpot histograms record, one write per completed
    request; reads are racy-but-monotone floats (GIL-atomic), safe from
    the enqueue path without the engine lock."""

    __slots__ = ("ttft_ewma", "tpot_ewma", "samples")

    ALPHA = 0.3

    def __init__(self):
        self.ttft_ewma: Optional[float] = None
        self.tpot_ewma: Optional[float] = None
        self.samples = 0

    def observe(self, ttft_s: Optional[float], tpot_s: Optional[float]):
        if ttft_s is not None:
            self.ttft_ewma = (
                ttft_s if self.ttft_ewma is None
                else (1 - self.ALPHA) * self.ttft_ewma + self.ALPHA * ttft_s
            )
        if tpot_s is not None:
            self.tpot_ewma = (
                tpot_s if self.tpot_ewma is None
                else (1 - self.ALPHA) * self.tpot_ewma + self.ALPHA * tpot_s
            )
        self.samples += 1


class TokenBudgetScheduler:
    """Pure host-side planner: slices the per-step flat-token budget into
    decode rows + class-apportioned prefill chunks, and answers the
    admission-control questions (shed? Retry-After?) from per-class
    feedback. Owns NO device state — the continuous engine translates the
    plan into one mixed ragged launch.

    width: flat-token launch width (the compiled mixed program's shape);
    tile: the ragged kernel's query tile — every launch entry occupies
    whole tiles, so budget accounting is in tiles.
    """

    def __init__(self, classes, default_name: str, width: int, tile: int,
                 n_slots: int, registry=None, tenant_weights=()):
        self.classes = classes
        self.default_name = default_name
        # tenant -> prefill-budget weight (engine_cfg.tenant_weights);
        # unlisted tenants (and the anonymous "" tenant) weigh 1.0
        self.tenant_weights = {
            str(name): float(w) for name, w in tenant_weights
        }
        # tenant -> _ClassFeedback, created lazily at first observation
        # (the tenant population is open-ended, unlike the class set)
        self.tenant_feedback: dict = {}
        self.tile = int(tile)
        # every active slot's decode row costs one tile, and at least one
        # tile must remain for prefill progress (starvation freedom) —
        # clamp the width up instead of starting a scheduler that can
        # wedge with a full fleet
        min_width = (int(n_slots) + 1) * self.tile
        self.width = -(-max(int(width), min_width) // self.tile) * self.tile
        if self.width > width:
            log.info(
                "step_budget_clamped", requested=width, width=self.width,
                reason="decode rows + one prefill tile must fit",
            )
        self.n_slots = int(n_slots)
        self.feedback = {name: _ClassFeedback() for name in classes}
        # summary of the most recent non-empty plan() — the flight
        # recorder's "plan" event embeds it so a crash dump shows the
        # last budget split (per-class tiles) without replaying the
        # scheduler (ISSUE 17 forensics)
        self.last_plan: Optional[dict] = None
        # per-slot draft-acceptance feedback: slot -> [EWMA, skipped
        # plans] (adaptive K; reset on re-assignment via spec_reset)
        self._spec_fb: dict = {}
        self._m_depth = self._m_shed = None
        self._m_spec_k = self._m_spec_ewma = None
        if registry is not None:
            from ..utils.metrics import DEFAULT_SIZE_BUCKETS

            self._m_spec_k = registry.histogram(
                "dli_spec_draft_len",
                "planned draft length K per verify row (after the "
                "adaptive per-slot throttle)",
                buckets=DEFAULT_SIZE_BUCKETS,
            ).labels()
            self._m_spec_ewma = registry.gauge(
                "dli_spec_accept_ewma",
                "fleet-mean per-slot draft acceptance-rate EWMA (0..1)",
            ).labels()
        if registry is not None:
            self._m_depth = registry.gauge(
                "dli_slo_queue_depth",
                "queued requests per SLO class and tenant",
                ("slo_class", "tenant"),
            )
            self._m_shed = registry.counter(
                "dli_slo_shed_total",
                "requests shed with 429 by SLO admission control (class "
                "drain estimate over the TTFT target, or queue full)",
                ("slo_class",),
            )
            for name in classes:
                # pre-touch every class series (anonymous tenant) so the
                # scrape schema is stable from the first request
                self._m_depth.labels(slo_class=name, tenant="").set(0)

    # -- classification ------------------------------------------------------
    def classify(self, name: Optional[str]) -> SLOClass:
        """Request slo_class -> SLOClass; None/unknown falls back to the
        default class (the serving edge validates and 400s unknown names
        BEFORE enqueue — this fallback covers embedded/API callers)."""
        if name is not None and name in self.classes:
            return self.classes[name]
        return self.classes[self.default_name]

    # jaxlint: decode-unreachable -- validation helper for embedders/tests; host-only by construction
    def valid(self, name: str) -> bool:
        return name in self.classes

    # -- feedback ------------------------------------------------------------
    def observe(self, cls_name: str, ttft_s: Optional[float],
                tpot_s: Optional[float]):
        fb = self.feedback.get(cls_name)
        if fb is not None:
            fb.observe(ttft_s, tpot_s)

    def observe_tenant(self, tenant: Optional[str],
                       ttft_s: Optional[float], tpot_s: Optional[float]):
        """Per-tenant twin of `observe`: the same completed-request TTFT
        / TPOT samples, keyed by the request's tenant. Anonymous
        requests (no tenant) record nothing — their feedback already
        lands in the class EWMAs."""
        if not tenant:
            return
        fb = self.tenant_feedback.get(tenant)
        if fb is None:
            fb = self.tenant_feedback[tenant] = _ClassFeedback()
        fb.observe(ttft_s, tpot_s)

    def tenant_weight(self, tenant: Optional[str]) -> float:
        """Configured prefill-budget weight for `tenant` (1.0 when the
        tenant is anonymous or unlisted in engine_cfg.tenant_weights)."""
        if not tenant:
            return 1.0
        return self.tenant_weights.get(tenant, 1.0)

    def set_depth(self, cls_name: str, depth: int, tenant: str = ""):
        if self._m_depth is not None:
            self._m_depth.labels(
                slo_class=cls_name, tenant=tenant or ""
            ).set(depth)

    def count_shed(self, cls_name: str):
        if self._m_shed is not None:
            self._m_shed.labels(slo_class=cls_name).inc()

    # -- admission control ---------------------------------------------------
    def drain_estimate_s(self, cls: SLOClass, class_depth: int) -> float:
        """Expected wait for a NEW request of `cls` behind its class-local
        backlog: depth x the class's observed per-request TTFT EWMA. With
        no samples yet, a coarse depth/fleet-width heuristic (the same
        unit the pre-SLO global hint used, but over the CLASS depth)."""
        fb = self.feedback.get(cls.name)
        if fb is not None and fb.ttft_ewma is not None:
            return class_depth * fb.ttft_ewma
        return float(overload_retry_after(class_depth, self.n_slots))

    def retry_after_s(self, cls: SLOClass, class_depth: int) -> int:
        """Class-aware Retry-After: when THIS class's backlog drains, not
        when the global queue does — a deep batch backlog must not tell
        an interactive client to stay away, and vice versa."""
        est = self.drain_estimate_s(cls, class_depth)
        return int(min(RETRY_AFTER_CAP_S, max(1.0, round(est))))

    def should_shed(self, cls: SLOClass, class_depth: int) -> bool:
        """Shed (429) a sheddable class whose drain estimate already
        overruns SHED_GRACE x its TTFT target — admitting it would burn
        budget on a request whose SLO is unmeetable. Small backlogs never
        shed (estimate noise), non-sheddable classes never shed (they
        queue until the bounded queue itself is full)."""
        if not cls.sheddable or class_depth < MIN_SHED_DEPTH:
            return False
        fb = self.feedback.get(cls.name)
        if fb is None or fb.ttft_ewma is None:
            return False  # no data: never refuse work on a guess
        return (
            self.drain_estimate_s(cls, class_depth)
            > SHED_GRACE * cls.ttft_target_s
        )

    # -- preemption policy ---------------------------------------------------
    def victim_key(self, cls: SLOClass, enqueued: float) -> tuple:
        """Sort key for KV-preemption victim selection: LOWEST SLO weight
        first, then the YOUNGEST request (latest enqueue) within a
        weight tie — the request whose eviction wastes the least
        progress and whose class the operator values least. min() over
        candidates' keys picks the victim."""
        return (cls.weight, -enqueued)

    def select_victim(self, candidates, beneficiary_cls: SLOClass):
        """Pick the preemption victim from `candidates`
        ([(request, SLOClass, enqueued_s)]) on behalf of a request of
        `beneficiary_cls`, or None. A victim must not outrank the
        beneficiary (weight strictly above it is protected — a batch
        admission never preempts an interactive decode); among eligible
        candidates the lowest-weight / youngest loses."""
        eligible = [
            (req, cls, enq) for req, cls, enq in candidates
            if cls.weight <= beneficiary_cls.weight
        ]
        if not eligible:
            return None
        return min(eligible, key=lambda c: self.victim_key(c[1], c[2]))[0]

    # -- the per-step budget slice -------------------------------------------
    def _urgency(self, cls: SLOClass, oldest_wait_s: float) -> float:
        """How far past (or inside) its TTFT target the class's oldest
        pending prefill is — the apportionment feedback term, clamped so
        one pathological wait cannot zero everyone else's share."""
        return min(8.0, max(0.25, oldest_wait_s / cls.ttft_target_s))

    def decode_pressure(self, active_classes) -> bool:
        """True when any class with active decode rows observes TPOT over
        its target — the signal to halve the step's prefill budget."""
        for name in active_classes:
            cls = self.classes.get(name)
            fb = self.feedback.get(name)
            if (
                cls is not None and fb is not None
                and fb.tpot_ewma is not None
                and fb.tpot_ewma > cls.tpot_target_s
            ):
                return True
        return False

    # -- speculation throttle ------------------------------------------------
    def observe_spec(self, slot: int, drafted: int, accepted: int):
        """Per-slot acceptance feedback, fed from the SAME packed fetch
        that carries the verify row's emissions (engine/continuous.
        _process_mixed) — one EWMA write per fetched verify row."""
        if drafted <= 0:
            return
        rate = min(1.0, max(0.0, accepted / drafted))
        fb = self._spec_fb.get(slot)
        if fb is None:
            fb = [rate, 0]
            self._spec_fb[slot] = fb
        else:
            fb[0] = (1 - SPEC_EWMA_ALPHA) * fb[0] + SPEC_EWMA_ALPHA * rate
        fb[1] = 0
        if self._m_spec_ewma is not None:
            self._m_spec_ewma.set(
                sum(f[0] for f in self._spec_fb.values())
                / len(self._spec_fb)
            )

    def spec_slot_k(self, slot: int, k_max: int) -> int:
        """Adaptive per-slot draft length: size the slot's NEXT draft by
        its observed acceptance EWMA. No data yet -> full `k_max` (new
        streams probe at full depth — the n-gram gate already filters
        slots with nothing to draft); EWMA below SPEC_MIN_RATE -> 0 (a
        plain decode row, no verify tiles burnt), with a 1-token
        re-probe every SPEC_REPROBE skipped plans; otherwise the draft
        scales with the EWMA, converging back to k_max as acceptance
        recovers."""
        if k_max <= 0:
            return 0
        fb = self._spec_fb.get(slot)
        if fb is None:
            return k_max
        ewma = fb[0]
        if ewma < SPEC_MIN_RATE:
            fb[1] += 1
            if fb[1] >= SPEC_REPROBE:
                fb[1] = 0
                return 1
            return 0
        return max(1, min(k_max, math.ceil(ewma * k_max)))

    def spec_reset(self, slot: int):
        """Forget a slot's acceptance history (the slot was re-assigned:
        a new tenant's stream predicts nothing about the old one's)."""
        self._spec_fb.pop(slot, None)

    def count_spec_plan(self, k: int):
        """Record one verify row's planned K (dli_spec_draft_len)."""
        if self._m_spec_k is not None:
            self._m_spec_k.observe(k)

    def spec_draft_len(self, k_max: int, n_spec_rows: int,
                       n_plain_rows: int, active_classes=(),
                       jobs_pending: bool = False) -> int:
        """Draft length K for this step's verify rows (0 = speculation
        off). Speculated tokens spend step_token_budget like any other
        flat token, so the SLO layer throttles them with the knobs it
        already owns: under decode TPOT pressure (the SAME signal that
        halves the prefill budget) K drops to 0 — speculation
        accelerates idle fleets and self-disables under load — and
        otherwise K shrinks until every verify row (ceil((1+K)/tile)
        tiles each), every plain decode row, and one prefill-progress
        tile (when prefill is pending) fit the step budget together."""
        if k_max <= 0 or n_spec_rows <= 0:
            return 0
        if self.decode_pressure(active_classes):
            return 0
        tiles_total = self.width // self.tile
        reserve = n_plain_rows + (1 if jobs_pending else 0)
        for k in range(k_max, 0, -1):
            spec_tiles = -(-(1 + k) // self.tile) * n_spec_rows
            if spec_tiles + reserve <= tiles_total:
                return k
        return 0

    def _grant_class(self, members, tiles: int, give) -> int:
        """Distribute one class's tile grant across its TENANTS by
        configured weight (FIFO within a tenant), returning the unspent
        remainder. A single-tenant class degenerates to plain FIFO — the
        pre-tenancy behavior, byte-for-byte. Unused tenant shares spill
        FIFO within the class before leaking up to the cross-class
        spill, so a light tenant's share is never wasted while a heavy
        one still has work."""
        if tiles <= 0:
            return 0
        by_tenant: dict = collections.OrderedDict()
        for job in members:
            t = getattr(job.req, "tenant", None) or ""
            by_tenant.setdefault(t, []).append(job)
        if len(by_tenant) == 1:
            for job in members:
                tiles -= give(job, tiles)
                if tiles <= 0:
                    break
            return max(0, tiles)
        weights = {t: self.tenant_weight(t) for t in by_tenant}
        total = sum(weights.values())
        shares = {t: int(tiles * w / total) for t, w in weights.items()}
        spare = tiles - sum(shares.values())
        # remainder tiles to the heaviest tenants (stable sort keeps
        # arrival order among equal weights — deterministic)
        for t in sorted(weights, key=lambda n: -weights[n]):
            if spare <= 0:
                break
            shares[t] += 1
            spare -= 1
        leftover = 0
        for t, tjobs in by_tenant.items():
            share = shares.get(t, 0)
            for job in tjobs:
                share -= give(job, share)
                if share <= 0:
                    break
            leftover += max(0, share)
        if leftover > 0:
            for job in members:
                leftover -= give(job, leftover)
                if leftover <= 0:
                    break
        return max(0, leftover)

    def plan(self, n_decode_tiles: int, jobs: list,
             active_classes=(), now: Optional[float] = None) -> list:
        """Slice one step's budget: returns [(job, chunk_tokens)] with
        chunk_tokens >= 1, tile-granular except a job's FINAL chunk.

        Decode rows were reserved upstream — `n_decode_tiles` query
        tiles, one per plain decode row plus ceil((1+K)/tile) per
        speculative verify row, so speculated tokens debit the budget
        exactly like prefill tokens; `jobs` are the pending prefills in
        arrival order. Tiles left after decode are apportioned across
        classes by weight x urgency, then WITHIN each class across
        tenants by configured tenant weight (`_grant_class`), FIFO
        within a tenant; leftovers spill FIFO across classes; the
        OLDEST job is guaranteed a tile (starvation freedom). Under
        decode TPOT pressure the prefill budget halves (never below one
        tile)."""
        if not jobs:
            return []
        t = time.time() if now is None else now
        tiles_total = self.width // self.tile
        tiles_left = tiles_total - n_decode_tiles
        if tiles_left < 1:
            # structurally unreachable (width clamps to n_slots + 1 tiles
            # and a prefilling admission occupies a slot), but never plan
            # a launch that cannot hold its entries
            return []
        if self.decode_pressure(active_classes):
            tiles_left = max(1, tiles_left // 2)

        by_class: dict = collections.OrderedDict()
        for job in jobs:
            by_class.setdefault(job.cls.name, []).append(job)
        # class shares: weight x urgency over the classes with work
        scores = {}
        for name, members in by_class.items():
            cls = members[0].cls
            oldest_wait = max(t - m.req.enqueued for m in members)
            scores[name] = cls.weight * self._urgency(cls, oldest_wait)
        total = sum(scores.values())
        tiles_for = {
            name: int(tiles_left * s / total) for name, s in scores.items()
        }
        # remainder tiles to the highest-scoring classes, deterministic
        spare = tiles_left - sum(tiles_for.values())
        for name in sorted(scores, key=lambda n: -scores[n]):
            if spare <= 0:
                break
            tiles_for[name] += 1
            spare -= 1

        grants: dict = {}

        def give(job, tiles):
            need = -(-job.remaining // self.tile)
            take = min(tiles, need - grants.get(id(job), 0))
            if take > 0:
                grants[id(job)] = grants.get(id(job), 0) + take
            return take

        leftover = 0
        for name, members in by_class.items():
            leftover += self._grant_class(
                members, tiles_for.get(name, 0), give
            )
        # spill unused class budget FIFO across every class
        if leftover > 0:
            for job in jobs:
                leftover -= give(job, leftover)
                if leftover <= 0:
                    break
        # starvation freedom: the globally oldest job always progresses —
        # reclaim a tile from the fattest (newest on ties) grant when the
        # budget is fully spoken for
        oldest = min(jobs, key=lambda j: j.req.enqueued)
        if not grants.get(id(oldest)):
            if sum(grants.values()) >= tiles_left:
                granted = [j for j in jobs if grants.get(id(j))]
                if granted:
                    victim = max(
                        granted,
                        key=lambda j: (grants[id(j)], j.req.enqueued),
                    )
                    grants[id(victim)] -= 1
                    if not grants[id(victim)]:
                        del grants[id(victim)]
            give(oldest, 1)

        out = []
        for job in jobs:  # arrival order, independent of grant order
            tiles = grants.get(id(job), 0)
            if tiles > 0:
                out.append((job, min(tiles * self.tile, job.remaining)))
        self.last_plan = {
            "decode_tiles": int(n_decode_tiles),
            "prefill_tiles": int(sum(grants.values())),
            "tiles_total": tiles_total,
            "class_tiles": dict(tiles_for),
            "jobs": len(jobs),
            "chunks": len(out),
        }
        return out
