"""Host-side crash-consistent KV shadow store: warm recovery for the
paged fleet, and tiers 1+2 of the KV cache hierarchy.

Every recovery path this repro grew in PRs 5-8 — supervisor restarts,
poison quarantine, graceful drain, router failover, rolling restarts —
comes back COLD: the rebuilt pool holds no KV, so each salvaged request
re-prefills its whole prompt and a drained replica respawns with an
empty block-prefix cache. At production scale that is minutes of
recomputed prefill per incident (the reference's recovery story is
"restart the Colab"; preemptible TPU capacity makes restart cost a
first-order serving metric — see PAPERS.md).

Paged KV blocks are append-only and immutable once FILLED (decode and
tail-prefill writes only ever land at later positions; the frozen-row
overrun clamp only touches a request's own partial last block or the
trash block — engine/paged.py), so the shadow works at block
granularity:

  * CAPTURE (worker thread, async): when a block fills — a whole-prefill
    admission lands, a chunked-prefill launch crosses a block boundary,
    or a fetched decode chunk shows a row crossed one — the engine
    dispatches a small read-only device gather of the filled blocks
    (engine/paged.gather_shadow_blocks, enqueued in launch order AFTER
    the filling program, so device execution order guarantees the
    gathered bytes are the block's final content) and hands the device
    arrays to THIS module's copier thread. The device->host transfer
    (the only blocking step) happens entirely off the scheduler loop;
    the pending queue is bounded and overflow DEMOTES the batch straight
    to the disk tier (and only a doubly-full queue drops it — a lost
    shadow block costs a colder recovery, never correctness), so the
    zero-host-sync launch invariants survive untouched — this module is
    pinned decode-UNREACHABLE in the test_analysis.py callgraph fixture
    exactly like utils/faults.py.
  * KEYS are content: a block's key is the full token prefix it
    completes (a tuple of ids, length a multiple of block_size). A
    block's KV is a pure function of the token prefix under
    teacher-forcing, so a content-keyed entry can never be stale and
    restoring it into ANY rebuilt pool is bit-exact — the same
    immutability argument engine/block_prefix.py makes for live block
    sharing, extended across a pool rebuild. Entries are stamped with
    the engine's mutation seq at capture (observability + persist
    versioning; consistency never depends on the stamp).
  * TIERS (ARCHITECTURE.md "Tiered KV"): the pool is tier 0 (HBM), the
    in-memory entries here are tier 1 (host DRAM), and `disk_dir` adds
    tier 2 — one self-describing npz chunk file per block, named by its
    parent-chained digest (chunk_<digest>.npz, the same layout the
    --restore-dir persist uses). Capacity eviction from tier 1 DEMOTES
    to tier 2 instead of dropping; every read surface (entries_for /
    chain_for_digest / select / has) falls through to tier 2 and
    PROMOTES hits back into tier 1, so existing consumers (block-prefix
    planning, warm recovery, preemption swap, the KV fabric)
    transparently hit through the deepest tier. Content keying is what
    keeps every tier trivially consistent: a chunk file is rejected
    (and deleted) unless its own manifest tokens reproduce both its
    filename digest and the key being looked up — a truncated,
    tampered, or wrong-block-size file can only produce a MISS into the
    next tier up (then a cold re-prefill), never wrong KV.
  * RESTORE (supervisor restart): the engine flushes pending copies,
    selects as many MRU chains as the fresh pool can hold — spanning
    tiers 1 AND 2 — and scatters them back in ONE launch
    (engine/paged.restore_shadow_blocks), then registers the chains
    into the BlockPrefixIndex: salvaged requests re-admit through the
    ordinary block-prefix hit machinery and re-prefill ONLY the partial
    tail block.
  * PERSIST (graceful drain): save()/load() serialize tier 1 to an
    atomic npz under --restore-dir, so a rolling restart cycles the
    replica back in with a WARM prefix cache. Tier 2 is already
    persistent — a restart rescans it.
  * WIRE (the cross-replica KV fabric, serving/kv_fabric.py): entries
    are additionally indexed by their parent-chained chunk digest
    (block_prefix.chunk_digests over the key), so a peer replica can
    fetch a whole chain by digest through GET /kv/{digest} —
    chain_for_digest / resident_digests / put_host are that surface,
    and all of them span the disk tier. Content keying is what makes
    this sound over the wire: the digest names the token prefix, the
    fetcher recomputes it from the payload's tokens, and KV is a pure
    function of the prefix — so a fetched chain is bit-identical to one
    computed locally, or it is rejected.

What is deliberately NOT shadowed: partial tail blocks (mutable until
they fill), slot/sampling state (host-reconstructable from the salvage
record), constraint FSM rows (re-derived by advancing the DFA over
salvaged tokens), the trash block, and dense-fleet caches (no block
immutability to lean on).
"""

from __future__ import annotations

import collections
import io
import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..utils.logging import get_logger
from .block_prefix import chunk_digests

log = get_logger("shadow")

_PERSIST_VERSION = 1
_PERSIST_NAME = "shadow.npz"

# tier-2 chunk files: one block per file, named by the parent-chained
# digest of the full token prefix the block completes
_DISK_VERSION = 1
_DISK_PREFIX = "chunk_"
_DISK_SUFFIX = ".npz"


class _Entry:
    __slots__ = ("leaves", "seq")

    def __init__(self, leaves, seq):
        self.leaves = leaves  # list of per-leaf np arrays (one block each)
        self.seq = seq

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.leaves)


def _read_chunk_file(path: str, key: tuple, block_size: int) -> _Entry:
    """Parse + content-verify one tier-2 chunk file: the file's own
    manifest tokens must reproduce the key being looked up (and hence
    the filename digest), its block_size must match, and its arrays
    must parse. Raises on ANY mismatch — pure (no store state), so
    promotion can fan reads out across threads without the lock."""
    with np.load(path, allow_pickle=False) as z:
        manifest = json.loads(str(z["manifest"]))
        leaves = []
        j = 0
        while f"leaf_{j}" in z.files:
            leaves.append(np.array(z[f"leaf_{j}"]))
            j += 1
    if manifest.get("version") != _DISK_VERSION:
        raise ValueError(f"version {manifest.get('version')!r}")
    if manifest.get("block_size") != block_size:
        raise ValueError(
            f"block_size {manifest.get('block_size')!r} != {block_size}"
        )
    toks = tuple(int(t) for t in manifest.get("t", ()))
    if toks != key:
        raise ValueError("manifest tokens do not reproduce the key")
    if not leaves:
        raise ValueError("no leaf arrays")
    return _Entry(leaves, int(manifest.get("seq", 0)))


class ShadowStore:
    """Bounded LRU of host-side shadowed KV blocks, content-keyed by the
    token prefix each block completes, with an optional disk tier
    (`disk_dir`) LRU host entries demote into instead of dropping.

    Single-writer discipline mirrors the allocator's: put_async /
    select / drop_pending run on the continuous engine's worker thread,
    the copier thread only consumes its own queue, and the lock exists
    for stats()/save() readers on other threads. Disk files are written
    on whichever thread evicts (small single-block npz) or on the
    copier thread (backpressure spills), and read on the caller's
    thread at promotion — never on the device path.

    registry (utils/metrics.MetricsRegistry, optional):
    `dli_shadow_blocks` (resident host-shadowed blocks),
    `dli_shadow_copies_total` (blocks copied device->host),
    `dli_shadow_dropped_total` (blocks dropped: doubly-full copier
    queue or a failed transfer), plus the tier families
    `dli_kv_tier_{entries,bytes}` (gauges, tier=host|disk) and
    `dli_kv_tier_{promotions,demotions,disk_hits}_total` — families
    pre-registered in engine/engine.py.
    """

    def __init__(self, block_size: int, max_blocks: int = 256,
                 max_pending: int = 32, registry=None,
                 disk_dir: Optional[str] = None,
                 max_disk_blocks: int = 0):
        if block_size < 1:
            raise ValueError("shadow store needs block_size >= 1")
        self.block_size = int(block_size)
        self.max_blocks = max(1, int(max_blocks))
        self.max_pending = max(1, int(max_pending))
        self.disk_dir = disk_dir or None
        # 0 = auto: 8x the host tier, so the logical cache is an order
        # of magnitude deeper than host DRAM before files churn
        self.max_disk_blocks = (
            max(1, int(max_disk_blocks)) if max_disk_blocks
            else 8 * self.max_blocks
        )
        # guarded-by: _lock
        self._entries: "collections.OrderedDict[tuple, _Entry]" = (
            collections.OrderedDict()
        )
        self._children: dict = {}  # key -> set of child keys; guarded-by: _lock
        # chunk-digest index over the resident keys (the same parent-
        # chained digests engine/block_prefix.chunk_digests exports for
        # router affinity), so the KV fabric's /kv lookups are O(1)
        # instead of a full-store digest sweep per request
        self._digest_key: dict = {}  # digest hex -> key; guarded-by: _lock
        # tier 2 index: key -> (digest, file bytes), LRU like _entries;
        # plus the digest->key and parent->children views. All
        # guarded-by: _lock — files themselves are only touched while
        # the index says they exist.
        self._disk: "collections.OrderedDict[tuple, tuple]" = (
            collections.OrderedDict()
        )
        self._disk_digest: dict = {}  # guarded-by: _lock
        self._disk_children: dict = {}  # guarded-by: _lock
        self._disk_bytes = 0  # guarded-by: _lock
        self._host_bytes = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # copier queue: (keys, dev_leaves, seq, to_disk) batches; keys in
        # _pending are visible to has() so the worker never re-captures
        # a block whose copy is still in flight
        self._q: collections.deque = collections.deque()
        self._pending: set = set()
        self._busy = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self.copied = 0
        self.dropped = 0
        self.evicted = 0
        self.demoted = 0
        self.promoted = 0
        self.disk_hits = 0
        self.disk_rejected = 0
        self._m_blocks = self._m_copies = self._m_dropped = None
        self._m_tier_entries: dict = {}
        self._m_tier_bytes: dict = {}
        self._m_promotions: dict = {}
        self._m_demotions = self._m_disk_hits = None
        if registry is not None:
            self._m_blocks = registry.gauge(
                "dli_shadow_blocks",
                "host-shadowed paged-KV blocks resident for warm recovery",
            ).labels()
            self._m_copies = registry.counter(
                "dli_shadow_copies_total",
                "paged-KV blocks copied device->host into the shadow store",
            ).labels()
            self._m_dropped = registry.counter(
                "dli_shadow_dropped_total",
                "shadow blocks dropped (copier backpressure or a failed "
                "device->host transfer)",
            ).labels()
            g_entries = registry.gauge(
                "dli_kv_tier_entries",
                "KV blocks resident per cache tier (host = shadow DRAM, "
                "disk = persisted chunk files)", ("tier",),
            )
            g_bytes = registry.gauge(
                "dli_kv_tier_bytes",
                "approximate bytes resident per KV cache tier", ("tier",),
            )
            for tier in ("host", "disk"):
                self._m_tier_entries[tier] = g_entries.labels(tier=tier)
                self._m_tier_bytes[tier] = g_bytes.labels(tier=tier)
            c_prom = registry.counter(
                "dli_kv_tier_promotions_total",
                "KV blocks promoted up the tier hierarchy, by destination "
                "tier (host = disk->DRAM load, pool = scattered into HBM)",
                ("tier",),
            )
            self._m_promotions = {
                "host": c_prom.labels(tier="host"),
                "pool": c_prom.labels(tier="pool"),
            }
            self._m_demotions = registry.counter(
                "dli_kv_tier_demotions_total",
                "KV blocks demoted down the tier hierarchy, by destination "
                "tier (disk = host-LRU spill or copier-backpressure spill)",
                ("tier",),
            ).labels(tier="disk")
            self._m_disk_hits = registry.counter(
                "dli_kv_tier_disk_hits_total",
                "lookups served from the disk tier (chunk files loaded and "
                "verified on a read that missed the host tier)",
            ).labels()
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
            with self._lock:
                self._disk_scan_locked()
                self._note_tiers_locked()
        self._thread = threading.Thread(
            target=self._copier, daemon=True, name="shadow-copier"
        )
        self._thread.start()

    # -- worker-thread surface ----------------------------------------------
    def has(self, key: tuple) -> bool:
        """True when `key` is resident in ANY tier OR its copy is
        already in flight (capture dedup must not re-gather a block the
        hierarchy can already restore)."""
        with self._lock:
            return (
                key in self._entries or key in self._pending
                or key in self._disk
            )

    def has_resident(self, key: tuple) -> bool:
        """True only when `key` is restorable right now — landed in the
        host tier or persisted in the disk tier (an in-flight copy is
        not; preemption's swap path flushes first)."""
        with self._lock:
            return key in self._entries or key in self._disk

    def entries_for(self, keys: list) -> Optional[list]:
        """The resident entries for `keys` in order, or None when ANY is
        missing from every tier (a targeted restore needs the whole
        contiguous run — a chain with a hole cannot be registered).
        Disk-tier members are loaded, verified, and PROMOTED into the
        host tier first; a corrupt chunk file rejects into a miss.
        Touches each entry MRU, like a hit."""
        missing: list = []
        with self._lock:
            for k in keys:
                if k in self._entries:
                    continue
                if k in self._disk:
                    missing.append(k)
                else:
                    return None
            if not missing:
                out = []
                for k in keys:
                    e = self._entries[k]
                    self._entries.move_to_end(k)
                    out.append(e)
                return out
        if not self._promote_keys(missing):
            return None
        out = []
        with self._lock:
            for k in keys:
                e = self._entries.get(k)
                if e is None:  # promoted entry already churned out: miss
                    return None
                self._entries.move_to_end(k)
                out.append(e)
        return out

    # -- chunk-digest surface (the KV fabric, serving/kv_fabric.py) ----------
    def digest_of(self, key: tuple) -> str:
        """The parent-chained chunk digest covering the whole of `key`
        (engine/block_prefix.chunk_digests — the router tier's affinity
        and residency currency). Content-addressed: two replicas holding
        the same token prefix compute the same digest with no
        coordination, which is what makes the digest a fetchable name."""
        bs = self.block_size
        return chunk_digests(key, bs, max_chunks=len(key) // bs)[-1]

    def resident_digests(self, limit: int = 0) -> list:
        """Digests of resident entries, MRU first, host tier before disk
        (the /health residency bootstrap reads this so a router can
        learn what a replica holds without ever having routed traffic
        to it). limit > 0 caps the list — /health payloads must stay
        O(1) however deep the disk tier grows."""
        with self._lock:
            out = []
            seen = set()
            for key in reversed(self._entries):
                d = self.digest_of(key)
                seen.add(d)
                out.append(d)
                if limit and len(out) >= limit:
                    return out
            for key in reversed(self._disk):
                d = self._disk[key][0]
                if d in seen:
                    continue
                out.append(d)
                if limit and len(out) >= limit:
                    break
        return out

    def digest_tier(self, digest: str) -> Optional[str]:
        """The shallowest tier holding the chain tip `digest` names
        ("host" | "disk" | None) — the serving side labels transfer
        bytes and the X-KV-Tier response header off this."""
        with self._lock:
            if digest in self._digest_key:
                return "host"
            if digest in self._disk_digest:
                return "disk"
        return None

    def chain_for_digest(self, digest: str) -> Optional[tuple]:
        """(keys, entries) for the full resident chain ending at the key
        `digest` names — parents first, the scatter/registration order a
        fetching replica needs — or None when the digest is unknown in
        every tier or the chain has a hole (a miss is a 404, never an
        error). Disk-tier members promote into the host tier on the
        way. O(1) digest lookup + O(depth) ancestor walk; touches each
        entry MRU like a hit."""
        bs = self.block_size
        missing: list = []
        with self._lock:
            key = self._digest_key.get(digest)
            if key is None:
                key = self._disk_digest.get(digest)
            if key is None:
                return None
            keys = [key[: (i + 1) * bs] for i in range(len(key) // bs)]
            for k in keys:
                if k in self._entries:
                    continue
                if k in self._disk:
                    missing.append(k)
                else:
                    return None
        if missing and not self._promote_keys(missing):
            return None
        out = []
        with self._lock:
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    return None
                self._entries.move_to_end(k)
                out.append(e)
        return keys, out

    def put_host(self, keys: list, per_block_leaves: list, seq: int) -> int:
        """Insert already-host-resident blocks (a chain fetched over the
        KV fabric, or a peer's proactive POST /kv push): no copier hop —
        the bytes are here. Same LRU/demotion discipline as a landed
        copy, so a fetched chain becomes onward-servable through /kv
        exactly like a locally captured one. Returns entries inserted."""
        with self._lock:
            if self._closed:
                return 0
            for key, leaves in zip(keys, per_block_leaves):
                self._insert_locked(
                    key, _Entry([np.asarray(a) for a in leaves], int(seq))
                )
            self._note_blocks_locked()
            self._note_tiers_locked()
        return len(keys)

    def put_async(self, keys: list, dev_leaves: list, seq: int) -> bool:
        """Hand one gathered batch to the copier. keys[i] is the token
        prefix block i of the batch completes; dev_leaves are the
        STACKED device arrays from gather_shadow_blocks (leaf order =
        jax.tree flatten order of the pool; row i of each leaf is key
        i's block — rows past len(keys) are gather padding). NEVER
        blocks: a full queue marks the batch spill-to-disk (the copier
        lands it straight in tier 2 — a DEMOTION, not a loss), and only
        a doubly-full queue (or no disk tier) drops the batch and
        counts it. The doubled bound keeps the number of gathered
        device arrays held alive by the queue strictly bounded."""
        if not keys:
            return True
        with self._lock:
            if self._closed:
                return False
            to_disk = False
            if len(self._q) >= self.max_pending:
                if self.disk_dir is None or (
                    len(self._q) >= 2 * self.max_pending
                ):
                    self.dropped += len(keys)
                    if self._m_dropped is not None:
                        self._m_dropped.inc(len(keys))
                    return False
                to_disk = True
            self._q.append((list(keys), list(dev_leaves), int(seq), to_disk))
            self._pending.update(keys)
            self._cv.notify_all()
        return True

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Wait for every in-flight copy to land (restore/persist call
        this so the recovery depth is deterministic). True when the
        queue fully drained inside the timeout."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._q or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    def select(self, max_blocks: int) -> tuple:
        """Pick up to `max_blocks` resident entries for a pool restore,
        newest chains first, every selected entry's ancestors included
        (a chain with a hole cannot be registered). Spans the disk
        tier: once the host tier's chains are in, remaining budget
        fills with MRU disk chains (loaded + verified here — a corrupt
        file drops its chain, never the restore). Returns
        (entries, leaf_keys): `entries` is [(key, leaves)] ordered
        parents-before-children (the scatter/registration order),
        `leaf_keys` the maximal keys — one per restored chain tip."""
        if max_blocks <= 0:
            return [], []
        bs = self.block_size
        chosen: dict = {}
        with self._lock:
            for key in reversed(self._entries):  # MRU first
                if key in chosen:
                    continue
                chain = []
                k = key
                while len(k) > 0:
                    if k in chosen:
                        break
                    e = self._entries.get(k)
                    if e is None:
                        chain = None  # hole (demotion should prevent this)
                        break
                    chain.append(k)
                    k = k[:-bs]
                if chain is None:
                    continue
                if len(chosen) + len(chain) > max_blocks:
                    continue  # try a shorter chain further down the LRU
                for k in chain:
                    chosen[k] = self._entries[k]
            # disk tier fills what is left: MRU chunk files, whole
            # chains only, each file verified at load (tier-2 hit)
            if self.disk_dir is not None and len(chosen) < max_blocks:
                for key in list(reversed(self._disk)):
                    if key in chosen or key in self._entries:
                        continue
                    chain = []
                    k = key
                    ok = True
                    while len(k) > 0:
                        if k in chosen:
                            break
                        if k in self._entries:
                            chain.append((k, self._entries[k]))
                        elif k in self._disk:
                            chain.append((k, None))
                        else:
                            ok = False
                            break
                        k = k[:-bs]
                    if not ok or len(chosen) + len(chain) > max_blocks:
                        continue
                    loaded = {}
                    for k2, e in chain:
                        if e is None:
                            e2 = self._disk_load_locked(k2)
                            if e2 is None:
                                ok = False
                                break
                            loaded[k2] = e2
                    if not ok:
                        continue
                    for k2, e in chain:
                        chosen[k2] = e if e is not None else loaded[k2]
            entries = sorted(chosen.items(), key=lambda kv: len(kv[0]))
            selected = set(chosen)
            leaf_keys = [
                k for k in selected
                if not any(
                    c in selected
                    for c in (
                        set(self._children.get(k, ()))
                        | set(self._disk_children.get(k, ()))
                    )
                )
            ]
            self._note_tiers_locked()
        return entries, leaf_keys

    def count_pool_promotion(self, n: int):
        """Count `n` blocks entering tier 0 (scattered into pool HBM by
        a restore / local promotion / fabric import) — the engine calls
        this at its scatter sites; the store itself never touches HBM."""
        if n > 0:
            self.promoted += n
            m = self._m_promotions.get("pool")
            if m is not None:
                m.inc(n)

    # -- tier-2 internals ----------------------------------------------------
    def _disk_path(self, digest: str) -> str:
        return os.path.join(self.disk_dir, _DISK_PREFIX + digest + _DISK_SUFFIX)

    def _promote_keys(self, keys: list) -> bool:
        """Load `keys` from the disk tier and insert them into the host
        tier (tier-2 hit -> tier-1 promotion). False when any key is
        gone or its file fails verification — the caller treats the
        whole lookup as a miss (next tier up: a cold re-prefill).
        Chunk files are read and content-verified in PARALLEL outside
        the lock — a deep chain's promotion latency IS tier 2's whole
        hit cost, and one-np.load-at-a-time under the lock serializes
        it — then inserted parents-first under it (rejection
        bookkeeping stays lock-guarded, exactly as the sequential
        path's)."""
        with self._lock:
            todo = []
            for k in keys:
                if k in self._entries:
                    continue
                ent = self._disk.get(k)
                if ent is None:
                    return False
                todo.append((k, self._disk_path(ent[0])))
        if not todo:
            return True
        bs = self.block_size

        def _read(item):
            k, path = item
            try:
                return k, _read_chunk_file(path, k, bs)
            except Exception as e:  # noqa: BLE001 - judged under the lock
                return k, e

        if len(todo) > 1:
            with ThreadPoolExecutor(
                max_workers=min(8, len(todo))
            ) as ex:
                loaded = list(ex.map(_read, todo))
        else:
            loaded = [_read(todo[0])]
        ok = True
        with self._lock:
            for k, res in loaded:
                if isinstance(res, Exception):
                    if k in self._disk:
                        # a FILE failure (truncated/tampered/stale
                        # format), not a racing LRU eviction: reject —
                        # delete + cascade, count it — into a miss
                        path = self._disk_path(self._disk[k][0])
                        log.warning(
                            "shadow_disk_rejected", error=str(res),
                            path=path,
                        )
                        self.disk_rejected += 1
                        self._disk_evict_subtree_locked(k)
                        self._note_tiers_locked()
                    ok = False
                    continue
                if k in self._entries:
                    continue
                if k not in self._disk:
                    ok = False  # churned out between snapshot and read
                    continue
                self.disk_hits += 1
                if self._m_disk_hits is not None:
                    self._m_disk_hits.inc()
                self.promoted += 1
                m = self._m_promotions.get("host")
                if m is not None:
                    m.inc()
                self._insert_locked(k, res)
            self._note_blocks_locked()
            self._note_tiers_locked()
        return ok

    def _disk_load_locked(self, key: tuple):  # guarded-by: _lock
        """Read + VERIFY one chunk file. A truncated, tampered, or
        wrong-block-size file REJECTS (file deleted, index dropped with
        its disk descendants) into a miss, never wrong KV. Keeps the
        disk copy on success: a later host eviction then skips the
        rewrite."""
        ent = self._disk.get(key)
        if ent is None:
            return None
        digest, _nbytes = ent
        path = self._disk_path(digest)
        try:
            return _read_chunk_file(path, key, self.block_size)
        except Exception as e:  # noqa: BLE001 - a bad file is a MISS
            log.warning("shadow_disk_rejected", error=str(e), path=path)
            self.disk_rejected += 1
            self._disk_evict_subtree_locked(key)
            self._note_tiers_locked()
            return None

    # guarded-by: _lock
    def _disk_write_locked(self, key: tuple, entry: _Entry,
                           digest: str) -> bool:
        """Persist one block as an atomic chunk file (tmp + rename, like
        save()) and index it. False on an I/O failure — the demotion
        becomes a plain drop, never an error."""
        manifest = {
            "version": _DISK_VERSION,
            "block_size": self.block_size,
            "t": [int(t) for t in key],
            "seq": int(entry.seq),
        }
        arrays = {"manifest": np.array(json.dumps(manifest))}
        for j, leaf in enumerate(entry.leaves):
            arrays[f"leaf_{j}"] = np.asarray(leaf)
        path = self._disk_path(digest)
        tmp = os.path.join(
            self.disk_dir, "." + _DISK_PREFIX + digest + ".tmp"
        )
        try:
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            data = buf.getvalue()
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("shadow_disk_write_failed", error=str(e), path=path)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        self._disk_insert_locked(key, digest, len(data))
        return True

    # guarded-by: _lock
    def _disk_insert_locked(self, key: tuple, digest: str,
                            nbytes: int):
        if key in self._disk:
            old = self._disk[key][1]
            self._disk_bytes += nbytes - old
            self._disk[key] = (digest, nbytes)
            self._disk.move_to_end(key)
            return
        self._disk[key] = (digest, nbytes)
        self._disk_digest[digest] = key
        self._disk_bytes += nbytes
        parent = key[: -self.block_size]
        if parent:
            self._disk_children.setdefault(parent, set()).add(key)
        while len(self._disk) > self.max_disk_blocks:
            victim = next(iter(self._disk))
            if victim == key:
                break  # never evict what we just inserted
            self._disk_evict_subtree_locked(victim)

    def _disk_evict_subtree_locked(self, key: tuple):  # guarded-by: _lock
        """Disk-tier eviction cascades through DISK descendants, like
        the host tier's: a disk chain with a missing interior block
        cannot be promoted (host copies of a descendant, if any, stay —
        the host tier keeps its own no-hole invariant independently)."""
        ent = self._disk.pop(key, None)
        if ent is None:
            return
        digest, nbytes = ent
        self._disk_digest.pop(digest, None)
        self._disk_bytes -= nbytes
        parent = key[: -self.block_size]
        sibs = self._disk_children.get(parent)
        if sibs is not None:
            sibs.discard(key)
            if not sibs:
                self._disk_children.pop(parent, None)
        try:
            os.remove(self._disk_path(digest))
        except OSError:
            pass
        for child in list(self._disk_children.get(key, ())):
            self._disk_evict_subtree_locked(child)
        self._disk_children.pop(key, None)

    def _disk_scan_locked(self):  # guarded-by: _lock
        """Rebuild the tier-2 index from `disk_dir` at startup: every
        chunk file whose manifest reproduces its filename digest joins,
        mtime-ordered (oldest = coldest LRU position); invalid files
        and orphaned descendants (parent file missing) are deleted.
        Array payloads are NOT read here — np.load is lazy, so the scan
        is O(files), not O(bytes); full verification happens per load."""
        bs = self.block_size
        found = []
        try:
            names = os.listdir(self.disk_dir)
        except OSError as e:
            log.warning("shadow_disk_scan_failed", error=str(e))
            return
        for name in names:
            if not (name.startswith(_DISK_PREFIX)
                    and name.endswith(_DISK_SUFFIX)):
                continue
            digest = name[len(_DISK_PREFIX):-len(_DISK_SUFFIX)]
            path = os.path.join(self.disk_dir, name)
            try:
                with np.load(path, allow_pickle=False) as z:
                    manifest = json.loads(str(z["manifest"]))
                toks = tuple(int(t) for t in manifest.get("t", ()))
                if (
                    manifest.get("version") != _DISK_VERSION
                    or manifest.get("block_size") != bs
                    or not toks or len(toks) % bs
                    or chunk_digests(
                        toks, bs, max_chunks=len(toks) // bs
                    )[-1] != digest
                ):
                    raise ValueError("manifest fails the content-key check")
                st = os.stat(path)
                found.append((st.st_mtime, toks, digest, st.st_size))
            except Exception as e:  # noqa: BLE001 - a bad file is deleted
                log.warning("shadow_disk_scan_rejected", path=path,
                            error=str(e))
                self.disk_rejected += 1
                try:
                    os.remove(path)
                except OSError:
                    pass
        # orphan filter: a chunk whose parent chunk is missing can never
        # be promoted — delete it instead of carrying dead weight
        keys = {toks for _, toks, _, _ in found}
        kept = []
        for item in sorted(found, key=lambda it: len(it[1])):
            parent = item[1][:-bs]
            if parent and parent not in keys:
                keys.discard(item[1])
                try:
                    os.remove(self._disk_path(item[2]))
                except OSError:
                    pass
                continue
            kept.append(item)
        for _, toks, digest, size in sorted(kept, key=lambda it: it[0]):
            if toks in keys:
                self._disk_insert_locked(toks, digest, int(size))
        if self._disk:
            log.info("shadow_disk_scanned", entries=len(self._disk),
                     bytes=self._disk_bytes, dir=self.disk_dir)

    # -- copier thread -------------------------------------------------------
    def _copier(self):
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed and not self._q:
                    return
                keys, dev_leaves, seq, to_disk = self._q.popleft()
                self._busy = True
            try:
                # the one blocking device->host transfer, strictly off
                # the scheduler thread
                host = [np.asarray(leaf) for leaf in dev_leaves]
                per_block = [
                    [leaf[i] for leaf in host] for i in range(len(keys))
                ]
            except Exception as e:  # noqa: BLE001 - a lost copy is only colder
                log.warning("shadow_copy_failed", error=str(e))
                with self._lock:
                    self._pending.difference_update(keys)
                    self.dropped += len(keys)
                    if self._m_dropped is not None:
                        self._m_dropped.inc(len(keys))
                    self._busy = False
                    self._cv.notify_all()
                continue
            with self._lock:
                for key, leaves in zip(keys, per_block):
                    if to_disk:
                        # backpressure spill: land straight in tier 2
                        # (a DEMOTION — the block stays restorable)
                        if key not in self._entries and key not in self._disk:
                            if self._disk_write_locked(
                                key, _Entry(leaves, seq),
                                self.digest_of(key),
                            ):
                                self.demoted += 1
                                if self._m_demotions is not None:
                                    self._m_demotions.inc()
                    else:
                        self._insert_locked(key, _Entry(leaves, seq))
                self._pending.difference_update(keys)
                self.copied += len(keys)
                if self._m_copies is not None:
                    self._m_copies.inc(len(keys))
                self._note_blocks_locked()
                self._note_tiers_locked()
                self._busy = False
                self._cv.notify_all()

    def _insert_locked(self, key: tuple, entry: _Entry):  # guarded-by: _lock
        if key in self._entries:
            self._host_bytes += entry.nbytes() - self._entries[key].nbytes()
            self._entries[key] = entry
            self._entries.move_to_end(key)
            return
        self._entries[key] = entry
        self._host_bytes += entry.nbytes()
        self._digest_key[self.digest_of(key)] = key
        parent = key[: -self.block_size]
        if parent:
            self._children.setdefault(parent, set()).add(key)
        while len(self._entries) > self.max_blocks:
            victim = next(iter(self._entries))
            if victim == key:
                break  # never evict what we just inserted
            self._evict_subtree_locked(victim)

    def _evict_subtree_locked(self, key: tuple):  # guarded-by: _lock
        """Host-tier LRU eviction cascades through descendants, like the
        block-prefix index's (a chain with a missing interior block can
        never be restored from tier 1 alone — the no-hole invariant
        save()/select() lean on stays per-tier). With a disk tier, the
        whole evicted subtree DEMOTES: each block spills to a chunk
        file (parents first — this recursion's natural order — so a
        crash mid-spill leaves a valid chain prefix on disk, never an
        orphan), and the chain stays promotable. Without one, eviction
        drops, as before."""
        entry = self._entries.get(key)
        if entry is None:
            return
        del self._entries[key]
        self._host_bytes -= entry.nbytes()
        digest = self.digest_of(key)
        self._digest_key.pop(digest, None)
        parent = key[: -self.block_size]
        sibs = self._children.get(parent)
        if sibs is not None:
            sibs.discard(key)
            if not sibs:
                self._children.pop(parent, None)
        self.evicted += 1
        if self.disk_dir is not None:
            if key in self._disk:
                self._disk.move_to_end(key)  # still persisted: no rewrite
            elif self._disk_write_locked(key, entry, digest):
                self.demoted += 1
                if self._m_demotions is not None:
                    self._m_demotions.inc()
        for child in list(self._children.get(key, ())):
            self._evict_subtree_locked(child)
        self._children.pop(key, None)

    def _note_blocks_locked(self):  # guarded-by: _lock
        if self._m_blocks is not None:
            self._m_blocks.set(len(self._entries))

    def _note_tiers_locked(self):  # guarded-by: _lock
        if self._m_tier_entries:
            self._m_tier_entries["host"].set(len(self._entries))
            self._m_tier_entries["disk"].set(len(self._disk))
            self._m_tier_bytes["host"].set(self._host_bytes)
            self._m_tier_bytes["disk"].set(self._disk_bytes)

    def demote_host_tier(self) -> int:
        """Spill every host-tier entry to the disk tier (parents-first —
        the eviction cascade's natural order) and drop it from tier 1:
        the graceful-drain shape. A restart over the same --kv-disk-dir
        then promotes the working set back through tier 2 instead of
        re-prefilling it. No-op (returns 0) without a disk tier; callers
        should flush() first so in-flight copies are included. Returns
        the number of chunk files newly written (entries already
        persisted on disk spill for free)."""
        with self._lock:
            if self.disk_dir is None:
                return 0
            before = self.demoted
            for key in list(self._entries):
                self._evict_subtree_locked(key)
            self._note_blocks_locked()
            self._note_tiers_locked()
            return self.demoted - before

    # -- persistence (graceful drain / --restore-dir) ------------------------
    def save(self, directory: str) -> int:
        """Serialize every HOST-tier entry to `directory`/shadow.npz,
        atomically (tmp + rename): a crash mid-save leaves the previous
        file intact — the on-disk shadow is crash-consistent the same
        way the in-memory one is. The disk tier needs no save — its
        chunk files already are the persisted form. Returns entries
        written."""
        os.makedirs(directory, exist_ok=True)
        bs = self.block_size
        with self._lock:
            ordered = sorted(
                self._entries.items(),
                key=lambda kv: len(kv[0]),
            )
            lru_pos = {k: i for i, k in enumerate(self._entries)}
            snapshot = [
                (k, [np.array(a) for a in e.leaves], e.seq, lru_pos[k])
                for k, e in ordered
            ]
        idx = {k: i for i, (k, _, _, _) in enumerate(snapshot)}
        manifest = {
            "version": _PERSIST_VERSION,
            "block_size": bs,
            "entries": [
                {
                    "p": idx.get(k[:-bs], -1),
                    "t": [int(t) for t in k[-bs:]],
                    "seq": seq,
                    "lru": lru,
                }
                for k, _, seq, lru in snapshot
            ],
        }
        arrays = {"manifest": np.array(json.dumps(manifest))}
        if snapshot:
            n_leaves = len(snapshot[0][1])
            for j in range(n_leaves):
                arrays[f"leaf_{j}"] = np.stack(
                    [leaves[j] for _, leaves, _, _ in snapshot]
                )
        tmp = os.path.join(directory, "." + _PERSIST_NAME + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(directory, _PERSIST_NAME))
        log.info("shadow_saved", entries=len(snapshot), dir=directory)
        return len(snapshot)

    def load(self, directory: str) -> int:
        """Load a persisted shadow (missing/invalid file = start cold,
        never an error: a warm cache is an optimization). Returns
        entries loaded."""
        path = os.path.join(directory, _PERSIST_NAME)
        if not os.path.exists(path):
            return 0
        try:
            with np.load(path, allow_pickle=False) as z:
                manifest = json.loads(str(z["manifest"]))
                if (
                    manifest.get("version") != _PERSIST_VERSION
                    or manifest.get("block_size") != self.block_size
                ):
                    log.warning(
                        "shadow_load_skipped",
                        reason="version/block_size mismatch", path=path,
                    )
                    return 0
                ents = manifest.get("entries", [])
                leaves = []
                j = 0
                while f"leaf_{j}" in z.files:
                    leaves.append(z[f"leaf_{j}"])
                    j += 1
        except Exception as e:  # noqa: BLE001 - cold start beats crashing
            log.warning("shadow_load_failed", error=str(e), path=path)
            return 0
        if not ents or not leaves or any(
            leaf.shape[0] != len(ents) for leaf in leaves
        ):
            return 0
        keys: list = []
        for i, ent in enumerate(ents):
            p = int(ent["p"])
            if p >= i:  # parents-first ordering violated: corrupt
                return 0
            parent_key = keys[p] if p >= 0 else ()
            keys.append(parent_key + tuple(int(t) for t in ent["t"]))
        order = sorted(range(len(ents)), key=lambda i: ents[i]["lru"])
        with self._lock:
            for i in order:
                self._insert_locked(
                    keys[i],
                    _Entry(
                        [leaf[i] for leaf in leaves], int(ents[i]["seq"])
                    ),
                )
            self._note_blocks_locked()
            self._note_tiers_locked()
            n = len(self._entries)
        log.info("shadow_loaded", entries=n, dir=directory)
        return n

    # -- shared surface ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._entries),
                "block_size": self.block_size,
                "max_blocks": self.max_blocks,
                "pending": len(self._pending),
                "copied": self.copied,
                "dropped": self.dropped,
                "evicted": self.evicted,
                "host_bytes": self._host_bytes,
                "disk_dir": self.disk_dir,
                "disk_blocks": len(self._disk),
                "max_disk_blocks": (
                    self.max_disk_blocks if self.disk_dir else 0
                ),
                "disk_bytes": self._disk_bytes,
                "demoted": self.demoted,
                "promoted": self.promoted,
                "disk_hits": self.disk_hits,
                "disk_rejected": self.disk_rejected,
            }

    def clear(self, disk: bool = False):
        """Drop the host tier (and, with disk=True, the disk tier —
        files included). The default keeps tier 2: a cleared host tier
        (e.g. a failed restore's reset) can still promote persisted
        chains back."""
        with self._lock:
            self._entries.clear()
            self._children.clear()
            self._digest_key.clear()
            self._host_bytes = 0
            if disk and self.disk_dir is not None:
                for key in list(self._disk):
                    self._disk_evict_subtree_locked(key)
            self._note_blocks_locked()
            self._note_tiers_locked()

    def close(self):
        with self._lock:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
