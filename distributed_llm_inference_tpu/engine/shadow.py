"""Host-side crash-consistent KV shadow store: warm recovery for the
paged fleet.

Every recovery path this repro grew in PRs 5-8 — supervisor restarts,
poison quarantine, graceful drain, router failover, rolling restarts —
comes back COLD: the rebuilt pool holds no KV, so each salvaged request
re-prefills its whole prompt and a drained replica respawns with an
empty block-prefix cache. At production scale that is minutes of
recomputed prefill per incident (the reference's recovery story is
"restart the Colab"; preemptible TPU capacity makes restart cost a
first-order serving metric — see PAPERS.md).

Paged KV blocks are append-only and immutable once FILLED (decode and
tail-prefill writes only ever land at later positions; the frozen-row
overrun clamp only touches a request's own partial last block or the
trash block — engine/paged.py), so the shadow works at block
granularity:

  * CAPTURE (worker thread, async): when a block fills — a whole-prefill
    admission lands, a chunked-prefill launch crosses a block boundary,
    or a fetched decode chunk shows a row crossed one — the engine
    dispatches a small read-only device gather of the filled blocks
    (engine/paged.gather_shadow_blocks, enqueued in launch order AFTER
    the filling program, so device execution order guarantees the
    gathered bytes are the block's final content) and hands the device
    arrays to THIS module's copier thread. The device->host transfer
    (the only blocking step) happens entirely off the scheduler loop;
    the pending queue is bounded and overflow DROPS the batch (a lost
    shadow block costs a colder recovery, never correctness), so the
    zero-host-sync launch invariants survive untouched — this module is
    pinned decode-UNREACHABLE in the test_analysis.py callgraph fixture
    exactly like utils/faults.py.
  * KEYS are content: a block's key is the full token prefix it
    completes (a tuple of ids, length a multiple of block_size). A
    block's KV is a pure function of the token prefix under
    teacher-forcing, so a content-keyed entry can never be stale and
    restoring it into ANY rebuilt pool is bit-exact — the same
    immutability argument engine/block_prefix.py makes for live block
    sharing, extended across a pool rebuild. Entries are stamped with
    the engine's mutation seq at capture (observability + persist
    versioning; consistency never depends on the stamp).
  * RESTORE (supervisor restart): the engine flushes pending copies,
    selects as many MRU chains as the fresh pool can hold, scatters
    them back in ONE launch (engine/paged.restore_shadow_blocks), and
    registers the chains into the BlockPrefixIndex — salvaged requests
    then re-admit through the ordinary block-prefix hit machinery and
    re-prefill ONLY the partial tail block.
  * PERSIST (graceful drain): save()/load() serialize the store to an
    atomic npz under --restore-dir, so a rolling restart cycles the
    replica back in with a WARM prefix cache.
  * WIRE (the cross-replica KV fabric, serving/kv_fabric.py): entries
    are additionally indexed by their parent-chained chunk digest
    (block_prefix.chunk_digests over the key), so a peer replica can
    fetch a whole chain by digest through GET /kv/{digest} —
    chain_for_digest / resident_digests / put_host are that surface.
    Content keying is what makes this sound over the wire: the digest
    names the token prefix, the fetcher recomputes it from the payload's
    tokens, and KV is a pure function of the prefix — so a fetched chain
    is bit-identical to one computed locally, or it is rejected.

What is deliberately NOT shadowed: partial tail blocks (mutable until
they fill), slot/sampling state (host-reconstructable from the salvage
record), constraint FSM rows (re-derived by advancing the DFA over
salvaged tokens), the trash block, and dense-fleet caches (no block
immutability to lean on).
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Optional

import numpy as np

from ..utils.logging import get_logger
from .block_prefix import chunk_digests

log = get_logger("shadow")

_PERSIST_VERSION = 1
_PERSIST_NAME = "shadow.npz"


class _Entry:
    __slots__ = ("leaves", "seq")

    def __init__(self, leaves, seq):
        self.leaves = leaves  # list of per-leaf np arrays (one block each)
        self.seq = seq


class ShadowStore:
    """Bounded LRU of host-side shadowed KV blocks, content-keyed by the
    token prefix each block completes.

    Single-writer discipline mirrors the allocator's: put_async /
    select / drop_pending run on the continuous engine's worker thread,
    the copier thread only consumes its own queue, and the lock exists
    for stats()/save() readers on other threads.

    registry (utils/metrics.MetricsRegistry, optional):
    `dli_shadow_blocks` (resident host-shadowed blocks),
    `dli_shadow_copies_total` (blocks copied device->host),
    `dli_shadow_dropped_total` (blocks dropped: queue backpressure or a
    failed transfer) — families pre-registered in engine/engine.py.
    """

    def __init__(self, block_size: int, max_blocks: int = 256,
                 max_pending: int = 32, registry=None):
        if block_size < 1:
            raise ValueError("shadow store needs block_size >= 1")
        self.block_size = int(block_size)
        self.max_blocks = max(1, int(max_blocks))
        self.max_pending = max(1, int(max_pending))
        # guarded-by: _lock
        self._entries: "collections.OrderedDict[tuple, _Entry]" = (
            collections.OrderedDict()
        )
        self._children: dict = {}  # key -> set of child keys; guarded-by: _lock
        # chunk-digest index over the resident keys (the same parent-
        # chained digests engine/block_prefix.chunk_digests exports for
        # router affinity), so the KV fabric's /kv lookups are O(1)
        # instead of a full-store digest sweep per request
        self._digest_key: dict = {}  # digest hex -> key; guarded-by: _lock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # copier queue: (keys, dev_leaves, seq) batches; keys in
        # _pending are visible to has() so the worker never re-captures
        # a block whose copy is still in flight
        self._q: collections.deque = collections.deque()
        self._pending: set = set()
        self._busy = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self.copied = 0
        self.dropped = 0
        self.evicted = 0
        self._m_blocks = self._m_copies = self._m_dropped = None
        if registry is not None:
            self._m_blocks = registry.gauge(
                "dli_shadow_blocks",
                "host-shadowed paged-KV blocks resident for warm recovery",
            ).labels()
            self._m_copies = registry.counter(
                "dli_shadow_copies_total",
                "paged-KV blocks copied device->host into the shadow store",
            ).labels()
            self._m_dropped = registry.counter(
                "dli_shadow_dropped_total",
                "shadow blocks dropped (copier backpressure or a failed "
                "device->host transfer)",
            ).labels()
        self._thread = threading.Thread(
            target=self._copier, daemon=True, name="shadow-copier"
        )
        self._thread.start()

    # -- worker-thread surface ----------------------------------------------
    def has(self, key: tuple) -> bool:
        """True when `key` is resident OR its copy is already in flight."""
        with self._lock:
            return key in self._entries or key in self._pending

    def has_resident(self, key: tuple) -> bool:
        """True only when `key`'s copy has LANDED (restorable right now —
        an in-flight copy is not; preemption's swap path flushes first)."""
        with self._lock:
            return key in self._entries

    def entries_for(self, keys: list) -> Optional[list]:
        """The resident entries for `keys` in order, or None when ANY is
        missing (a targeted restore needs the whole contiguous run — a
        chain with a hole cannot be registered). Touches each entry MRU,
        like a hit."""
        out = []
        with self._lock:
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    return None
                self._entries.move_to_end(k)
                out.append(e)
        return out

    # -- chunk-digest surface (the KV fabric, serving/kv_fabric.py) ----------
    def digest_of(self, key: tuple) -> str:
        """The parent-chained chunk digest covering the whole of `key`
        (engine/block_prefix.chunk_digests — the router tier's affinity
        and residency currency). Content-addressed: two replicas holding
        the same token prefix compute the same digest with no
        coordination, which is what makes the digest a fetchable name."""
        bs = self.block_size
        return chunk_digests(key, bs, max_chunks=len(key) // bs)[-1]

    def resident_digests(self, limit: int = 0) -> list:
        """Digests of resident entries, MRU first (the /health residency
        bootstrap reads this so a router can learn what a replica holds
        without ever having routed traffic to it). limit > 0 caps the
        list — /health must stay cheap on a large store."""
        with self._lock:
            out = []
            for key in reversed(self._entries):
                out.append(self.digest_of(key))
                if limit and len(out) >= limit:
                    break
        return out

    def chain_for_digest(self, digest: str) -> Optional[tuple]:
        """(keys, entries) for the full resident chain ending at the key
        `digest` names — parents first, the scatter/registration order a
        fetching replica needs — or None when the digest is unknown or
        the chain has a hole (cascade eviction should prevent holes; a
        miss is a 404, never an error). O(1) digest lookup + O(depth)
        ancestor walk; touches each entry MRU like a hit."""
        bs = self.block_size
        with self._lock:
            key = self._digest_key.get(digest)
            if key is None:
                return None
            keys = [key[: (i + 1) * bs] for i in range(len(key) // bs)]
            out = []
            for k in keys:
                e = self._entries.get(k)
                if e is None:
                    return None
                self._entries.move_to_end(k)
                out.append(e)
        return keys, out

    def put_host(self, keys: list, per_block_leaves: list, seq: int) -> int:
        """Insert already-host-resident blocks (a chain fetched over the
        KV fabric): no copier hop — the bytes are here. Same LRU/cascade
        discipline as a landed copy, so a fetched chain becomes onward-
        servable through /kv exactly like a locally captured one.
        Returns entries inserted."""
        with self._lock:
            if self._closed:
                return 0
            for key, leaves in zip(keys, per_block_leaves):
                self._insert_locked(
                    key, _Entry([np.asarray(a) for a in leaves], int(seq))
                )
            self._note_blocks_locked()
        return len(keys)

    def put_async(self, keys: list, dev_leaves: list, seq: int) -> bool:
        """Hand one gathered batch to the copier. keys[i] is the token
        prefix block i of the batch completes; dev_leaves are the
        STACKED device arrays from gather_shadow_blocks (leaf order =
        jax.tree flatten order of the pool; row i of each leaf is key
        i's block — rows past len(keys) are gather padding). NEVER
        blocks: a full queue drops the batch and counts it."""
        if not keys:
            return True
        with self._lock:
            if self._closed:
                return False
            if len(self._q) >= self.max_pending:
                self.dropped += len(keys)
                if self._m_dropped is not None:
                    self._m_dropped.inc(len(keys))
                return False
            self._q.append((list(keys), list(dev_leaves), int(seq)))
            self._pending.update(keys)
            self._cv.notify_all()
        return True

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Wait for every in-flight copy to land (restore/persist call
        this so the recovery depth is deterministic). True when the
        queue fully drained inside the timeout."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._q or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    def select(self, max_blocks: int) -> tuple:
        """Pick up to `max_blocks` resident entries for a pool restore,
        newest chains first, every selected entry's ancestors included
        (a chain with a hole cannot be registered). Returns
        (entries, leaf_keys): `entries` is [(key, leaves)] ordered
        parents-before-children (the scatter/registration order),
        `leaf_keys` the maximal keys — one per restored chain tip."""
        if max_blocks <= 0:
            return [], []
        bs = self.block_size
        chosen: dict = {}
        with self._lock:
            for key in reversed(self._entries):  # MRU first
                if key in chosen:
                    continue
                chain = []
                k = key
                while len(k) > 0:
                    if k in chosen:
                        break
                    e = self._entries.get(k)
                    if e is None:
                        chain = None  # hole (cascade should prevent this)
                        break
                    chain.append(k)
                    k = k[:-bs]
                if chain is None:
                    continue
                if len(chosen) + len(chain) > max_blocks:
                    continue  # try a shorter chain further down the LRU
                for k in chain:
                    chosen[k] = self._entries[k]
            entries = sorted(chosen.items(), key=lambda kv: len(kv[0]))
            selected = set(chosen)
            leaf_keys = [
                k for k in selected
                if not any(
                    c in selected for c in self._children.get(k, ())
                )
            ]
        return entries, leaf_keys

    # -- copier thread -------------------------------------------------------
    def _copier(self):
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed and not self._q:
                    return
                keys, dev_leaves, seq = self._q.popleft()
                self._busy = True
            try:
                # the one blocking device->host transfer, strictly off
                # the scheduler thread
                host = [np.asarray(leaf) for leaf in dev_leaves]
                per_block = [
                    [leaf[i] for leaf in host] for i in range(len(keys))
                ]
            except Exception as e:  # noqa: BLE001 - a lost copy is only colder
                log.warning("shadow_copy_failed", error=str(e))
                with self._lock:
                    self._pending.difference_update(keys)
                    self.dropped += len(keys)
                    if self._m_dropped is not None:
                        self._m_dropped.inc(len(keys))
                    self._busy = False
                    self._cv.notify_all()
                continue
            with self._lock:
                for key, leaves in zip(keys, per_block):
                    self._insert_locked(key, _Entry(leaves, seq))
                self._pending.difference_update(keys)
                self.copied += len(keys)
                if self._m_copies is not None:
                    self._m_copies.inc(len(keys))
                self._note_blocks_locked()
                self._busy = False
                self._cv.notify_all()

    def _insert_locked(self, key: tuple, entry: _Entry):  # guarded-by: _lock
        if key in self._entries:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            return
        self._entries[key] = entry
        self._digest_key[self.digest_of(key)] = key
        parent = key[: -self.block_size]
        if parent:
            self._children.setdefault(parent, set()).add(key)
        while len(self._entries) > self.max_blocks:
            victim = next(iter(self._entries))
            if victim == key:
                break  # never evict what we just inserted
            self._evict_subtree_locked(victim)

    def _evict_subtree_locked(self, key: tuple):  # guarded-by: _lock
        """LRU eviction cascades through descendants, like the
        block-prefix index's: a chain with a missing interior block can
        never be restored, so children of an evicted block are dead
        weight."""
        if key not in self._entries:
            return
        del self._entries[key]
        self._digest_key.pop(self.digest_of(key), None)
        parent = key[: -self.block_size]
        sibs = self._children.get(parent)
        if sibs is not None:
            sibs.discard(key)
            if not sibs:
                self._children.pop(parent, None)
        self.evicted += 1
        for child in list(self._children.get(key, ())):
            self._evict_subtree_locked(child)
        self._children.pop(key, None)

    def _note_blocks_locked(self):  # guarded-by: _lock
        if self._m_blocks is not None:
            self._m_blocks.set(len(self._entries))

    # -- persistence (graceful drain / --restore-dir) ------------------------
    def save(self, directory: str) -> int:
        """Serialize every resident entry to `directory`/shadow.npz,
        atomically (tmp + rename): a crash mid-save leaves the previous
        file intact — the on-disk shadow is crash-consistent the same
        way the in-memory one is. Returns entries written."""
        os.makedirs(directory, exist_ok=True)
        bs = self.block_size
        with self._lock:
            ordered = sorted(
                self._entries.items(),
                key=lambda kv: len(kv[0]),
            )
            lru_pos = {k: i for i, k in enumerate(self._entries)}
            snapshot = [
                (k, [np.array(a) for a in e.leaves], e.seq, lru_pos[k])
                for k, e in ordered
            ]
        idx = {k: i for i, (k, _, _, _) in enumerate(snapshot)}
        manifest = {
            "version": _PERSIST_VERSION,
            "block_size": bs,
            "entries": [
                {
                    "p": idx.get(k[:-bs], -1),
                    "t": [int(t) for t in k[-bs:]],
                    "seq": seq,
                    "lru": lru,
                }
                for k, _, seq, lru in snapshot
            ],
        }
        arrays = {"manifest": np.array(json.dumps(manifest))}
        if snapshot:
            n_leaves = len(snapshot[0][1])
            for j in range(n_leaves):
                arrays[f"leaf_{j}"] = np.stack(
                    [leaves[j] for _, leaves, _, _ in snapshot]
                )
        tmp = os.path.join(directory, "." + _PERSIST_NAME + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(directory, _PERSIST_NAME))
        log.info("shadow_saved", entries=len(snapshot), dir=directory)
        return len(snapshot)

    def load(self, directory: str) -> int:
        """Load a persisted shadow (missing/invalid file = start cold,
        never an error: a warm cache is an optimization). Returns
        entries loaded."""
        path = os.path.join(directory, _PERSIST_NAME)
        if not os.path.exists(path):
            return 0
        try:
            with np.load(path, allow_pickle=False) as z:
                manifest = json.loads(str(z["manifest"]))
                if (
                    manifest.get("version") != _PERSIST_VERSION
                    or manifest.get("block_size") != self.block_size
                ):
                    log.warning(
                        "shadow_load_skipped",
                        reason="version/block_size mismatch", path=path,
                    )
                    return 0
                ents = manifest.get("entries", [])
                leaves = []
                j = 0
                while f"leaf_{j}" in z.files:
                    leaves.append(z[f"leaf_{j}"])
                    j += 1
        except Exception as e:  # noqa: BLE001 - cold start beats crashing
            log.warning("shadow_load_failed", error=str(e), path=path)
            return 0
        if not ents or not leaves or any(
            leaf.shape[0] != len(ents) for leaf in leaves
        ):
            return 0
        keys: list = []
        for i, ent in enumerate(ents):
            p = int(ent["p"])
            if p >= i:  # parents-first ordering violated: corrupt
                return 0
            parent_key = keys[p] if p >= 0 else ()
            keys.append(parent_key + tuple(int(t) for t in ent["t"]))
        order = sorted(range(len(ents)), key=lambda i: ents[i]["lru"])
        with self._lock:
            for i in order:
                self._insert_locked(
                    keys[i],
                    _Entry(
                        [leaf[i] for leaf in leaves], int(ents[i]["seq"])
                    ),
                )
            self._note_blocks_locked()
            n = len(self._entries)
        log.info("shadow_loaded", entries=n, dir=directory)
        return n

    # -- shared surface ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._entries),
                "block_size": self.block_size,
                "max_blocks": self.max_blocks,
                "pending": len(self._pending),
                "copied": self.copied,
                "dropped": self.dropped,
                "evicted": self.evicted,
            }

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._children.clear()
            self._digest_key.clear()
            self._note_blocks_locked()

    def close(self):
        with self._lock:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
