"""Paged runtime LoRA adapter pool: many adapters, one resident base.

Merge-at-load (models/lora.merge_lora) bakes ONE adapter into the dense
weights — the single-adapter fast path. This module is the multi-tenant
shape: the base model's layer stack grows fourteen `lora_{leaf}_{a,b}`
leaves, each a PAGED stack of low-rank factors —

    lora_wq_a [L, P, D, r]     lora_wq_b [L, P, r, H*Dh]   (etc.)

with P = adapter_slots + 1 device pages. Page 0 is the RESERVED base
page: all-zero, never written, never evicted — a row selecting page 0
computes the bit-identical base output (the delta is skipped by a traced
select, not added as zero, so not even -0.0 can flip). Registered
adapters (models/lora.load_lora_stacked: rank-padded, scale folded into
b) are written into pages 1..P-1 by donation-aliased jitted updates, so
a load is two HBM writes per leaf and zero recompiles: the leaves ride
`params["layers"]`, the pytree structure is fixed at engine build, and
every launch program (decode chunks, ragged admission, the mixed
scheduler step, the pp shard_map twins) takes the per-row page ids as a
TRACED operand — one compiled program serves any adapter mix.

Pool discipline mirrors engine/paged.BlockAllocator: pages are
refcounted holders (one per live decode slot using the adapter), a
refcount-0 resident adapter parks in an LRU instead of being dropped
(the next request for it costs zero loads), and a new registration under
pressure evicts the LRU victim — never a referenced page. acquire() with
every page referenced returns None, the same backpressure contract as
block exhaustion (the admission requeues at the front and retries after
a release).

Threading (same split as BlockAllocator / BlockPrefixIndex): acquire /
release / reset_refs mutate only on the continuous engine's worker
thread; the lock exists because stats()//metrics render from serving
threads. register() is serving-startup / admin-path territory and takes
the lock for the registry map.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..utils.logging import get_logger

log = get_logger("adapters")

# stacked-leaf name -> (in_dim, out_dim) factory; mirrors the mm sites in
# models/llama.decoder_layer (stacked leaves hold W.T [in, out])
_ATTN_LEAVES = ("wq", "wk", "wv", "wo")
_MLP_LEAVES = ("w_gate", "w_up", "w_down")


def adapter_leaf_dims(cfg: ModelConfig) -> dict:
    """{base leaf: (in_dim, out_dim)} of every projection the adapter
    delta can target on this config. MoE configs carry no dense mlp
    leaves, so mlp-targeting adapters are rejected at registration."""
    D, Dh = cfg.dim, cfg.head_dim
    H, KV, F = cfg.n_heads, cfg.n_kv_heads, cfg.ffn_dim
    dims = {
        "wq": (D, H * Dh),
        "wk": (D, KV * Dh),
        "wv": (D, KV * Dh),
        "wo": (H * Dh, D),
    }
    if not cfg.n_experts:
        dims.update({
            "w_gate": (D, F),
            "w_up": (D, F),
            "w_down": (F, D),
        })
    return dims


def install_adapter_leaves(cfg: ModelConfig, params: dict, slots: int,
                           rank: int) -> dict:
    """Add the zeroed paged lora_* leaves to params["layers"] (page 0 =
    the base page). Runs at engine build, AFTER quantization (the lora
    leaves stay dense — ops/quant only touches _QUANT_KEYS) and BEFORE
    sharding, so pp/tp meshes shard them through the ordinary
    parallel/partition specs."""
    if cfg.arch != "llama":
        raise ValueError(
            f"runtime adapters are wired for the llama family; got "
            f"{cfg.arch!r}"
        )
    if slots < 1:
        raise ValueError(f"adapter_slots must be >= 1, got {slots}")
    if rank < 1:
        raise ValueError(f"adapter_rank must be >= 1, got {rank}")
    L, P = cfg.n_layers, slots + 1
    dt = cfg.jnp_dtype
    layers = dict(params["layers"])
    for leaf, (d_in, d_out) in adapter_leaf_dims(cfg).items():
        if leaf not in layers:
            continue  # defensive: only shadow projections that exist
        layers[f"lora_{leaf}_a"] = jnp.zeros((L, P, d_in, rank), dt)
        layers[f"lora_{leaf}_b"] = jnp.zeros((L, P, rank, d_out), dt)
    out = dict(params)
    out["layers"] = layers
    return out


@partial(jax.jit, donate_argnums=(0,))
def _page_write(buf, page, val):
    """One donation-aliased page write: buf [L, P, ...] <- val [L, ...]
    at page `page` (traced int32 — no recompile across pages)."""
    return buf.at[:, page].set(val)


class AdapterPool:
    """Refcounted LRU pool of device-resident LoRA adapters.

    backend must expose write_adapter_page(page, updates) (engine/
    engine.SingleDeviceBackend and parallel/pipeline.PipelineBackend
    do); updates = {base leaf: (a [L, in, r], b [L, r, out]) host
    arrays}.

    registry (utils/metrics.MetricsRegistry, optional): the
    dli_adapter_* families pre-registered in engine/engine.py.
    """

    def __init__(self, cfg: ModelConfig, backend: Any, slots: int,
                 rank: int, registry=None,
                 merged_source: Optional[str] = None):
        self.cfg = cfg
        self.backend = backend
        self.slots = int(slots)
        self.rank = int(rank)
        # --lora merge-at-load source, if any: registering the SAME
        # adapter as a runtime adapter would apply its delta twice
        self.merged_source = (
            os.path.abspath(merged_source) if merged_source else None
        )
        self._dims = adapter_leaf_dims(cfg)
        # name -> host stacked tensors ({leaf: (a, b)} np.float32)
        self._registry: dict = {}          # guarded-by: _lock
        self._page_of: dict = {}           # name -> page (resident)
        self._name_of: dict = {}           # page -> name
        self._refs: dict = {}              # page -> holder count
        self._free = list(range(1, self.slots + 1))
        # refcount-0 residents, insertion order == LRU order
        self._lru: dict = {}               # name -> page (ordered)
        self._lock = threading.Lock()
        self.loads = 0
        self.evictions = 0
        self.swaps = 0
        self._m_resident = self._m_bytes = None
        self._m_loads = self._m_evictions = self._m_swaps = None
        if registry is not None:
            self._m_resident = registry.gauge(
                "dli_adapter_pool_resident",
                "adapters resident in device pool pages (referenced + LRU)",
            ).labels()
            self._m_bytes = registry.gauge(
                "dli_adapter_pool_bytes",
                "HBM bytes reserved by the paged adapter leaves (all "
                "pages, base page included)",
            ).labels()
            self._m_loads = registry.counter(
                "dli_adapter_loads_total",
                "adapter page writes into the device pool",
            ).labels()
            self._m_evictions = registry.counter(
                "dli_adapter_evictions_total",
                "resident adapters dropped from their page (LRU "
                "reclaim; referenced pages are never evicted)",
            ).labels()
            self._m_swaps = registry.counter(
                "dli_adapter_swaps_total",
                "page loads that displaced another adapter (evict + "
                "write on one page)",
            ).labels()
            self._m_bytes.set(self.pool_bytes)

    # -- sizing --------------------------------------------------------------
    @property
    def pool_bytes(self) -> int:
        """Reserved HBM of the paged lora leaves (fixed at install)."""
        per_page = sum(
            (d_in * self.rank + self.rank * d_out) * self.cfg.n_layers
            for d_in, d_out in self._dims.values()
        )
        itemsize = jnp.dtype(self.cfg.jnp_dtype).itemsize
        return per_page * (self.slots + 1) * itemsize

    @property
    def total(self) -> int:
        return self.slots

    @property
    def free(self) -> int:
        """Pages acquirable RIGHT NOW without backpressure: never-
        written free pages plus refcount-0 LRU residents."""
        with self._lock:
            return len(self._free) + len(self._lru)

    # -- registration (serving startup / admin path) -------------------------
    def register(self, name: str, source) -> None:
        """Register `name` -> host adapter tensors. `source` is a PEFT
        adapter directory path (models/lora.load_lora_stacked) or a
        preloaded {leaf: (a, b)} dict (tests / programmatic callers).
        Rejects adapters targeting projections this config has no lora
        leaves for (MoE mlp), rank overflow (inside load_lora_stacked),
        empty/reserved names, and double registration."""
        if not name or not isinstance(name, str):
            raise ValueError("adapter name must be a non-empty string")
        if name == self.cfg.name:
            raise ValueError(
                f"adapter name {name!r} collides with the base model name "
                f"— `model: {name!r}` must keep meaning the base"
            )
        if isinstance(source, str):
            if (self.merged_source is not None
                    and os.path.abspath(source) == self.merged_source):
                raise ValueError(
                    f"adapter {name!r} points at {source!r}, which is "
                    f"already merged into the base weights (--lora "
                    f"merge-at-load, the single-adapter fast path); its "
                    f"output IS the base output — registering it again "
                    f"would apply the delta twice"
                )
            from ..models.lora import load_lora_stacked

            tensors = load_lora_stacked(self.cfg, source, self.rank)
        else:
            tensors = dict(source)
        bad = sorted(set(tensors) - set(self._dims))
        if bad:
            raise ValueError(
                f"adapter {name!r} targets projections with no adapter "
                f"leaves on this config: {bad} (MoE configs carry "
                f"attention adapters only)"
            )
        L = self.cfg.n_layers
        for leaf, (a, b) in tensors.items():
            d_in, d_out = self._dims[leaf]
            if a.shape != (L, d_in, self.rank) or (
                b.shape != (L, self.rank, d_out)
            ):
                raise ValueError(
                    f"adapter {name!r} {leaf}: stacked shapes "
                    f"{a.shape}/{b.shape} do not match "
                    f"[L={L}, {d_in}|{d_out}, rank={self.rank}]"
                )
        with self._lock:
            if name in self._registry:
                raise ValueError(f"adapter {name!r} is already registered")
            self._registry[name] = tensors
        log.info("adapter_registered", name=name,
                 leaves=sorted(tensors))

    def names(self) -> list:
        with self._lock:
            return sorted(self._registry)

    def is_registered(self, name: str) -> bool:
        with self._lock:
            return name in self._registry

    # -- page lifecycle (worker thread) --------------------------------------
    def acquire(self, name: str) -> Optional[int]:
        """One holder on `name`'s device page, loading/evicting as
        needed. Returns the page id, or None when every page is
        referenced (the caller backpressures exactly like block
        exhaustion). KeyError for unregistered names — the serving edge
        400s those before they reach admission."""
        with self._lock:
            if name not in self._registry:
                raise KeyError(f"unknown adapter {name!r}")
            page = self._page_of.get(name)
            if page is not None:
                self._refs[page] = self._refs.get(page, 0) + 1
                self._lru.pop(name, None)  # referenced: out of the LRU
                return page
            if self._free:
                page = self._free.pop()
                swapped = False
            elif self._lru:
                # evict the LRU refcount-0 resident; referenced pages
                # are untouchable (the eviction-under-refs contract)
                victim, page = next(iter(self._lru.items()))
                self._lru.pop(victim)
                self._page_of.pop(victim, None)
                self._name_of.pop(page, None)
                self.evictions += 1
                swapped = True
            else:
                return None  # every page referenced: backpressure
            tensors = self._registry[name]
        # the device write happens OUTSIDE the lock: it is worker-thread
        # serialized anyway, and a multi-MB host->HBM copy must not
        # block a /metrics render
        updates = {
            leaf: (a, b) for leaf, (a, b) in tensors.items()
        }
        self.backend.write_adapter_page(page, updates)
        with self._lock:
            self._page_of[name] = page
            self._name_of[page] = name
            self._refs[page] = 1
            self.loads += 1
            if swapped:
                self.swaps += 1
            n_resident = len(self._page_of)
        if self._m_loads is not None:
            self._m_loads.inc()
            if swapped:
                self._m_swaps.inc()
                self._m_evictions.inc()
            self._m_resident.set(n_resident)
        log.info("adapter_loaded", name=name, page=page, swapped=swapped)
        return page

    def release(self, name: str) -> None:
        """Drop one holder; at refcount 0 the adapter PARKS in the LRU
        (still resident — the next acquire is free) instead of freeing
        its page."""
        with self._lock:
            page = self._page_of.get(name)
            if page is None:
                return
            refs = self._refs.get(page, 0) - 1
            if refs < 0:
                # over-release is an accounting bug — surface loudly,
                # then clamp so the pool keeps serving
                log.error("adapter_over_release", name=name, page=page)
                refs = 0
            self._refs[page] = refs
            if refs == 0:
                self._lru[name] = page

    def reset_refs(self) -> None:
        """Crash-recovery fleet rebuild: every live holder died with the
        fleet (engine/continuous._release_fleet_resources discipline);
        re-admissions re-acquire. Device page CONTENT survives — the
        leaves live in params, which no crashed launch donated — so the
        residents all park in the LRU and recovered requests reload
        nothing."""
        with self._lock:
            for name, page in self._page_of.items():
                self._refs[page] = 0
                self._lru.setdefault(name, page)

    def referenced(self) -> int:
        """Pages with live holders (the post-drain `free == total`
        hygiene check is `referenced() == 0`)."""
        with self._lock:
            return sum(1 for r in self._refs.values() if r > 0)

    def page_name(self, page: int) -> Optional[str]:
        with self._lock:
            return self._name_of.get(page)

    def stats(self) -> dict:
        with self._lock:
            return {
                "registered": len(self._registry),
                "resident": len(self._page_of),
                "referenced": sum(1 for r in self._refs.values() if r > 0),
                "free": len(self._free) + len(self._lru),
                "total": self.slots,
                "loads": self.loads,
                "evictions": self.evictions,
                "swaps": self.swaps,
                "pool_bytes": self.pool_bytes,
            }


def attach_adapter_pool(engine, slots: int, rank: int) -> AdapterPool:
    """Install the paged lora leaves into a built engine's single-device
    backend and hang an AdapterPool off it (engine.adapters). The
    create_engine path installs the leaves BEFORE sharding instead
    (runtime.create_backend), so this helper is for directly-constructed
    engines — tests and the analysis tiny engines."""
    be = engine.backend
    be.params = install_adapter_leaves(engine.cfg, be.params, slots, rank)
    engine.adapters = AdapterPool(
        engine.cfg, be, slots, rank, registry=engine.metrics
    )
    return engine.adapters
