"""Block-level prefix sharing for the paged fleet: a chunk-hash index
over REFCOUNTED pool blocks (vLLM-style prefix caching).

The snapshot path (engine/prefix.py) serves a shared prompt prefix by
paying for it twice in HBM: a dense snapshot at store time, a splice into
the dense scratch at hit time, and then a full scatter of EVERY block
into the pool. Here the pool itself is the cache: once a request's
prefill scatters a FULL prompt block into the pool, that block's content
is immutable (decode and tail-prefill writes only ever land at positions
>= the prompt's block-floored shared depth — see ARCHITECTURE.md "Block
sharing"), so a later request whose prompt starts with the same tokens
maps the same physical block straight into its block table. Zero splice,
zero per-hit copy of the shared head; only the tail past the deepest
shared full block is prefilled into fresh private blocks (the partial
last block is never shared — its tokens are recomputed into the
request's own block, the "tail copy-out" rule).

Index structure: one entry per cached block, keyed by
(parent physical block id, this block's token chunk). A chain is a walk
from the root: key_0 = (ROOT, ids[:bs]) -> block b0, key_1 = (b0,
ids[bs:2bs]) -> b1, ... Keying on the PARENT BLOCK ID instead of a
rolling content hash makes matches exact (dict equality over the real
tokens — no hash-collision wrong-KV hazard) while keeping entries O(bs)
each; stale child entries cannot survive a parent's eviction because
eviction cascades through the subtree (see evict()).

Lifecycle (refcounts live in paged.BlockAllocator):
  * register() after a successful admission increfs each newly cached
    block — the index is a first-class holder, so completed requests'
    prefix blocks stay resident (decref'd to 1, not freed).
  * lookup() maps a hit's shared blocks into the new request's table;
    the ENGINE increfs them (one holder per live table).
  * evict() reclaims LRU chains whose blocks have refcount 1 — held by
    nobody but this index. A chain mapped by any live table is never
    reclaimed; eviction cascades to the chain's descendants (which are
    provably also unreferenced: a live request mapping a child block
    always holds the parent too).

Single-owner discipline: lookup/mark/register/evict run only on the
continuous engine's worker thread; the lock exists because stats() serves
/stats//metrics from other threads — same split as PrefixCache.

Planner interface: lookup(ids) -> (p0, entry, key) and mark(key, hit)
match engine/prefix.PrefixCache, so engine.InferenceEngine._prefix_plan
drives either store (entry = shared physical block ids here, a KV
snapshot there).
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Optional

ROOT = -1  # parent id of a prompt's first block (base-model chains)


def _root_for(adapter) -> object:
    """Chain root for a (possibly adapter-serving) prompt. The adapter
    changes every KV byte its prompt writes, so adapter chains hang off
    a per-adapter sentinel root instead of ROOT — two adapters (or an
    adapter and the base) never match each other's chains even for
    IDENTICAL prompts. Roots are compared by dict equality like any
    parent id; an int parent is always a physical block, so sentinel
    tuples can never collide with real chain interiors."""
    return ("adapter", adapter) if adapter is not None else ROOT


def chunk_digests(seq, chunk: int, max_chunks: int = 64) -> list:
    """Progressive chain digests of `seq`'s head at `chunk` granularity —
    the affinity-key export the router tier (serving/router.py) uses.

    digest[i] covers chunks 0..i with the SAME parent-chained structure
    as the index keys above (each digest folds the previous one in, so
    two sequences share digest[i] iff their first (i+1)*chunk items are
    identical — a chain, not a bag of chunks). Only FULL chunks digest,
    mirroring lookup(): a partial tail block is never shared, so it must
    never pin affinity either.

    `seq` may be token ids (engine-side, chunk = block_size) or
    bytes/str (the router hashes the raw prompt head — it has no
    tokenizer, so it works at a byte granularity approximating
    block_size * bytes-per-token). Digests are hex strings, safe as dict
    keys and log fields. Collisions are a ROUTING concern only (a wrong
    replica pick costs a cache-cold prefill, never wrong KV), so a
    truncated sha1 is plenty.
    """
    if chunk < 1:
        raise ValueError("chunk_digests needs chunk >= 1")
    if isinstance(seq, str):
        seq = seq.encode("utf-8")
    out: list = []
    h = hashlib.sha1(b"dli-chunk-chain")
    for i in range(min(len(seq) // chunk, max_chunks)):
        part = seq[i * chunk : (i + 1) * chunk]
        if isinstance(part, bytes):
            h.update(part)
        else:
            h.update(",".join(str(int(t)) for t in part).encode())
        out.append(h.hexdigest()[:20])
    return out


class BlockPrefixIndex:
    """Chunk-keyed index of cached block chains over a BlockAllocator.

    registry (utils/metrics.MetricsRegistry, optional): reuses the
    `dli_prefix_cache_{hits,misses,evictions}_total` / `_entries`
    families under scope="paged" (entries = cached BLOCKS here), plus
    `dli_prefix_tail_copies_total` (hit admissions that prefilled a
    private tail past the mapped head) and
    `dli_prefix_dedup_saved_tokens_total` (prompt tokens served by
    mapping instead of prefill+scatter).
    """

    def __init__(self, alloc, block_size: int, registry=None):
        if block_size < 1:
            raise ValueError("block prefix index needs block_size >= 1")
        self._alloc = alloc
        self.block_size = int(block_size)
        # planner-protocol granularity (engine._prefix_plan degrades the
        # reuse depth in steps of `chunk` when the deepest offset leaves
        # a tail no prefill bucket fits)
        self.chunk = self.block_size
        # key = (parent block id, chunk token tuple) -> physical block id;
        # insertion order is the LRU order (mark()/register() promote)
        self._entries: "collections.OrderedDict[tuple, int]" = (
            collections.OrderedDict()
        )
        self._children: dict = {}  # parent block id -> set of child keys
        self._block_key: dict = {}  # cached block id -> its entry key
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.saved_tokens = 0
        self._m_hits = self._m_misses = self._m_evictions = None
        self._m_entries = self._m_tail = self._m_saved = None
        if registry is not None:
            self._m_hits = registry.counter(
                "dli_prefix_cache_hits_total",
                "prefix-cache hits (tail actually planned and spliced)",
                ("scope",),
            ).labels(scope="paged")
            self._m_misses = registry.counter(
                "dli_prefix_cache_misses_total", "prefix-cache misses",
                ("scope",),
            ).labels(scope="paged")
            self._m_evictions = registry.counter(
                "dli_prefix_cache_evictions_total",
                "prefix snapshots evicted by the LRU bound", ("scope",),
            ).labels(scope="paged")
            self._m_entries = registry.gauge(
                "dli_prefix_cache_entries", "resident prefix snapshots",
                ("scope",),
            ).labels(scope="paged")
            self._m_tail = registry.counter(
                "dli_prefix_tail_copies_total",
                "prefix-hit admissions that prefilled a private tail "
                "past the mapped shared head",
            ).labels()
            self._m_saved = registry.counter(
                "dli_prefix_dedup_saved_tokens_total",
                "prompt tokens served by mapping shared blocks instead "
                "of prefilling them",
            ).labels()

    # -- planner interface (engine._prefix_plan) ----------------------------
    def lookup(self, ids: list, adapter=None) -> tuple[int, Optional[list],
                                                       Optional[tuple]]:
        """(p0, shared block ids, key) for the deepest cached chain whose
        full blocks token-match the prompt; (0, None, None) on miss. Pure
        — no counters, no LRU promotion, no refcounts: the engine increfs
        the returned blocks once it commits to mapping them, and
        _prefix_plan calls mark() on the PLANNED outcome (a hit that fell
        back cold must not count — and must not hold references).

        adapter: runtime adapter name — the walk starts at that adapter's
        own root (_root_for), so content keys are (adapter, chain), never
        chain alone (the adapter changes the KV).

        Depth is capped to leave at least one tail token to prefill (the
        sampling chunk needs a real token), so a prompt that IS a cached
        chain still decodes — its last block is recomputed, not mapped.
        """
        bs = self.block_size
        ids_t = tuple(ids)
        cap = (len(ids_t) - 1) // bs  # full blocks usable after the cap
        blocks: list = []
        keys: list = []
        parent = _root_for(adapter)
        with self._lock:
            for i in range(cap):
                key = (parent, ids_t[i * bs : (i + 1) * bs])
                b = self._entries.get(key)
                if b is None:
                    break
                blocks.append(b)
                keys.append(key)
                parent = b
        if not blocks:
            return 0, None, None
        return len(blocks) * bs, blocks, tuple(keys)

    def mark(self, key: Optional[tuple], hit: bool, depth: int = 0) -> None:
        """Record the request outcome; a REAL hit (tail planned and
        admitted against the mapped head) promotes the whole chain to MRU
        and counts the dedup'd tokens + the tail copy-out. depth is the
        PLANNED reuse offset — bucket limits may have degraded it below
        the full chain (engine._prefix_plan), and only the mapped tokens
        count as saved."""
        saved = 0
        with self._lock:
            if hit:
                self.hits += 1
                for k in key or ():
                    if k in self._entries:
                        self._entries.move_to_end(k)
                saved = (
                    depth if depth else len(key or ()) * self.block_size
                )
                self.saved_tokens += saved
            else:
                self.misses += 1
        m = self._m_hits if hit else self._m_misses
        if m is not None:
            m.inc()
        if hit and self._m_tail is not None:
            self._m_tail.inc()
            self._m_saved.inc(saved)

    # -- cache mutation (worker thread) --------------------------------------
    def register(self, ids: list, prompt_len: int, row_blocks: list,
                 adapter=None) -> int:
        """Index the admitted prompt's FULL blocks (positions below
        prompt_len // bs * bs — complete, immutable once the insert
        scatter lands). Blocks already cached (the mapped shared head, or
        a chain another request registered) are promoted, not re-added;
        each newly cached block gains the index's own reference. Adapter
        chains register under their adapter's root (see lookup). Returns
        the number of newly cached blocks."""
        bs = self.block_size
        n_full = prompt_len // bs
        parent = _root_for(adapter)
        new = 0
        with self._lock:
            for i in range(n_full):
                key = (parent, tuple(ids[i * bs : (i + 1) * bs]))
                b = self._entries.get(key)
                if b is not None:
                    self._entries.move_to_end(key)
                    parent = b
                    continue
                b = int(row_blocks[i])
                if b in self._block_key:
                    # a block can hold at most one entry (free-listed
                    # blocks are never cached; eviction removes the entry
                    # before the block can recycle) — defensive skip
                    parent = b
                    continue
                self._entries[key] = b
                self._block_key[b] = key
                self._children.setdefault(parent, set()).add(key)
                self._alloc.incref([b])
                new += 1
                parent = b
            n_entries = len(self._entries)
        if self._m_entries is not None:
            self._m_entries.set(n_entries)
        return new

    def import_chain(self, ids: list, row_blocks: list) -> int:
        """Register a RESTORED chain of already-filled pool blocks (the
        warm-recovery path, engine/shadow.py): the caller allocated the
        blocks and scattered their shadowed KV back into the pool, so
        they satisfy the same filled-and-immutable contract register()
        relies on. Thin wrapper over register()'s dedup/incref walk —
        whole blocks only (row_blocks[i] holds ids[i*bs:(i+1)*bs]).
        Returns the number of newly cached blocks."""
        if len(row_blocks) * self.block_size > len(ids):
            raise ValueError(
                f"import_chain: {len(row_blocks)} blocks of "
                f"{self.block_size} exceed the {len(ids)}-token chain"
            )
        return self.register(
            ids, len(row_blocks) * self.block_size, row_blocks
        )

    def export_chains(self) -> list:
        """Every cached chain as token-chunk lists, LRU->MRU by chain
        tip — [(chunk tuple, ...), ...], one entry per LEAF block (a
        chain tip no other entry extends). The persist path
        (engine/shadow.py save ordering) and tests use it; physical
        block ids deliberately do NOT appear — they are meaningless
        across a pool rebuild, which is the whole point of the
        content-keyed shadow.

        Adapter-rooted chains are deliberately EXCLUDED: adapter KV is
        never shadow-captured (the shadow store is content-keyed by
        tokens alone, and adapter KV under base keys would be wrong KV
        on restore), so exporting their chains would persist orderings
        with no backing data."""
        with self._lock:
            parents_with_children = {k[0] for k in self._entries}
            chains = []
            for key, b in self._entries.items():
                if b in parents_with_children:
                    continue  # interior block: some entry extends it
                chunks = []
                k = key
                while True:
                    chunks.append(k[1])
                    if k[0] == ROOT:
                        break
                    if not isinstance(k[0], int):
                        # adapter sentinel root: drop the whole chain
                        chunks = None
                        break
                    k = self._block_key[k[0]]
                if chunks is not None:
                    chains.append(tuple(reversed(chunks)))
        return chains

    def evictable_blocks(self) -> int:
        """Cached blocks reclaimable right now (refcount 1 — held only by
        this index). Admission adds this to the free count when deciding
        whether a queued request can EVER be placed without a release."""
        with self._lock:
            return sum(
                1 for b in self._block_key if self._alloc.refcount(b) == 1
            )

    def evict(self, n: int) -> int:
        """Reclaim >= n blocks from LRU chains whose blocks nobody maps
        (refcount 1), cascading through each chain's descendants — a
        subtree under an unreferenced block is provably unreferenced too.
        Chains mapped by live tables are never touched. Returns blocks
        actually freed (may be < n when the rest of the cache is pinned).
        """
        freed = 0
        if n <= 0:
            return 0
        with self._lock:
            for key in list(self._entries):
                if freed >= n:
                    break
                if key not in self._entries:
                    continue  # removed by an earlier cascade
                if self._alloc.refcount(self._entries[key]) > 1:
                    continue  # mapped by a live table: pinned
                freed += self._evict_subtree(key)
            n_entries = len(self._entries)
        if self._m_entries is not None:
            self._m_entries.set(n_entries)
        return freed

    def _evict_subtree(self, key: tuple) -> int:
        """Drop one entry and every descendant entry (lock held). The
        decref returns each block to the free list — refcount was 1."""
        b = self._entries.pop(key)
        self._block_key.pop(b, None)
        parent_children = self._children.get(key[0])
        if parent_children is not None:
            parent_children.discard(key)
            if not parent_children:
                self._children.pop(key[0], None)
        freed = 1
        for child in list(self._children.get(b, ())):
            freed += self._evict_subtree(child)
        self._children.pop(b, None)
        self._alloc.decref([b])
        self.evictions += 1
        if self._m_evictions is not None:
            self._m_evictions.inc()
        return freed

    def clear(self) -> int:
        """Drop EVERY cached entry, releasing the index's own reference
        on each block. The supervisor's fleet-rebuild path (engine/
        continuous._rebuild_fleet) uses this: the pool buffer is being
        reinitialized, so cached chains no longer hold valid KV and must
        not survive into the restarted fleet. Unlike evict(), refcounts
        above 1 are legal here — the caller has already released the
        live tables, but a block only loses THIS index's holder either
        way. Returns the number of entries dropped."""
        with self._lock:
            blocks = list(self._entries.values())
            self._entries.clear()
            self._children.clear()
            self._block_key.clear()
            self.evictions += len(blocks)
            if blocks:
                self._alloc.decref(blocks)
        if self._m_evictions is not None and blocks:
            self._m_evictions.inc(len(blocks))
        if self._m_entries is not None:
            self._m_entries.set(0)
        return len(blocks)

    def stats(self) -> dict:
        with self._lock:
            return {
                "cached_blocks": len(self._entries),
                "cached_tokens": len(self._entries) * self.block_size,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "dedup_saved_tokens": self.saved_tokens,
            }
