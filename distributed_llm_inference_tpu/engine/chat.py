"""Chat prompt templating (reference C2, /root/reference/orchestration.py:60-67).

The TinyLlama-Chat Zephyr-style format is the behavioral spec; other model
families get their own template or passthrough.
"""

from __future__ import annotations

TINYLLAMA_SYSTEM = "You are a helpful assistant."


def format_chat_prompt(
    user_message: str, system: str = TINYLLAMA_SYSTEM, arch: str = "llama",
    template: str = None,
) -> str:
    """TinyLlama chat format — identical layout to the reference's
    format_chat_prompt (orchestration.py:66). GPT-2 has no chat format;
    the raw prompt passes through. template overrides the arch-derived
    default ("tinyllama" | "gemma" | "none"; cfg.chat_template)."""
    if template is None:
        template = "none" if arch == "gpt2" else "tinyllama"
    if template == "none":
        return user_message
    if template == "gemma":
        # Gemma instruction format (no system turn in gemma's template;
        # the system text folds into the user turn like HF does)
        msg = f"{system}\n\n{user_message}" if system else user_message
        return f"<start_of_turn>user\n{msg}<end_of_turn>\n<start_of_turn>model\n"
    if template == "phi3":
        # Phi-3 instruct HAS a native system role (unlike gemma)
        sys_turn = f"<|system|>\n{system}<|end|>\n" if system else ""
        return f"{sys_turn}<|user|>\n{user_message}<|end|>\n<|assistant|>\n"
    if template != "tinyllama":
        # fail loudly: a typo'd template would silently produce the Zephyr
        # prompt and garbage completions from a non-TinyLlama checkpoint
        raise ValueError(f"unknown chat template {template!r}")
    return f"<|system|>\n{system}</s>\n<|user|>\n{user_message}</s>\n<|assistant|>\n"
