"""Chat prompt templating (reference C2, /root/reference/orchestration.py:60-67).

The TinyLlama-Chat Zephyr-style format is the behavioral spec; other model
families get their own template or passthrough.
"""

from __future__ import annotations

TINYLLAMA_SYSTEM = "You are a helpful assistant."


def format_chat_prompt(user_message: str, system: str = TINYLLAMA_SYSTEM, arch: str = "llama") -> str:
    """TinyLlama chat format — identical layout to the reference's
    format_chat_prompt (orchestration.py:66). GPT-2 has no chat format;
    the raw prompt passes through."""
    if arch == "gpt2":
        return user_message
    return f"<|system|>\n{system}</s>\n<|user|>\n{user_message}</s>\n<|assistant|>\n"
