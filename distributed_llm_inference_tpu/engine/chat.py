"""Chat prompt templating (reference C2, /root/reference/orchestration.py:60-67).

The TinyLlama-Chat Zephyr-style format is the behavioral spec; other model
families get their own template or passthrough.
"""

from __future__ import annotations

TINYLLAMA_SYSTEM = "You are a helpful assistant."


def format_chat_prompt(
    user_message: str, system: str = TINYLLAMA_SYSTEM, arch: str = "llama",
    template: str = None,
) -> str:
    """TinyLlama chat format — identical layout to the reference's
    format_chat_prompt (orchestration.py:66). GPT-2 has no chat format;
    the raw prompt passes through. template overrides the arch-derived
    default ("tinyllama" | "gemma" | "none"; cfg.chat_template)."""
    if template is None:
        template = "none" if arch == "gpt2" else "tinyllama"
    if template == "none":
        return user_message
    # ONE rendering exists per template: the single-turn format is the
    # multi-turn renderer applied to [system, user] (empty system string =
    # omit/blank the system turn, template-dependent, as before)
    return format_chat_messages(
        [{"role": "system", "content": system},
         {"role": "user", "content": user_message}],
        arch=arch, template=template,
    )


def format_chat_messages(
    messages: list, arch: str = "llama", template: str = None,
) -> str:
    """Render a full OpenAI-style message list ([{role, content}, ...])
    into one prompt string, ending with the assistant generation header.

    Multi-turn generalization of `format_chat_prompt` (the reference only
    ever formats a single user turn, orchestration.py:60-67); the
    single-turn output of both functions is byte-identical per template.
    Roles: "system" (first message only), "user", "assistant".
    """
    if template is None:
        template = "none" if arch == "gpt2" else "tinyllama"
    system = None
    turns = []
    for i, m in enumerate(messages):
        role, content = m.get("role"), m.get("content", "")
        if not isinstance(content, str):
            raise ValueError("message content must be a string")
        if role == "system":
            if i != 0:
                raise ValueError("system message must come first")
            system = content
        elif role in ("user", "assistant"):
            turns.append((role, content))
        else:
            raise ValueError(f"unknown role {role!r}")
    if not turns or turns[-1][0] != "user":
        raise ValueError("messages must end with a user turn")

    if template == "none":
        parts = [system] if system else []
        parts += [c for _, c in turns]
        return "\n".join(parts)
    # non-passthrough templates: same default system text as
    # format_chat_prompt, so single-turn renders stay byte-identical
    if system is None:
        system = TINYLLAMA_SYSTEM
    if template == "gemma":
        out = []
        folded = not system  # system folds into the FIRST USER turn
        for role, content in turns:
            tag = "user" if role == "user" else "model"
            if role == "user" and not folded:
                content = f"{system}\n\n{content}"
                folded = True
            out.append(f"<start_of_turn>{tag}\n{content}<end_of_turn>\n")
        return "".join(out) + "<start_of_turn>model\n"
    if template == "phi3":
        out = [f"<|system|>\n{system}<|end|>\n"] if system else []
        out += [f"<|{role}|>\n{content}<|end|>\n" for role, content in turns]
        return "".join(out) + "<|assistant|>\n"
    if template != "tinyllama":
        raise ValueError(f"unknown chat template {template!r}")
    out = [f"<|system|>\n{system}</s>\n"]
    out += [f"<|{role}|>\n{content}</s>\n" for role, content in turns]
    return "".join(out) + "<|assistant|>\n"
