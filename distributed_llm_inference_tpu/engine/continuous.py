"""Continuous (in-flight) batching: slot-based decode with mid-flight admission.

The serving ladder so far:
  * reference: one request at a time, batch dim hardcoded to 1
    (/root/reference/orchestration.py:98,144);
  * serving/queue.py: dispatch-time coalescing — a burst becomes one ragged
    fleet, but the fleet drains to completion before the next group starts,
    so a long generation head-of-line blocks everything behind it.

Here a fixed fleet of `n_slots` KV-cache rows decodes in lock-step
(engine/generate.py decode_slots — per-row positions, per-slot sampling
params), and a new request is admitted the moment any slot frees: its
prompt prefills on a batch=1 scratch cache (reusing the engine's bucketed /
chunked prefill machinery) and splices into the free row (insert_slot)
while the other slots keep decoding. Decode runs in chunks of `chunk_steps`
with ONE device->host fetch per chunk, and the next chunk is launched
BEFORE the previous chunk's tokens are fetched (lag-1 pipelining), so the
TPU queue never drains on host round-trips — on the tunneled single-chip
setup the fetch RTT fully overlaps compute.

Attribution discipline: each launched chunk snapshots the slot->request
assignment. A chunk in flight when a slot is freed and re-admitted would
otherwise credit the old tenant's (masked, pad) emissions to the new one.

Backends: the single-device backend runs the fleet as a plain jit
(engine/generate.decode_slots); the pp PipelineBackend runs the same fleet
inside its shard_map ring (parallel/pipeline._build_decode_slots — each
step is S gated microsteps, dp must be 1). Llama AND gpt2 families: slots
need no left-padding (every slot starts at position 0), so gpt2's learned
absolute positions stay exact — the one batching mode gpt2 supports.
Seeded / debug requests fall back to the solo engine — their contracts
(deterministic RNG stream, single-stream prefill logits) are per-request,
not per-fleet. Greedy `speculative` requests run IN-FLEET on ragged paged
chunked fleets (draft-then-verify rows inside the mixed launch — see
"Speculative decoding" below); only fleets without the mixed program
still serve them solo.

Speculative decoding (ISSUE 13; ARCHITECTURE.md "Speculative decoding"):
eligible greedy decode slots submit a [current + K-token draft] VERIFY
row instead of a 1-token decode row in the mixed scheduler launch — the
ragged kernel already serves arbitrary-length rows, so verifying K
drafts costs ~one decode step of weight streaming and emits up to K+1
tokens. Drafts are host-planned n-gram lookups against the slot's own
fetched history (engine/scheduler.ngram_draft; zero extra weights) or,
cfg-gated, a small draft model's device-side greedy chain sharing the
fleet's block tables (engine_cfg.spec_draft_model). Accept/reject is
fully traced (engine/paged.spec_verify — match-prefix + correction token
on device, packed into the existing fetch), the slot's position simply
advances by the accepted count (rejected draft K/V beyond the new
frontier is overwritten before it can be attended or shadow-captured),
and the host position model resyncs from the fetched advance. With
device-derived launch metadata (ISSUE 15, engine_cfg.spec_device_meta,
default ON) the kernel reads each decode/verify row's q_start and
per-token positions from the device-resident slot state
(engine/paged.DeviceMeta + apply_device_meta), so an unfetched verify
row never freezes its slot: every eligible slot submits a verify row
EVERY scheduler step, back to back under lag pipelining, the host
drafts from an OPTIMISTIC history (fetched tokens + its own pending
predicted windows — a misprediction only lowers acceptance, never
correctness: the verify accepts only the model's own argmax), and the
packed fetch confirms emissions after the fact. Per-slot adaptive K
(TokenBudgetScheduler.spec_slot_k): an acceptance-rate EWMA fed from
the same fetch sizes each slot's next draft between 0 and
spec_draft_len. spec_device_meta=False pins the PR-13 behavior — a
slot with an unfetched verify row is skipped (frozen on device via
SpecPlan.dec_on) so the host-planned q_start stays exact — kept as the
bench.py spec_lag baseline. Speculated tokens debit step_token_budget
(TokenBudgetScheduler.spec_draft_len), so the SLO layer throttles K to 0
under decode TPOT pressure — speculation accelerates idle fleets and
self-disables under load. Greedy output is bit-identical to
non-speculative decode (spec_verify replicates slot_step token for
token), crash/preemption salvage included (unfetched verify emissions
drop exactly like unfetched chunks).

Failure containment (ARCHITECTURE.md "Failure containment"): the worker
loop runs under a SUPERVISOR (_loop/_supervise). A crash anywhere in the
scheduler releases every fleet-held resource (block tables decref'd,
constraint rows freed, cached prefix chains dropped), rebuilds the
device-side fleet, and restarts the loop under a bounded consecutive-crash
budget with exponential backoff. Live requests are salvaged: their prompt
and fetched tokens are host-side, so each is re-admitted as a CONTINUATION
prefill (prompt + tokens-so-far) — greedy output across a crash is
bit-identical to a fault-free run. Requests admitted since the last
healthy fetch form the crash SUSPECT set; recovery re-admits one request
per healthy chunk so a recurring crash implicates exactly one suspect,
and a request implicated poison_strikes times is quarantined alone
(error_type "poison") while its fleet-mates survive. Every path is
exercised deterministically in CI via utils/faults.py injection points
(tests/test_faults.py).

Warm-state recovery (ARCHITECTURE.md "Warm recovery"): paged fleets
shadow every FILLED pool block host-side as it becomes immutable
(engine/shadow.py — async device->host copies off the scheduler
thread), so a supervisor restart scatters the shadowed blocks back
into the rebuilt pool, re-learns their block-prefix chains, and each
salvage re-admission re-prefills ONLY its partial tail block instead
of the whole prompt (dli_recovery_tokens_recomputed_total measures
it). Graceful drain persists the shadow to --restore-dir and startup
restores it, so the router's rolling restarts hand replicas back in
with a WARM prefix cache (tests/test_recovery.py chaos matrix).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import faults
from ..utils.logging import get_logger
from ..utils.metrics import DEFAULT_SIZE_BUCKETS
from ..utils.retry import overload_retry_after
from ..utils.tracing import Trace, sample_decision
from . import generate as G
from .block_prefix import chunk_digests

log = get_logger("continuous")

# _admit_one sentinel: the paged pool has no blocks for this request right
# now — requeue it (front) and retry after the next release
_BLOCKED = object()


class _Request:
    __slots__ = (
        "prompt", "kwargs", "done", "result", "t_start", "ttft",
        "first_id", "tokens", "slot", "enqueued", "budget",
        "stream_q", "streamed_text", "record", "prefix_hit_tokens",
        "cancelled", "prompt_tokens", "block_ids", "need", "cart",
        "trace", "salvaged", "strikes", "allowed", "slo",
        "ids", "shadow_depth", "recovering",
        "deadline_at", "cancel_cause", "preemptions", "preempted_at",
        "resume_seq", "drop_seq", "kv_hint", "fabric_blocks",
        "promoted_blocks",
        "spec_want", "spec_drafted", "spec_accepted", "spec_launches",
        "adapter", "tenant", "adapter_page", "trace_ctx", "profiled",
    )

    def __init__(self, prompt: str, kwargs: dict, stream_q=None,
                 request_id=None, kv_hint=None, adapter=None, tenant=None,
                 trace_ctx=None):
        self.prompt = prompt
        # multi-tenant adapter serving (engine/adapters.py): registered
        # adapter name (None = base model), the tenant the request bills
        # against, and — once admitted — the HBM adapter page its launch
        # rows select (0 = the base page; held via the pool's refcount
        # from admission to release)
        self.adapter = adapter
        self.tenant = tenant
        self.adapter_page: Optional[int] = None
        # SLO class name (engine/scheduler.py): resolved against the
        # configured classes at enqueue; drives prefill-budget
        # apportionment, shed decisions, and class-aware Retry-After
        self.slo = kwargs.pop("slo_class", None)
        self.kwargs = kwargs
        # per-request stage trace (utils/tracing.py): queue_wait /
        # admission / decode / detokenize spans + the request id echoed
        # in the response and the X-Request-Id header
        self.trace = Trace(request_id)
        # fleet trace context (ISSUE 17): the SpanContext parsed from the
        # inbound traceparent header (None = untraced request). profiled
        # flips True only when the deterministic per-trace sample
        # decision under engine_cfg.trace_sample_rate says this request
        # gets launch-level attribution spans.
        self.trace_ctx = trace_ctx
        self.profiled = False
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.enqueued = time.time()
        self.t_start = self.enqueued
        self.ttft: float = 0.0
        self.first_id: Optional[int] = None
        self.tokens: list[int] = []
        self.slot: Optional[int] = None
        self.budget: int = 0
        # token streaming (NDJSON serving): events land here as chunks
        # process; None = non-streaming request
        self.stream_q = stream_q
        self.streamed_text = ""  # chars already emitted (BPE-safe deltas)
        self.record = True  # False: warmup traffic, kept out of /stats
        self.prefix_hit_tokens = 0  # prompt tokens served from the prefix cache
        self.cancelled = False  # client went away; free the slot early
        self.prompt_tokens = 0  # set at admission (tokenized prompt length)
        self.block_ids = None  # paged mode: this request's pool blocks
        # paged mode: FRESH blocks required after the mapped shared head
        # (set on the 1st admission attempt; drives the head-of-queue
        # backpressure check)
        self.need = None
        # grammar constraint (constrain/): (CompiledConstraint, fleet-table
        # row offset) once admitted; None = unconstrained
        self.cart = None
        # crash recovery (the scheduler supervisor): tokens generated
        # before a scheduler crash, re-prefilled as a continuation on
        # re-admission so greedy decode resumes bit-exactly
        self.salvaged: list[int] = []
        # crash-restarts this request was implicated in (suspect set at
        # crash time); poison_strikes of them quarantine it
        self.strikes = 0
        # total generated-token cap fixed at FIRST admission (clamped
        # max_tokens) — re-admissions shrink their budget against it
        self.allowed: Optional[int] = None
        # warm-recovery shadow bookkeeping (engine/shadow.py): the
        # admitted token sequence (prompt + salvaged continuation — the
        # content the request's pool blocks hold) and how many of its
        # full blocks have been handed to the shadow copier
        self.ids: Optional[list] = None
        self.shadow_depth = 0
        # set while the recovery path re-admits this request — drives
        # the dli_recovery_tokens_recomputed_total accounting
        self.recovering = False
        # end-to-end deadline (deadline_ms on /generate and the OpenAI
        # routes, propagated via X-Request-Deadline-Ms through the
        # router): absolute wall-clock expiry, checked ONLY at launch
        # boundaries on the host (never inside compiled code); None =
        # no per-request deadline (engine_cfg.request_deadline_s still
        # applies as the server-wide cap)
        dl = kwargs.pop("deadline_ms", None)
        self.deadline_at = (
            self.enqueued + float(dl) / 1e3 if dl is not None else None
        )
        # why the cancel flag was flipped (dli_cancelled_total{cause})
        self.cancel_cause = "disconnect"
        # SLO-aware KV preemption (engine_cfg.preempt_policy): how many
        # times this request was evicted mid-decode to make pool room —
        # at max_preemptions_per_req it becomes immune — and when it was
        # last parked (feeds dli_preempted_resume_seconds)
        self.preemptions = 0
        self.preempted_at: float = 0.0
        # swap path: the token sequence whose shadowed chain the resume
        # re-admission restores (None = drop-and-recompute)
        self.resume_seq = None
        # launch-seq barrier: emissions fetched from chunks launched
        # BEFORE this seq are dropped (a preempted victim's in-flight
        # chunks are regenerated after resume, exactly like the crash
        # salvage contract)
        self.drop_seq = 0
        # KV-fabric handoff hint (the router's X-KV-Transfer-* headers):
        # {"peer": url, "digest": hex} naming where this prompt's prefix
        # chain is resident. Consumed on the FIRST admission attempt —
        # retries/requeues/salvages never re-fetch (the first import
        # either landed in the block-prefix index or the fallback is
        # local prefill).
        self.kv_hint = kv_hint
        # blocks imported over the fabric for this request (envelope
        # observability: the router reads it to score handoff outcomes)
        self.fabric_blocks = 0
        self.promoted_blocks = 0
        # speculative decoding (mixed-fleet draft-then-verify): the
        # request asked for it ("speculative": true — fleet-wide
        # engine_cfg.spec_decode makes every eligible greedy request a
        # candidate too), plus per-request draft/accept/launch counts
        # for the envelope
        self.spec_want = bool(kwargs.get("speculative"))
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_launches = 0


class ContinuousEngine:
    """In-flight batching front end over an InferenceEngine's model/backend.

    submit() blocks until the request's envelope is ready (same response
    schema as InferenceEngine.generate, plus "continuous": true and the
    admission depth it shared the fleet with).
    """

    def __init__(
        self,
        engine: Any,
        n_slots: int = 8,
        chunk_steps: int = 16,
        max_queue: int = 64,
        chunk_lag: int = 2,
        slot_max_seq: Optional[int] = None,
        kv_pool_blocks: Optional[int] = None,
        kv_block_size: int = 16,
        restart_budget: int = 3,
        restart_backoff_s: float = 0.05,
        poison_strikes: int = 2,
        kv_shadow: Optional[bool] = None,
        restore_dir: Optional[str] = None,
    ):
        cfg = engine.cfg
        if cfg.arch not in ("llama", "gpt2"):
            raise ValueError(
                f"continuous batching supports the llama and gpt2 families; "
                f"model arch is {cfg.arch!r}"
            )
        if not getattr(engine.backend, "supports_slots", False):
            raise ValueError(
                f"backend {engine.backend.name!r} does not support slot "
                f"decode; continuous batching runs on the single-device "
                f"backend or a pp pipeline mesh with dp == 1"
            )
        self.engine = engine
        self.cfg = cfg
        self.backend = engine.backend
        self.n_slots = int(n_slots)
        self.chunk_steps = int(chunk_steps)
        self.max_queue = int(max_queue)
        # How many decode chunks may be in flight on the device before the
        # worker blocks on the oldest chunk's fetch. 1 = classic lag-1
        # (fetch N-1 overlaps compute N). Higher absorbs a fetch RTT
        # LARGER than a chunk's compute (e.g. a tunneled TPU: ~70 ms RTT
        # vs ~45 ms of chunk compute would idle the device every chunk at
        # lag-1) at the cost of noticing EOS/stop/cancel up to `lag`
        # chunks late — bounded compute waste, never wrong output.
        self.chunk_lag = max(1, int(chunk_lag))
        # Failure containment (the supervisor wrapped around _loop_inner):
        # how many CONSECUTIVE crashes the scheduler absorbs before it
        # declares the fleet dead (a healthy fetch resets the window), the
        # backoff base doubled per consecutive crash, and how many crash
        # implications (suspect-set membership at crash time) quarantine a
        # request as poison.
        self.restart_budget = max(0, int(restart_budget))
        self.restart_backoff_s = float(restart_backoff_s)
        self.poison_strikes = max(1, int(poison_strikes))
        # SLO-aware KV preemption (graceful degradation under memory
        # pressure): when the pool still cannot place an admission after
        # the evict-unreferenced-chains retry, _preempt_for evicts the
        # lowest-SLO-weight / youngest decoding victim instead of
        # stalling the queue (policy: "swap" pushes the victim's filled
        # blocks to the host shadow first, "recompute" drops them,
        # "off" restores the wait-for-release behavior).
        self.preempt_policy = str(engine.engine_cfg.preempt_policy)
        if self.preempt_policy not in ("swap", "recompute", "off"):
            raise ValueError(
                f"preempt_policy must be 'swap', 'recompute', or 'off', "
                f"got {self.preempt_policy!r}"
            )
        self.max_preemptions = max(
            0, int(engine.engine_cfg.max_preemptions_per_req)
        )
        # preempted requests parked for re-admission (served BEFORE the
        # regular queue — a victim must not also lose its queue position)
        self._resume: list[_Request] = []
        self.preempted_total = 0

        # Per-slot KV budget (round-2 review weak #7): the fleet cache pins
        # n_slots x slot_max_seq of KV in HBM for the server's lifetime —
        # at Llama-2-7B/4096/8-slot scale that is ~8.5 GB bf16 BEFORE
        # weights when sized to the model window. slot_max_seq caps the
        # slot class: allocation becomes a function of the configured
        # budget, and admission plans/clamps against it (prompts beyond it
        # are rejected, decode budgets clamped to fit).
        self.slot_max_seq = min(
            int(slot_max_seq or cfg.max_seq_len), cfg.max_seq_len
        )
        buckets = engine._buckets()
        # Ragged paged ingest (engine/paged.py): admission prefills
        # straight into the pool in fixed-width flat-token launches — the
        # prefill-bucket ladder (and its scratch gather/scatter) becomes
        # the cfg-gated fallback. Decided here because the bucket guard
        # below only applies when the bucketed plan is what admission runs.
        ragged_planned = bool(
            kv_pool_blocks is not None
            and engine.engine_cfg.ragged_prefill
            and getattr(engine.backend, "supports_ragged_fill", False)
        )
        if not ragged_planned and buckets and self.slot_max_seq < buckets[0]:
            # the bucketed ingest plan needs at least one prefill bucket
            # inside the slot class — a smaller budget would start a
            # healthy-looking server that rejects EVERY request
            raise ValueError(
                f"slot_max_seq={self.slot_max_seq} is smaller than the "
                f"smallest prefill bucket {buckets[0]}; raise it or shrink "
                f"engine_cfg.prefill_buckets"
            )
        # Block-paged KV (engine/paged.py): fleet memory becomes a function
        # of the POOL (aggregate in-flight tokens), not n_slots x window —
        # the round-2 "n_slots x max_seq pinned HBM" review item's stretch
        # goal. Admission allocates blocks, release frees them, and a
        # request that can't get blocks waits in the queue (backpressure).
        self.paged = kv_pool_blocks is not None
        if self.paged:
            if not getattr(engine.backend, "supports_paged", False):
                raise ValueError(
                    f"backend {engine.backend.name!r} does not support "
                    f"paged KV (llama/gpt2 family, single device or a "
                    f"dp=1 pp/tp mesh); drop kv_pool_blocks or use the "
                    f"dense fleet"
                )
            from . import paged as P

            self._P = P
            self.kv_block_size = int(kv_block_size)
            if self.kv_block_size < 1:
                raise ValueError("kv_block_size must be >= 1")
            # logical blocks per slot; scratch rounds up to a whole number
            # of blocks so the insert scatter is an exact block reshape
            self._max_blocks = -(-self.slot_max_seq // self.kv_block_size)
            self._scratch_seq = self._max_blocks * self.kv_block_size
            if int(kv_pool_blocks) - 1 < self._max_blocks:
                raise ValueError(
                    f"kv_pool_blocks={kv_pool_blocks} cannot hold one "
                    f"full slot-class request ({self._max_blocks} blocks "
                    f"of {self.kv_block_size} + the trash block); raise it "
                    f"or shrink slot_max_seq"
                )
            self._pool_blocks = int(kv_pool_blocks)
            self.cache = self.backend.init_paged_pool(
                self._pool_blocks, self.kv_block_size
            )
            self._alloc = P.BlockAllocator(
                self._pool_blocks, registry=engine.metrics
            )
            # host-side block tables; device copy rebuilt lazily on change
            self._table = np.zeros(
                (self.n_slots, self._max_blocks), np.int32
            )
            self._table_dev = None
            # per-slot adapter page ids (engine/adapters.py): 0 = the
            # base page, set beside the block-table row at admission and
            # zeroed with it at release. Worker-thread-mutated like
            # _table; every paged launch carries a snapshot of it.
            self._slot_pages = np.zeros((self.n_slots,), np.int32)
            self._ragged = ragged_planned
            # query-tile granularity of the ragged kernel's flat token
            # axis; the launch width rounds up to a whole number of tiles
            self._ragged_tile = 8
            self._ragged_width = -(
                -max(1, int(engine.engine_cfg.ragged_width))
                // self._ragged_tile
            ) * self._ragged_tile
        else:
            self._ragged = False
            self._ragged_tile = 8
            self._scratch_seq = self.slot_max_seq
            self.cache = self.backend.init_cache(
                self.n_slots, self.slot_max_seq
            )
        # SLO-aware chunked-prefill scheduler (engine/scheduler.py): the
        # ragged paged fleet stops prefilling admissions whole — each
        # scheduler step is ONE mixed launch of every active decode row
        # plus budget-sliced prefill chunks. The TokenBudgetScheduler is
        # built for EVERY fleet mode (its SLO classification, per-class
        # feedback, shed decisions, and class-aware Retry-After apply to
        # admission regardless of ingest strategy); only the step
        # planning needs the mixed ragged program.
        from .scheduler import TokenBudgetScheduler, parse_slo_classes

        self._slo = parse_slo_classes(engine.engine_cfg)
        self._sched = TokenBudgetScheduler(
            self._slo, engine.engine_cfg.slo_default_class,
            int(engine.engine_cfg.step_token_budget), self._ragged_tile,
            self.n_slots, registry=engine.metrics,
            tenant_weights=engine.engine_cfg.tenant_weights,
        )
        self._chunked = bool(
            self._ragged
            and engine.engine_cfg.chunked_prefill
            and getattr(engine.backend, "supports_mixed_step", False)
        )
        # chunked-mode host state: pending PrefillJobs (arrival order),
        # slot -> job for slots whose prompt is still landing, and the
        # host's position model per slot. With device-derived launch
        # metadata (spec_device_meta) the kernel reads decode/verify
        # positions from slot state and this model is a LAGGED
        # accounting view (launch entries carry it only as a
        # placeholder; verify fetches catch it up by the accepted
        # advance); without it, it must be exact for live rows — it IS
        # the decode tiles' kernel metadata there (over-advance on rows
        # that went inactive since the last fetch is masked garbage,
        # the frozen-row argument)
        self._jobs: list = []
        self._prefilling: dict = {}
        self._host_pos = np.zeros((self.n_slots,), np.int64)
        self._idle_arm = None
        if self._chunked:
            from . import paged as _P_arm

            self._sched_width = self._sched.width
            self._idle_arm = _P_arm.idle_mixed_arm(
                self.n_slots, cfg.vocab_size
            )
        # Speculative decoding on the mixed fleet (ISSUE 13 + 15):
        # eligible greedy decode slots submit [current + K-draft] verify
        # rows inside the mixed launch. Two position disciplines:
        #   * spec_device_meta (default): q_start / per-token positions
        #     derive ON DEVICE from slot state (engine/paged.DeviceMeta)
        #     — verify rows launch EVERY step, back to back; the host
        #     keeps a FIFO of pending (unfetched) verify launches per
        #     slot (_spec_pending) carrying each launch's predicted
        #     window so n-gram drafting continues from the optimistic
        #     frontier, plus the advance upper bound for the block-
        #     capacity clamp.
        #   * legacy (spec_device_meta=False, the bench baseline): a
        #     slot with an unfetched verify row is skipped from planning
        #     (_spec_inflight) until the packed fetch resyncs its
        #     position — the PR-13 alternation.
        ecfg = engine.engine_cfg
        self._spec_k_max = max(0, int(getattr(ecfg, "spec_draft_len", 0)))
        self._spec_auto = bool(getattr(ecfg, "spec_decode", False))
        self._spec_capable = bool(self._chunked and self._spec_k_max > 0)
        self._spec_devmeta = bool(
            self._spec_capable
            and getattr(ecfg, "spec_device_meta", True)
        )
        self._spec_inflight: dict = {}  # legacy: slot -> (req, n_draft)
        # device-meta mode: slot -> FIFO of dicts per unfetched verify
        # launch ({req, nd, pred (drafts + predicted correction, n-gram
        # mode), adv (position-advance upper bound nd + 1)})
        self._spec_pending: dict = {}
        # amortized decode-chunk launches not yet fetched: their
        # emissions are unpredictable many-token advances, so drafting
        # pauses while any are outstanding (positions stay exact either
        # way — they derive on device)
        self._chunk_unfetched = 0
        self._row_inflight = np.zeros((self.n_slots,), np.int64)
        self.spec_launches = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        # verify rows launched while an earlier one was still unfetched
        # — the back-to-back counter the lag-pipelining tests pin (zero
        # by construction in the legacy mode)
        self.spec_pipelined = 0
        # cfg-gated draft model (the decode_draft_speculative flavor):
        # a small same-tokenizer model proposes drafts device-side,
        # batched over the fleet, over its OWN pool leaves indexed by
        # the SAME block tables — draft KV shares the target pool's
        # allocation lifecycle for free. An attached engine.set_draft()
        # draft takes precedence over loading the named config.
        self._draft_mode = False
        self._dcfg = self._dparams = self._dpool = None
        if self._spec_capable and getattr(ecfg, "spec_draft_model", None):
            if engine._draft is None:
                from ..models.registry import get_model_config

                engine.set_draft(get_model_config(ecfg.spec_draft_model))
            self._dcfg, self._dparams = engine._draft
            if self._dcfg.arch not in ("llama", "gpt2"):
                raise ValueError(
                    f"spec_draft_model must be a llama/gpt2-family config "
                    f"(the paged hook seam); got {self._dcfg.arch!r}"
                )
            self._dpool = self._P.init_pool(
                self._dcfg, self._pool_blocks, self.kv_block_size
            )
            self._draft_mode = True
        self.state, self.sparams = G.init_slots(self.n_slots, cfg.vocab_size)
        # Grammar-constraint fleet state (constrain/): per-slot FSM rows
        # into the COMBINED resident table (row 0 = the free state every
        # unconstrained slot sits at). The table registry is built lazily
        # on the first constrained admission; while any constrained slot
        # is active the worker launches the constrained slot program
        # (decode_slots_constrained — fsm chains device-side between
        # chunks), otherwise the untouched plain program.
        self._fsm = jnp.zeros((self.n_slots,), jnp.int32)
        from ..constrain import FleetConstraintTable

        self._ctable = FleetConstraintTable(
            cfg.vocab_size,
            max_states=engine.engine_cfg.constraint_fleet_states,
            registry=engine.metrics,
        )
        # scratch must match the fleet's logical extent: the insert splices
        # the whole row (dense) / scatters every logical block (paged).
        # The RAGGED paged path prefills straight into the pool, so it
        # carries no scratch cache at all — one slot-class of HBM saved
        # on top of deleting the gather/scatter admission moves.
        self._scratch = (
            None if self._ragged
            else self.backend.init_cache(1, self._scratch_seq)
        )
        # guarded-by: _cv
        self._assignment: list[Optional[_Request]] = [None] * self.n_slots
        # Prefix reuse, one planner per fleet mode (both drive the shared
        # engine._prefix_plan seam):
        #   * paged: block-level sharing (engine/block_prefix.py) — a hit
        #     MAPS the cached physical blocks into the request's table
        #     (refcounted, dedup'd in pool HBM), no snapshot, no splice;
        #   * dense: own PrefixCache instance (engine/prefix.py), NOT
        #     shared with the solo engine's — the solo path touches its
        #     cache under the engine lock while this worker thread runs
        #     lock-free; separate instances cost duplicate snapshots at
        #     worst, never a race.
        self._prefix = None
        self._bpx = None
        if engine.engine_cfg.prefix_cache_entries > 0:
            if self.paged:
                from .block_prefix import BlockPrefixIndex

                self._bpx = BlockPrefixIndex(
                    self._alloc, self.kv_block_size,
                    registry=engine.metrics,
                )
            else:
                from .prefix import PrefixCache

                if PrefixCache.compatible(self._scratch):
                    self._prefix = PrefixCache(
                        engine.engine_cfg.prefix_cache_entries,
                        engine.engine_cfg.prefix_chunk,
                        registry=engine.metrics, scope="continuous",
                    )
                else:
                    log.info("prefix_cache_disabled", reason="cache layout")

        # Warm-state recovery (engine/shadow.py): host-side crash-
        # consistent shadow of filled pool blocks. Requires the paged
        # fleet (block immutability is the consistency argument), the
        # block-prefix index (restore re-enters through the ordinary
        # prefix-hit machinery), and a backend with the shadow
        # gather/scatter programs (the single device AND the pp pipeline
        # — parallel/pipeline's layer-local shard_map twins — so
        # pp-sharded pools recover warm too).
        self._shadow = None
        self._restore_dir = restore_dir
        self._needs_restore = False
        self.shadow_restored_total = 0
        use_shadow = (
            engine.engine_cfg.kv_shadow if kv_shadow is None else kv_shadow
        )
        if (
            self.paged and use_shadow and self._bpx is not None
            and hasattr(self.backend, "gather_shadow_blocks")
        ):
            from .shadow import ShadowStore

            self._shadow = ShadowStore(
                self.kv_block_size,
                max_blocks=(
                    engine.engine_cfg.kv_shadow_blocks
                    or 2 * self._pool_blocks
                ),
                registry=engine.metrics,
                # tier 2 (ARCHITECTURE.md "Tiered KV"): host-LRU
                # evictions demote into chunk files here instead of
                # dropping, and every shadow read surface promotes hits
                # back out — None keeps the flat PR-9 behavior
                disk_dir=engine.engine_cfg.kv_disk_dir,
                max_disk_blocks=engine.engine_cfg.kv_disk_blocks,
            )
            if restore_dir and self._shadow.load(restore_dir):
                # persisted warm state (a drained predecessor's blocks +
                # chain metadata): restored by the worker thread before
                # it serves anything — same path as the crash restore
                self._needs_restore = True
        # fixed gather width of the shadow capture program: one compiled
        # program serves every capture batch (callers pad by repeating)
        self._shadow_gather_w = 8
        # fixed restore width: restores pad to a multiple of this (pad
        # rows scatter garbage into the write-only TRASH block), so one
        # compiled restore program serves the common case — and it is
        # PRE-WARMED here so a crash's restore never pays jit latency
        # inside the recovery window (same discipline as warmup())
        self._shadow_restore_w = 32
        if self._shadow is not None:
            W = self._shadow_restore_w
            zeros = jax.tree.map(
                lambda pl: jnp.zeros(
                    (W, pl.shape[0]) + pl.shape[2:], pl.dtype
                ),
                self.cache,
            )
            self.cache = self.backend.restore_shadow_blocks(
                self.cache, zeros,
                jnp.zeros((W,), jnp.int32),  # all rows -> trash block
            )
        # Cross-replica KV fabric (serving/kv_fabric.py): this replica's
        # fetch client, plus the serving half's gate. Rides the SAME
        # stack as warm recovery — the shadow store holds the servable
        # chains, the pre-warmed restore program scatters fetched ones,
        # the block-prefix index registers them — so fabric imports are
        # bit-exact by the identical content-key argument.
        self.replica_class = str(engine.engine_cfg.replica_class)
        if self.replica_class not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"replica_class must be 'prefill', 'decode', or 'mixed', "
                f"got {self.replica_class!r}"
            )
        self._fabric = None
        self.fabric_serving = bool(
            self._shadow is not None and engine.engine_cfg.kv_fabric
        )
        if self.fabric_serving:
            from ..serving.kv_fabric import KVFabricClient

            self._fabric = KVFabricClient(
                registry=engine.metrics, role=self.replica_class,
                timeout_s=engine.engine_cfg.kv_fabric_timeout_s,
            )
        # streamed pulls (chunk-at-a-time frames, scatter overlapping
        # the wire) vs the PR-11 whole-manifest pull; and the /health
        # residency-bootstrap cap (MRU-first — the disk tier makes the
        # full resident set unbounded)
        self._fabric_stream = bool(engine.engine_cfg.kv_fabric_stream)
        self._kv_health_digests = max(
            1, int(engine.engine_cfg.kv_health_digests)
        )
        # Paged LoRA adapter serving (engine/adapters.py): the engine's
        # AdapterPool, honored only on fleets whose launch programs can
        # carry the traced pages operand (ragged paged — every other
        # fleet rejects adapter requests at submit with a 400 envelope).
        self._adapters = (
            getattr(engine, "adapters", None)
            if (self.paged and self._ragged) else None
        )
        self._tenant_max_share = float(
            engine.engine_cfg.tenant_max_queue_share
        )
        self._cv = threading.Condition()
        self._queue: list[_Request] = []  # guarded-by: _cv
        # tenants that have ever queued (guarded-by: _cv) — keeps the
        # per-tenant queue-depth gauge schema stable after they drain
        self._gauge_tenants: set = {""}
        self._closed = False  # guarded-by: _cv
        self._key = jax.random.PRNGKey(int(time.time()) & 0x7FFFFFFF)
        # supervisor state (all worker-thread-mutated; readiness reads are
        # racy-but-monotone flags)
        self._draining = False  # guarded-by: _cv
        self._dead = False        # restart budget exhausted
        self._restarting = False  # mid crash-recovery (readiness = False)
        self._recovery: list[_Request] = []  # salvaged, awaiting re-admission
        # requests admitted since the last healthy fetch — the crash
        # suspect set (see _supervise / _process)
        self._suspects: set = set()
        self._admitting: Optional[_Request] = None
        self._consecutive_crashes = 0
        self._mutation_seq = 0  # bumped per admission; chunks snapshot it
        # Launch-level device-time attribution (ISSUE 17,
        # engine_cfg.trace_sample_rate): launch records appended at
        # dispatch and closed at the matching packed fetch — matched by
        # the launch's own perf_counter timestamp, so lag-pipelined
        # launches attribute correctly with ZERO extra device syncs.
        # At rate 0 (the default) the ONLY hot-path cost is one float
        # compare: _prof_note_launch is never called, the deque stays
        # empty, nothing allocates.
        self._trace_rate = float(engine.engine_cfg.trace_sample_rate)
        self._prof_active = 0  # profiled requests seen in the last launch
        self._launch_log: collections.deque = collections.deque()
        # observability
        self.admitted = 0  # guarded-by: _cv
        self.completed = 0  # guarded-by: _cv
        self.peak_occupancy = 0  # guarded-by: _cv
        self.restarts_total = 0
        self.recovered_total = 0
        self.poisoned_total = 0
        # registry families (engine.metrics — the one registry /metrics
        # scrapes): fleet occupancy, queue depth, admission waits, chunk
        # launch-to-fetch step time, preemptions
        m = engine.metrics
        m.gauge(
            "dli_slots_total", "continuous-fleet decode slots"
        ).labels().set(self.n_slots)
        self._m_occupied = m.gauge(
            "dli_slots_occupied", "continuous-fleet slots serving a request"
        ).labels()
        self._m_depth = m.gauge(
            "dli_queue_depth", "requests waiting for dispatch", ("queue",)
        ).labels(queue="continuous")
        self._m_admission_wait = m.histogram(
            "dli_admission_wait_seconds",
            "enqueue-to-admission wait", ("queue",),
        ).labels(queue="continuous")
        self._m_step = m.histogram(
            "dli_decode_step_seconds",
            "per-token decode step time, chunk launch-to-fetch / "
            "chunk_steps (includes pipelining lag)", ("engine",),
        ).labels(engine="continuous")
        self._m_preempt = m.counter(
            "dli_preemptions_total",
            "slots killed before their budget drained", ("reason",),
        )
        self._m_shed = m.counter(
            "dli_queue_shed_total", "requests shed with 429", ("queue",)
        ).labels(queue="continuous")
        # multi-tenant admission quota (family pre-registered in
        # engine/engine.py): requests shed because one tenant's queued
        # share crossed engine_cfg.tenant_max_queue_share
        self._m_tenant_shed = m.counter(
            "dli_tenant_shed_total",
            "requests shed with 429 by the per-tenant queue quota",
            ("tenant",),
        )
        # graceful-degradation families (pre-registered in
        # engine/engine.py): preempt->resume latency, cancellations by
        # cause, deadline overruns
        self._m_resume_s = m.histogram(
            "dli_preempted_resume_seconds",
            "preemption to successful re-admission latency",
        ).labels()
        self._m_cancelled = m.counter(
            "dli_cancelled_total",
            "requests cancelled before completion", ("cause",),
        )
        self._m_deadline_exceeded = m.counter(
            "dli_deadline_exceeded_total",
            "requests failed by their end-to-end deadline_ms",
        ).labels()
        self._m_restarts = m.counter(
            "dli_scheduler_restarts_total",
            "continuous-scheduler supervisor restarts", ("engine",),
        ).labels(engine="continuous")
        self._m_recovered = m.counter(
            "dli_requests_recovered_total",
            "in-flight requests re-admitted (continuation prefill) after "
            "a scheduler restart", ("engine",),
        ).labels(engine="continuous")
        self._m_poison = m.counter(
            "dli_poison_requests_total",
            "requests quarantined as poison after repeated crash "
            "implication", ("engine",),
        ).labels(engine="continuous")
        self._m_drain = m.histogram(
            "dli_drain_duration_seconds",
            "graceful-drain wall time (SIGTERM / drain())", ("component",),
        ).labels(component="continuous")
        # warm-recovery accounting (families pre-registered in
        # engine/engine.py): how much prefill each salvage re-admission
        # actually recomputed (warm recovery bounds it by the partial
        # tail block) and how many shadowed blocks restores scattered
        # back into rebuilt pools
        self._m_recovery_recomputed = m.counter(
            "dli_recovery_tokens_recomputed_total",
            "prompt tokens re-prefilled for crash-recovery re-admissions "
            "(warm recovery bounds this by the partial tail block)",
            ("engine",),
        ).labels(engine="continuous")
        self._m_shadow_restored = m.counter(
            "dli_shadow_restored_blocks_total",
            "shadowed blocks scattered back into a rebuilt pool "
            "(supervisor restart or --restore-dir start)",
        ).labels()
        # ragged-ingest observability (families pre-registered in
        # engine/engine.py for schema stability): launch composition,
        # padding overhead, exact-depth reuse, compiled-program gauge
        self._m_ragged_rows = m.counter(
            "dli_ragged_rows_total",
            "ragged-launch rows by kind (prefill chunk / decode token)",
            ("kind",),
        )
        self._m_ragged_tiles = m.counter(
            "dli_ragged_tiles_total",
            "ragged-launch query tiles by liveness (live / pad — pad "
            "tiles cost no DMA, only grid steps)", ("state",),
        )
        self._m_ragged_launches = m.counter(
            "dli_ragged_launches_total",
            "ragged ingest launches", ("phase",),
        )
        self._m_ragged_exact = m.counter(
            "dli_ragged_exact_prefix_hits_total",
            "prefix hits reused at exact chunk depth (no bucket "
            "degradation — the ragged path's planner win)",
        ).labels()
        self._m_ragged_programs = m.gauge(
            "dli_ragged_compiled_programs",
            "compiled ragged ingest programs (flat after warmup = no "
            "per-tail-shape recompile)",
        ).labels()
        # chunked-prefill scheduler families (pre-registered in
        # engine/engine.py): mixed-launch composition — how much of each
        # step's flat-token budget went to decode rows vs prefill chunks
        self._m_sched_tokens = m.counter(
            "dli_sched_step_tokens_total",
            "flat tokens launched by the chunked-prefill scheduler, by "
            "kind (decode rows / prefill chunk tokens)", ("kind",),
        )
        self._m_sched_chunks = m.counter(
            "dli_sched_prefill_chunks_total",
            "prefill chunks interleaved into mixed scheduler launches",
        ).labels()
        self._m_sched_rows = m.counter(
            "dli_sched_decode_rows_total",
            "decode rows carried by mixed scheduler launches",
        ).labels()
        # fleet speculative-decoding families (pre-registered in
        # engine/engine.py): draft/accept/reject token flow, verify-row
        # launches by draft source, tokens-per-launch distribution
        self._m_spec_drafted = m.counter(
            "dli_spec_drafted_tokens_total",
            "draft tokens submitted in mixed-launch verify rows",
        ).labels()
        self._m_spec_accepted = m.counter(
            "dli_spec_accepted_tokens_total",
            "draft tokens accepted (matched the model's own argmax and "
            "were emitted)",
        ).labels()
        self._m_spec_rejected = m.counter(
            "dli_spec_rejected_tokens_total",
            "draft tokens rejected by the traced verify",
        ).labels()
        self._m_spec_launches = m.counter(
            "dli_spec_launches_total",
            "verify rows launched inside mixed scheduler steps, by draft "
            "source", ("mode",),
        )
        self._m_spec_hist = m.histogram(
            "dli_spec_tokens_per_launch",
            "tokens emitted per verify row (accepted drafts + the "
            "correction token; > 1 is the speculation win)",
            buckets=DEFAULT_SIZE_BUCKETS,
        ).labels()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="continuous-engine"
        )
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def _needs_solo(self, kwargs: dict) -> bool:
        """Contracts slots cannot honor (deterministic RNG stream, single-
        stream prefill logits, per-token logprob buffers) run solo on the
        wrapped engine — one condition shared by submit() and stream().
        Speculative requests run IN-FLEET on ragged paged chunked fleets
        (draft-then-verify rows inside the mixed launch; non-greedy /
        penalized ones simply decode plainly there) — the solo fallback
        remains only for seeded/debug contracts and for fleets without
        the mixed program."""
        if (
            kwargs.get("seed") is not None
            or bool(kwargs.get("debug"))
            or (bool(kwargs.get("speculative")) and not self._spec_capable)
            or bool(kwargs.get("logprobs"))
            # slots share one sampling program; a per-request [V] bias
            # isn't in the slot params
            or bool(kwargs.get("logit_bias"))
            # beam search is its own batched program
            or int(kwargs.get("num_beams", 1) or 1) > 1
        ):
            return True
        if kwargs.get("constraint") is not None:
            # constrained slots need the constrained slot program (dense
            # fleet only in this PR — the paged pool falls back) and a
            # fleet table the DFA can ever fit; otherwise the solo engine
            # serves the constraint with its own per-request tables
            if self.paged or not getattr(
                self.backend, "supports_constrained_slots", False
            ):
                return True
            try:
                art = self.engine._compile_constraint(kwargs["constraint"])
            except ValueError:
                return True  # solo re-raises into the 400 envelope
            if not self._ctable.fits(art):
                return True
        return False

    def _note_queue_locked(self):  # guarded-by: _cv
        """Refresh the global + per-(SLO class, tenant) queue-depth
        gauges (caller holds the lock). One helper so every queue
        mutation keeps both views consistent. Tenants ever seen stay in
        the gauge schema (so a drained tenant's series reads 0, not its
        stale last value)."""
        self._m_depth.set(len(self._queue))
        counts: dict = {}
        for r in self._queue:
            t = r.tenant or ""
            self._gauge_tenants.add(t)
            counts[(r.slo, t)] = counts.get((r.slo, t), 0) + 1
        for name in self._slo:
            for t in self._gauge_tenants:
                self._sched.set_depth(
                    name, counts.get((name, t), 0), tenant=t
                )

    def _class_depth_locked(self, cls_name: str) -> int:  # guarded-by: _cv
        return sum(1 for r in self._queue if r.slo == cls_name)

    def _cancel_env(self, req: _Request) -> dict:
        """The cancelled envelope (HTTP 499 at the edge; the router
        never re-dispatches it) + the cause-labeled counter."""
        self._m_cancelled.labels(cause=req.cancel_cause).inc()
        return {
            "error": "Error: request cancelled", "status": "failed",
            "error_type": "cancelled",
        }

    def _deadline_env(self, req: _Request, where: str = "") -> dict:
        """The deadline_exceeded envelope (HTTP 504 at the edge; the
        router never re-dispatches it — the budget is the REQUEST's
        property, not the replica's)."""
        self._m_deadline_exceeded.inc()
        suffix = f" {where}" if where else ""
        return {
            "error": f"Error: request exceeded its deadline_ms "
            f"budget{suffix}",
            "status": "failed",
            "error_type": "deadline_exceeded",
        }

    @staticmethod
    def _past_deadline(req: _Request, now: Optional[float] = None) -> bool:
        return req.deadline_at is not None and (
            now if now is not None else time.time()
        ) >= req.deadline_at

    def _enqueue(self, req: _Request) -> Optional[dict]:
        """Admit a request to the bounded queue. Returns an error envelope
        (caller delivers it OUTSIDE any lock — a streaming caller yields to
        a possibly-slow socket write) or None on success.

        SLO admission control (engine/scheduler.py): the request's class
        resolves here; a full queue AND an over-target sheddable class
        both shed with 429, and in BOTH cases Retry-After derives from
        the CLASS's queue drain estimate (depth x observed per-request
        service time), never the global queue depth — a deep batch
        backlog must not tell an interactive client to stay away."""
        cls = self._sched.classify(req.slo)
        req.slo = cls.name
        if self._past_deadline(req):
            # fail-fast: an already-expired request must not spend a
            # prefill launch or a single pool block (tests assert zero
            # allocations for these)
            return self._deadline_env(req, where="before admission")
        with self._cv:
            if self._closed:
                return {
                    "error": "Error: server shutting down", "status": "failed",
                    "error_type": "overloaded",
                }
            if self._draining:
                # graceful drain: the serving edge maps this to HTTP 503
                # with a Retry-After header — the load balancer's cue to
                # take this replica out while in-flight work finishes
                return {
                    "error": "Error: server draining", "status": "failed",
                    "error_type": "draining",
                }
            class_depth = self._class_depth_locked(cls.name)
            if len(self._queue) >= self.max_queue:
                log.warning("queue_full", depth=len(self._queue),
                            slo_class=cls.name)
                self.engine.flight.record(
                    "shed", reason="queue_full",
                    request_id=req.trace.request_id,
                    depth=len(self._queue), slo_class=cls.name,
                )
                self._m_shed.inc()
                self._sched.count_shed(cls.name)
                return {
                    "error": f"Error: request queue full ({self.max_queue})",
                    "status": "failed",
                    "error_type": "overloaded",
                    "slo_class": cls.name,
                    "retry_after_s": self._sched.retry_after_s(
                        cls, class_depth
                    ),
                }
            if req.tenant is not None and self._tenant_max_share < 1.0:
                # tenant quota: one tenant's queued share of the bounded
                # queue is capped (beyond a small absolute floor — the
                # share is meaningless at tiny depths) so a tenant
                # flooding the queue sheds before OTHER tenants start
                # eating 429s off the global queue-full check
                from .scheduler import MIN_SHED_DEPTH

                t_depth = sum(
                    1 for r in self._queue if r.tenant == req.tenant
                )
                t_cap = max(
                    MIN_SHED_DEPTH,
                    int(self.max_queue * self._tenant_max_share),
                )
                if t_depth >= t_cap:
                    log.warning(
                        "tenant_shed", tenant=req.tenant, depth=t_depth,
                        cap=t_cap, slo_class=cls.name,
                    )
                    self.engine.flight.record(
                        "shed", reason="tenant_quota",
                        request_id=req.trace.request_id,
                        tenant=req.tenant, depth=t_depth, cap=t_cap,
                    )
                    self._m_shed.inc()
                    self._m_tenant_shed.labels(tenant=req.tenant).inc()
                    return {
                        "error": (
                            f"Error: tenant {req.tenant!r} is at its "
                            f"queue quota ({t_cap} of {self.max_queue})"
                        ),
                        "status": "failed",
                        "error_type": "overloaded",
                        "slo_class": cls.name,
                        "tenant": req.tenant,
                        "retry_after_s": self._sched.retry_after_s(
                            cls, class_depth
                        ),
                    }
            if self._sched.should_shed(cls, class_depth):
                # the class's drain estimate already overruns its TTFT
                # target: admitting would burn prefill budget on a
                # request whose SLO is unmeetable — shed it now with the
                # class-local horizon
                log.warning(
                    "slo_shed", slo_class=cls.name, depth=class_depth,
                    ttft_target_s=cls.ttft_target_s,
                )
                self.engine.flight.record(
                    "shed", reason="slo_drain",
                    request_id=req.trace.request_id,
                    slo_class=cls.name, depth=class_depth,
                )
                self._m_shed.inc()
                self._sched.count_shed(cls.name)
                return {
                    "error": (
                        f"Error: {cls.name} queue drain estimate exceeds "
                        f"the {cls.ttft_target_s:g}s TTFT target"
                    ),
                    "status": "failed",
                    "error_type": "overloaded",
                    "slo_class": cls.name,
                    "retry_after_s": self._sched.retry_after_s(
                        cls, class_depth
                    ),
                }
            self._queue.append(req)
            self._note_queue_locked()
            self._cv.notify_all()
        return None

    def _adapter_reject(self, adapter, kwargs) -> Optional[dict]:
        """400-style envelope for adapter requests the fleet cannot
        serve — no attached pool (the fleet is not ragged-paged or
        engine_cfg.adapter_slots is 0), an unregistered adapter name, or
        a solo-contract request (the solo engine serves only the one
        merged/base model — runtime adapter selection lives in the
        fleet's paged launch programs). None = serveable."""
        if adapter is None:
            return None

        def env(msg):
            return {
                "error": f"Error: {msg}", "status": "failed",
                "error_type": "invalid_request", "adapter": adapter,
            }

        if self._adapters is None:
            return env(
                "adapter serving needs the ragged paged fleet with an "
                "attached adapter pool (engine_cfg.adapter_slots > 0)"
            )
        if not self._adapters.is_registered(adapter):
            return env(f"unknown adapter {adapter!r}")
        if self._needs_solo(kwargs):
            return env(
                "adapter requests cannot combine with solo-engine "
                "contracts (seed / debug / logprobs / logit_bias / "
                "beams / constraints)"
            )
        return None

    def submit(self, prompt: str, **kwargs) -> dict:
        # KV-fabric handoff surface (serving/kv_fabric.py): the hint is
        # consumed at admission; prefill_only serves the disaggregation
        # handshake's phase 1 — prefill (and shadow) the prompt, sample
        # one token, and only answer once the shadow copies have LANDED,
        # so the decode-class replica's immediate fetch finds the chain
        # resident instead of racing the copier thread.
        kv_hint = kwargs.pop("kv_hint", None)
        kv_push_to = kwargs.pop("kv_push_to", None) or None
        trace_ctx = kwargs.pop("trace_ctx", None)
        adapter = kwargs.pop("adapter", None) or None
        tenant = kwargs.pop("tenant", None) or None
        err = self._adapter_reject(adapter, kwargs)
        if err is not None:
            return err
        prefill_only = bool(kwargs.pop("prefill_only", False))
        if prefill_only:
            kwargs["max_tokens"] = 1
        if self._needs_solo(kwargs):
            return self.engine.generate(prompt, **kwargs)
        req = _Request(prompt, kwargs,
                       request_id=kwargs.pop("request_id", None),
                       kv_hint=kv_hint, adapter=adapter, tenant=tenant,
                       trace_ctx=trace_ctx)
        if trace_ctx is not None and trace_ctx.sampled:
            req.profiled = sample_decision(
                trace_ctx.trace_id, self._trace_rate
            )
        err = self._enqueue(req)
        if err is not None:
            return err
        req.done.wait()
        if prefill_only and isinstance(req.result, dict):
            if self._shadow is not None:
                self._shadow.flush(timeout_s=10.0)
            req.result.setdefault("prefill_only", True)
            if kv_push_to:
                # proactive chain push (the handoff's phase 1.5): the
                # chain is resident NOW — POST it to the decode replica
                # the router pre-picked, so phase 2's admission finds
                # the prefix host-resident instead of round-tripping a
                # pull. Any failure silently keeps the pull fallback.
                pushed = self._fabric_push(req, kv_push_to)
                if pushed:
                    req.result["kv_pushed"] = pushed
        return req.result

    def stream(self, prompt: str, **kwargs):
        """Generator of streaming events for one request.

        Yields `{"delta": str, "tokens_so_far": N}` as decode chunks land
        (first event after prefill, then one per chunk with new tokens) and
        finally the standard response envelope (with "done": true). The
        caller iterates on its own thread (e.g. an HTTP handler writing
        NDJSON lines); the worker thread pushes into a per-request queue.

        Seeded / debug requests cannot stream (they run solo on the
        wrapped engine, which decodes entirely on-device) — one final
        envelope event is yielded instead. Speculative requests stream
        normally on spec-capable fleets (verify-row emissions land per
        fetched step, like any chunk).
        """
        kv_hint = kwargs.pop("kv_hint", None)
        trace_ctx = kwargs.pop("trace_ctx", None)
        adapter = kwargs.pop("adapter", None) or None
        tenant = kwargs.pop("tenant", None) or None
        err = self._adapter_reject(adapter, kwargs)
        if err is not None:
            yield {**err, "done": True}
            return
        if self._needs_solo(kwargs):
            out = self.engine.generate(prompt, **kwargs)
            out["done"] = True
            yield out
            return
        import queue as _queue

        q: _queue.Queue = _queue.Queue()
        req = _Request(prompt, kwargs, stream_q=q,
                       request_id=kwargs.pop("request_id", None),
                       kv_hint=kv_hint, adapter=adapter, tenant=tenant,
                       trace_ctx=trace_ctx)
        if trace_ctx is not None and trace_ctx.sampled:
            req.profiled = sample_decision(
                trace_ctx.trace_id, self._trace_rate
            )
        err = self._enqueue(req)  # error yielded OUTSIDE the engine lock:
        if err is not None:  # the consumer may block on a slow socket write
            yield {**err, "done": True}
            return
        try:
            while True:
                ev = q.get()
                yield ev
                if ev.get("done"):
                    return
        finally:
            # consumer abandoned the generator mid-stream (client socket
            # dropped, handler called close()): cancel so the slot frees
            # for queued requests instead of decoding to its full budget
            if not req.done.is_set():
                self.cancel(req)

    def cancel(self, req: _Request, cause: str = "disconnect"):
        """Cancel a request: dequeue it if still waiting (queue or the
        preemption resume queue), or flag it for the worker to kill its
        slot — and free its blocks/constraint row — at the next launch
        boundary. `cause` labels dli_cancelled_total."""
        req.cancel_cause = cause
        with self._cv:
            if req in self._queue or req in self._resume:
                if req in self._queue:
                    self._queue.remove(req)
                    self._note_queue_locked()
                else:
                    self._resume.remove(req)
                req.result = self._cancel_env(req)
                self._push_final(req)
                return
            req.cancelled = True
            # wake the worker: a cancel must free the slot within one
            # scheduler step even when nothing else is queued
            self._cv.notify_all()

    def _stream_tokens(self, req: _Request, final: bool = False, pre=None):
        """Push the not-yet-streamed suffix of req's text (worker thread).

        Deltas are computed on the FULL decoded text, and text ending in
        U+FFFD is held back until more tokens arrive: a multi-byte grapheme
        whose bytes straddle a chunk boundary decodes to a replacement char
        now and the real character later AT THE SAME LENGTH, so streaming
        it would make the joined deltas diverge from the final response.
        final=True flushes everything (a genuine trailing U+FFFD included)
        so concat(deltas) == response exactly. Text past a textual stop
        sequence is never streamed — and because a stop string may SPAN a
        chunk boundary, the last max(len(stop))-1 characters are held back
        until the next chunk resolves them (vLLM-style hold-back); the
        final flush emits exactly up to the truncation.
        pre: optional (gen_ids, text, hit) from the caller's _gen_text —
        avoids re-decoding the full sequence per chunk."""
        gen_ids, text, _ = pre if pre is not None else self._gen_text(req)
        if not gen_ids:
            return
        if not final:
            text = text.rstrip("�")
            stop = req.kwargs.get("stop") or ()
            hold = max((len(s) for s in stop if s), default=0) - 1
            if hold > 0:
                text = text[: max(len(req.streamed_text), len(text) - hold)]
        if len(text) > len(req.streamed_text):
            delta = text[len(req.streamed_text):]
            req.streamed_text = text
            req.stream_q.put({"delta": delta, "tokens_so_far": len(gen_ids)})

    @property
    def ready(self) -> bool:
        """Load-balancer readiness: False while draining, while the
        supervisor is mid-restart, or once the scheduler is closed or
        dead. Liveness (/health, process up) is deliberately separate —
        a restart-looping scheduler is alive but should take no new
        traffic."""
        return not (
            self._draining or self._restarting or self._dead or self._closed
        )

    def _work_pending(self) -> bool:
        """Anything the fleet still owes a response for: queued, assigned
        to a slot, mid-admission (popped from the queue but not yet
        spliced — invisible to both), or salvaged awaiting re-admission."""
        return bool(
            self._queue
            or any(r is not None for r in self._assignment)
            or self._admitting is not None
            or self._recovery
            or self._resume
        )

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting NEW requests (draining envelope
        → HTTP 503 + Retry-After at the serving edge), then wait for the
        queue and every in-flight slot to finish, up to deadline_s.
        Returns True when fully drained; stragglers past the deadline are
        failed by the caller's close(). Idempotent."""
        t0 = time.time()
        self.engine.flight.record("drain", deadline_s=deadline_s)
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        drained = True
        with self._cv:
            while self._work_pending():
                if self._closed or self._dead:
                    # a dead scheduler cannot drain its backlog; close()
                    # already failed (or will fail) the stragglers
                    drained = not self._work_pending()
                    break
                left = (
                    None if deadline_s is None
                    else deadline_s - (time.time() - t0)
                )
                if left is not None and left <= 0:
                    drained = False
                    break
                self._cv.wait(
                    timeout=0.1 if left is None else min(left, 0.1)
                )
        if self._shadow is not None and self._restore_dir:
            # warm handoff for the respawn (the router's rolling-restart
            # path): persist the shadow — blocks + chain metadata — so
            # `--restore-dir` starts the successor with a warm
            # block-prefix cache instead of a cold pool
            try:
                self._shadow.flush(timeout_s=5.0)
                self._shadow.save(self._restore_dir)
            except Exception as e:  # noqa: BLE001 - a failed persist only
                log.error("shadow_persist_failed", error=str(e))  # colder
        self._m_drain.observe(time.time() - t0)
        log.info(
            "continuous_drained", ok=drained,
            seconds=round(time.time() - t0, 3),
        )
        return drained

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        fail = {
            "error": "Error: server shutting down", "status": "failed",
            "error_type": "overloaded",
        }
        with self._cv:
            pending = self._queue[:] + self._resume[:]
            self._queue.clear()
            self._resume.clear()
            self._note_queue_locked()
        for req in pending + [r for r in self._assignment if r is not None]:
            if req.result is None:
                req.result = dict(fail)
            self._push_final(req)
        if self._shadow is not None:
            self._shadow.close()

    def warmup(self) -> dict:
        """Compile the slot programs (scratch prefill for the smallest
        bucket, insert_slot, decode_slots chunk, pack_chunk) by serving one
        real throwaway request through the fleet. The wrapped engine's
        warmup() separately covers every prefill bucket — together no
        client request pays jit latency (p50-TTFT discipline)."""
        t0 = time.time()
        req = _Request(
            "warmup",
            dict(max_tokens=self.chunk_steps + 2, greedy=True, chat=False),
        )
        # compile-only traffic: its multi-second jit TTFT must not land in
        # /stats (it would skew the very p50 TTFT warmup exists to protect)
        # nor count as a served request
        req.record = False
        err = self._enqueue(req)
        if err is not None:
            return {"ok": False, "seconds": 0.0, **err}
        req.done.wait()
        out = {
            "ok": (req.result or {}).get("status") == "success",
            "seconds": round(time.time() - t0, 2),
        }
        log.info("continuous_warmup", **out)
        return out

    def stats(self) -> dict:
        with self._cv:
            out = {
                "slots": self.n_slots,
                "occupied": sum(r is not None for r in self._assignment),
                "queued": len(self._queue),
                "admitted": self.admitted,
                "completed": self.completed,
                "peak_occupancy": self.peak_occupancy,
                "chunk_steps": self.chunk_steps,
            }
        out["preemption"] = {
            "policy": self.preempt_policy,
            "max_per_request": self.max_preemptions,
            "preempted_total": self.preempted_total,
            "parked": len(self._resume),
        }
        out["supervisor"] = {
            "ready": self.ready,
            "draining": self._draining,
            "dead": self._dead,
            "restarts": self.restarts_total,
            "recovered": self.recovered_total,
            "poisoned": self.poisoned_total,
            "consecutive_crashes": self._consecutive_crashes,
            "restart_budget": self.restart_budget,
        }
        if self.paged:
            out["paged"] = {
                "block_size": self.kv_block_size,
                "pool_blocks": self._alloc.n_blocks,
                "free_blocks": self._alloc.free_blocks,
                "shared_blocks": self._alloc.shared_blocks,
                "cached_blocks": (
                    self._bpx.stats()["cached_blocks"]
                    if self._bpx is not None else 0
                ),
                "ragged_prefill": self._ragged,
            }
            if self._ragged:
                out["paged"]["ragged_width"] = self._ragged_width
        if self._shadow is not None:
            out["shadow"] = {
                **self._shadow.stats(),
                "restored_blocks": self.shadow_restored_total,
            }
        if self._fabric is not None:
            out["kv_fabric"] = {
                **self._fabric.stats(),
                "serving": self.fabric_serving,
            }
        if self._adapters is not None:
            out["adapters"] = self._adapters.stats()
        out["slo"] = {
            "default": self._sched.default_name,
            "classes": {
                name: {
                    "ttft_target_s": c.ttft_target_s,
                    "tpot_target_s": c.tpot_target_s,
                    "weight": c.weight,
                    "sheddable": c.sheddable,
                    "ttft_ewma_s": self._sched.feedback[name].ttft_ewma,
                    "tpot_ewma_s": self._sched.feedback[name].tpot_ewma,
                }
                for name, c in self._slo.items()
            },
        }
        if self._chunked:
            out["scheduler"] = {
                "chunked_prefill": True,
                "step_width": self._sched_width,
                "tile": self._ragged_tile,
                "prefilling": len(self._jobs),
            }
        if self._spec_capable:
            out["speculative"] = {
                "mode": "draft_model" if self._draft_mode else "ngram",
                "draft_len": self._spec_k_max,
                "fleet_wide": self._spec_auto,
                "device_meta": self._spec_devmeta,
                "launches": self.spec_launches,
                "drafted_tokens": self.spec_drafted,
                "accepted_tokens": self.spec_accepted,
                "inflight_rows": len(self._spec_inflight) + sum(
                    len(v) for v in self._spec_pending.values()
                ),
                # verify rows launched while an earlier one was still
                # unfetched — >0 proves lag-pipelined speculation
                "pipelined_launches": self.spec_pipelined,
            }
        cstats = self._ctable.stats()
        if cstats["resident"]:
            out["constraints"] = cstats
        if self._prefix is not None:
            out["prefix_cache"] = self._prefix.stats()
        elif self._bpx is not None:
            out["prefix_cache"] = self._bpx.stats()
        return out

    # -- worker thread -------------------------------------------------------
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _loop(self):
        """Supervisor: a scheduler crash is recoverable and request-
        scoped, not fleet-fatal. Each exception out of _loop_inner goes
        through one _supervise round — release every fleet-held resource,
        strike/quarantine suspects, rebuild the fleet, re-admit salvaged
        requests — under a bounded consecutive-crash budget with
        exponential backoff. A dead worker must never hang clients: the
        give-up path fails everything with clean envelopes."""
        while True:
            try:
                self._loop_inner()
                return  # clean exit: close() flipped _closed
            except Exception as e:  # noqa: BLE001 - contained by the supervisor
                if not self._supervise(e):
                    return

    def _casualties(self) -> list:
        """Detach every live in-flight request (plus the one mid-
        admission, if any) from the dead fleet. Order: running tenants
        first, the just-admitting request last — recovery re-admits in
        this order, so vindicated tenants re-enter before the newest
        (most suspicious) one."""
        with self._cv:
            running = [
                r for r in self._assignment
                if r is not None and not r.done.is_set()
            ]
            self._assignment = [None] * self.n_slots
            admitting, self._admitting = self._admitting, None
        # chunked-prefill state dies with the fleet: jobs' requests are
        # casualties above (they sat in _assignment from job start), and
        # progress resets — the rebuilt pool holds none of their chunks,
        # so recovery re-plans each salvage from its last durable
        # boundary (zero; `done` was chunk-aligned by construction)
        self._jobs = []
        self._prefilling = {}
        self._host_pos[:] = 0
        # speculation bookkeeping dies with the fleet too: unfetched
        # verify rows are unfetched launches (their emissions drop, the
        # salvage record holds fetched tokens only — same contract);
        # pending device-meta windows and the chunk-fetch gate reset
        # with them
        self._spec_inflight.clear()
        self._spec_pending.clear()
        self._chunk_unfetched = 0
        self._row_inflight[:] = 0
        if (
            admitting is not None and admitting not in running
            and not admitting.done.is_set()
        ):
            running.append(admitting)
        return running

    def _release_fleet_resources(self, reqs: list):
        """Return every device/host resource the dead fleet holds:
        constraint-table rows, paged pool blocks, cached block-prefix
        chains, block-table rows. Shared by the restart and the give-up
        paths — leaking these on loop death (blocks never decref'd, rows
        never freed) was the failure mode this layer exists to fix."""
        for req in reqs:
            if req.cart is not None:
                self._ctable.release(req.cart[0].key)
                req.cart = None
            if self.paged and req.block_ids is not None:
                self._alloc.decref(req.block_ids)
                req.block_ids = None
            req.adapter_page = None
        if self._adapters is not None:
            # adapter-page refcounts reset wholesale: every holder was
            # detached above, and the device content SURVIVES the crash
            # (the lora leaves live in params, never in a donated launch
            # buffer) — recovery re-admissions re-acquire still-resident
            # pages for free
            self._adapters.reset_refs()
        if self._bpx is not None:
            # cached chains point into the pool buffer the rebuild below
            # replaces — drop them (and the index's refs) wholesale
            self._bpx.clear()
        if self.paged:
            self._table[:] = 0
            self._table_dev = None
            self._slot_pages[:] = 0
            if self._alloc.outstanding:
                # the explicit releases above must zero the books; a
                # mismatch is an accounting bug — surface it loudly, then
                # reset so the restarted fleet has no phantom holders
                log.error(
                    "kv_pool_leak_on_crash",
                    outstanding=self._alloc.outstanding,
                )
                self._alloc.reset()

    def _rebuild_fleet(self):
        """Fresh device-side fleet state for the restarted loop. Buffers
        the crashed iteration may have donated mid-program (fleet cache /
        pool, scratch) are rebuilt outright — cheaper than proving a
        half-executed donation chain left them intact. The dense prefix
        cache keeps its snapshots (standalone arrays, never donated)."""
        if self.paged:
            self.cache = self.backend.init_paged_pool(
                self._pool_blocks, self.kv_block_size
            )
            self._table = np.zeros(
                (self.n_slots, self._max_blocks), np.int32
            )
            self._table_dev = None
        else:
            self.cache = self.backend.init_cache(
                self.n_slots, self.slot_max_seq
            )
        self._scratch = (
            None if self._ragged
            else self.backend.init_cache(1, self._scratch_seq)
        )
        self.state, self.sparams = G.init_slots(
            self.n_slots, self.cfg.vocab_size
        )
        self._fsm = jnp.zeros((self.n_slots,), jnp.int32)
        if self._draft_mode:
            # the draft pool is rebuilt outright like the target pool
            # (it may have been donated mid-crash); its content is pure
            # draft-quality state — recovered tenants re-prefill it
            # through the ordinary admission fill
            self._dpool = self._P.init_pool(
                self._dcfg, self._pool_blocks, self.kv_block_size
            )

    def _shadow_capture(self, req: _Request, written: Optional[int] = None):
        """Hand req's newly FILLED pool blocks to the shadow copier
        (worker thread; engine/shadow.py). `written` = tokens known to
        be in the pool for this row (mid-chunked-prefill callers pass
        job progress); None derives it from the fetched token stream —
        the last sampled token's K/V is not yet written, hence the -1.
        The gather is dispatched AFTER the launch that filled the
        blocks (device execution order makes the bytes final); only the
        enqueue happens here, the device->host copy runs on the shadow
        thread — the scheduler loop never blocks."""
        if self._shadow is None or req.block_ids is None or req.ids is None:
            return
        if req.adapter is not None:
            # adapter-conditioned KV never enters the shadow: the store
            # (and the fabric it serves) keys chains by TOKEN CONTENT
            # alone, and an adapter's KV differs from the base model's
            # for the same tokens — persisting it would poison warm
            # restores and cross-replica imports with wrong-model bytes
            return
        bs = self.kv_block_size
        if written is None:
            head = (
                [req.first_id]
                if req.first_id is not None
                and req.first_id not in self.cfg.all_stop_ids else []
            )
            gen = head + req.tokens
            written = len(req.ids) + max(0, len(gen) - 1)
            seq_tokens = req.ids + gen
        else:
            seq_tokens = req.ids
        full = min(written // bs, len(req.block_ids))
        if full <= req.shadow_depth:
            return
        # chaos hook BEFORE the dedup: a repeat prompt whose blocks are
        # all resident must still exercise the shadow_copy drill
        faults.check("shadow_copy", tag=req.prompt)
        new_keys, new_blocks = [], []
        for i in range(req.shadow_depth, full):
            key = tuple(seq_tokens[: (i + 1) * bs])
            if not self._shadow.has(key):
                new_keys.append(key)
                new_blocks.append(int(req.block_ids[i]))
        req.shadow_depth = full
        if not new_keys:
            return
        W = self._shadow_gather_w
        for off in range(0, len(new_keys), W):
            keys = new_keys[off : off + W]
            ids = new_blocks[off : off + W]
            padded = ids + [ids[-1]] * (W - len(ids))  # one program, any n
            dev = self.backend.gather_shadow_blocks(
                self.cache, jnp.asarray(padded, jnp.int32)
            )
            self._shadow.put_async(
                keys, jax.tree.leaves(dev), self._mutation_seq
            )

    def _restore_shadow(self) -> int:
        """Scatter shadowed chains back into a FRESH pool (one restore
        launch) and register them into the block-prefix index, so
        salvage re-admissions — and post-restart traffic — hit them
        through the ordinary prefix machinery. Runs on the worker
        thread strictly BEFORE any re-admission (start of _loop_inner),
        under the supervisor: a crash mid-restore is contained like any
        scheduler crash, the partial registration is released by the
        next round's clear(), and the restore simply runs again (the
        double-fault drill in tests/test_recovery.py). Returns blocks
        restored."""
        if self._shadow is None or self._bpx is None:
            return 0
        # pending captures from before the crash land first, so the
        # restore depth is deterministic (the chaos matrix depends on it)
        self._shadow.flush(timeout_s=10.0)
        faults.check("shadow_copy", tag="restore")
        # leave one slot-class of headroom: restored chains are
        # evictable (refcount 1, index-held), but admission should not
        # have to evict just to place the first request
        budget = self._alloc.free_blocks - self._max_blocks
        entries, leaf_keys = self._shadow.select(budget)
        if not entries:
            return 0
        blocks = self._alloc.alloc(len(entries))
        if blocks is None:
            return 0
        bs = self.kv_block_size
        # pad to the fixed restore width (pre-warmed program): pad rows
        # repeat row 0's data and scatter it into the write-only TRASH
        # block — same discard as ungated pp microsteps
        W = self._shadow_restore_w
        pad = (-len(entries)) % W
        ids_padded = blocks + [self._P.TRASH_BLOCK] * pad
        try:
            stacked = []
            for i in range(len(entries[0][1].leaves)):
                arr = np.stack([e.leaves[i] for _, e in entries])
                if pad:
                    arr = np.concatenate(
                        [arr, np.repeat(arr[:1], pad, axis=0)]
                    )
                stacked.append(jnp.asarray(arr))
            restored = jax.tree.unflatten(
                jax.tree.structure(self.cache), stacked
            )
            self.cache = self.backend.restore_shadow_blocks(
                self.cache, restored, jnp.asarray(ids_padded, jnp.int32)
            )
        except Exception as e:  # noqa: BLE001 - a bad persisted shadow
            # (config drift across a restart) must cold-start, not
            # crash-loop the supervisor
            log.warning("shadow_restore_invalid", error=str(e))
            self._alloc.decref(blocks)
            self._shadow.clear()
            return 0
        assigned = {key: b for (key, _), b in zip(entries, blocks)}
        for leaf in leaf_keys:
            row_blocks = [
                assigned[leaf[: (i + 1) * bs]]
                for i in range(len(leaf) // bs)
            ]
            self._bpx.import_chain(list(leaf), row_blocks)
        # the index holds its own reference per cached block now; drop
        # the allocation's — restored chains end at refcount 1
        # (index-held, evictable), the steady-state cached-chain invariant
        self._alloc.decref(blocks)
        n = len(entries)
        self._shadow.count_pool_promotion(n)
        self.shadow_restored_total += n
        self._m_shadow_restored.inc(n)
        log.info(
            "shadow_restored", blocks=n, chains=len(leaf_keys),
            free_blocks=self._alloc.free_blocks,
        )
        return n

    # -- cross-replica KV fabric (serving/kv_fabric.py; ARCHITECTURE.md
    # "KV fabric & disaggregation") ------------------------------------------
    def fabric_chain(self, digest: str):
        """Wire bytes for the resident shadow chain ending at `digest`,
        or None (the server's GET /kv/{digest} -> 404). Any thread: the
        shadow store is lock-protected and the encode reads host arrays
        only — the HTTP handler serves peers without touching the
        scheduler loop."""
        if not self.fabric_serving:
            return None
        from ..serving.kv_fabric import serve_chain

        return serve_chain(self._shadow, digest)

    def fabric_chain_stream(self, digest: str):
        """(n_chunks, tier, frame iterator) for the resident chain ending
        at `digest`, or None — the server's streamed GET /kv/{digest}
        body (X-KV-Stream: 1). Same any-thread contract as
        fabric_chain, but frames encode lazily, one block at a time."""
        if not self.fabric_serving:
            return None
        from ..serving.kv_fabric import serve_chain_stream

        return serve_chain_stream(self._shadow, digest)

    def fabric_digest_tier(self, digest: str):
        """The shallowest shadow tier holding `digest` ("host" | "disk" |
        None) — the server labels X-KV-Tier and bytes{tier} off this."""
        if not self.fabric_serving:
            return None
        return self._shadow.digest_tier(digest)

    def fabric_accept_push(self, data: bytes):
        """The POST /kv route's body (any thread): validate a peer's
        proactively pushed chain against its OWN content key (the
        digest is recomputed from the payload's tokens — nothing
        external to trust) and land it in the host shadow tier, where
        the phase-2 admission's promotion path scatters it pool-ward
        without a pull round-trip. Returns the response dict, or None
        (-> 400) on a payload that fails validation."""
        if not self.fabric_serving or self._shadow is None:
            return None
        from ..serving.kv_fabric import FabricPayloadError, decode_push

        try:
            digest, keys, per_block = decode_push(
                data, self.kv_block_size
            )
        except FabricPayloadError as e:
            log.warning("fabric_push_rejected", error=str(e))
            return None
        n = self._shadow.put_host(keys, per_block, self._mutation_seq)
        self.engine.flight.record(
            "fabric_push_in", digest=str(digest)[:16], blocks=n,
        )
        return {"accepted": n, "digest": digest}

    def fabric_digests(self, limit: Optional[int] = None) -> list:
        """Resident chain digests, MRU first, host tier before disk —
        the /health field the router's residency bootstrap reads.
        Capped (default --kv-health-digests): the disk tier makes the
        full resident set unbounded, and bootstrap payloads must stay
        O(1) however deep it grows."""
        if not self.fabric_serving:
            return []
        if limit is None:
            limit = self._kv_health_digests
        return self._shadow.resident_digests(limit=limit)

    def _fabric_prefetch(self, req: _Request, ids: list):
        """Consume req's handoff hint (worker thread, at the admission
        host boundary — strictly BEFORE the prefix plan, so a successful
        import is just a deeper local hit). The fallback ladder: local
        chain already covers the prompt -> skip; fetch 404 / dead peer /
        timeout / failed recheck -> local prefill; pool too full to place
        the chain -> import what fits (a chain prefix is still a valid
        chain). Nothing here can fail the request."""
        hint, req.kv_hint = req.kv_hint, None
        if (
            hint is None or self._fabric is None or self._bpx is None
            or not self.paged
        ):
            return
        peer = hint.get("peer") if isinstance(hint, dict) else None
        digest = hint.get("digest") if isinstance(hint, dict) else None
        if not peer or not digest:
            return
        bs = self.kv_block_size
        # deepest depth the planner could ever use (it caps reuse to
        # leave >= 1 tail token); a local chain at that depth makes the
        # fetch pure waste
        cap = max(0, (len(ids) - 1) // bs) * bs
        p0_local, _, _ = self._bpx.lookup(ids)
        if cap <= 0 or p0_local >= cap:
            return
        if self._shadow is not None and self._shadow.has_resident(
            tuple(ids[:cap])
        ):
            # a proactive push (or an earlier demotion) already landed
            # the full chain in the local tier hierarchy: the promotion
            # pass scatters it without a wire round-trip
            return
        streamed = self._fabric_stream
        tier = ""
        if streamed:
            res = self._fabric.fetch_stream(
                peer, digest, bs, ctx=req.trace_ctx,
                request_id=req.trace.request_id,
                store=self.engine.trace_store,
            )
            hit = False
            if res is not None:
                _n_chunks, tier, blocks_iter = res
                hit, req.fabric_blocks = self._import_fabric_stream(
                    blocks_iter
                )
        else:
            fetched = self._fabric.fetch(
                peer, digest, bs, ctx=req.trace_ctx,
                request_id=req.trace.request_id,
                store=self.engine.trace_store,
            )
            hit = fetched is not None
            tier = getattr(self._fabric, "last_tier", "") if hit else ""
        self.engine.flight.record(
            "fabric_fetch", request_id=req.trace.request_id, peer=peer,
            digest=str(digest)[:16], hit=hit, tier=tier,
            streamed=streamed,
        )
        if not streamed and fetched is not None:
            keys, leaves = fetched
            req.fabric_blocks = self._import_fabric_chain(keys, leaves)

    def _import_fabric_chain(self, keys: list, per_block_leaves: list) -> int:
        """Scatter a verified fetched chain into the pool (the SAME
        pre-warmed restore program warm recovery uses), register it into
        the block-prefix index, and feed it to the local shadow so this
        replica can onward-serve it through /kv. Returns blocks imported
        (0 when the pool has no headroom — local prefill still works)."""
        # one slot-class of headroom, like _restore_shadow: an import
        # must never make the admission it serves unplaceable. Under
        # steady-state load the free list is empty while the pool is
        # full of COLD refcount-1 cached chains — reclaim those first
        # (the same evict-and-retry the admission path uses) so tier
        # promotion is never starved by its own tier-0 occupancy.
        budget = self._alloc.free_blocks - self._max_blocks
        if budget < len(keys) and self._bpx is not None:
            self._bpx.evict(len(keys) - budget)
            budget = self._alloc.free_blocks - self._max_blocks
        if budget <= 0:
            return 0
        if len(keys) > budget:
            keys = keys[:budget]
            per_block_leaves = per_block_leaves[:budget]
        blocks = self._alloc.alloc(len(keys))
        if blocks is None:
            return 0
        W = self._shadow_restore_w
        pad = (-len(keys)) % W
        ids_padded = blocks + [self._P.TRASH_BLOCK] * pad
        try:
            stacked = []
            for j in range(len(per_block_leaves[0])):
                arr = np.stack([pb[j] for pb in per_block_leaves])
                if pad:
                    arr = np.concatenate(
                        [arr, np.repeat(arr[:1], pad, axis=0)]
                    )
                stacked.append(jnp.asarray(arr))
            restored = jax.tree.unflatten(
                jax.tree.structure(self.cache), stacked
            )
            self.cache = self.backend.restore_shadow_blocks(
                self.cache, restored, jnp.asarray(ids_padded, jnp.int32)
            )
        except Exception as e:  # noqa: BLE001 - a leaf-shape mismatch
            # (peer config drift the digest cannot see) must degrade to
            # a cold prefill, never crash the scheduler
            log.warning("fabric_import_invalid", error=str(e))
            self._alloc.decref(blocks)
            return 0
        self._bpx.import_chain(list(keys[-1]), blocks)
        if self._shadow is not None:
            self._shadow.put_host(
                keys, per_block_leaves, self._mutation_seq
            )
            self._shadow.count_pool_promotion(len(keys))
        # the index now holds its reference per cached block; drop the
        # allocation's — imported chains end refcount-1 (evictable),
        # exactly like restored ones
        self._alloc.decref(blocks)
        log.info(
            "fabric_imported", blocks=len(keys),
            free_blocks=self._alloc.free_blocks,
        )
        return len(keys)

    def _scatter_stream_batch(self, batch: list, keys: list,
                              leaves_kept: list, blocks: list) -> bool:
        """Scatter one batch of streamed (key, leaves) frames into
        freshly allocated pool blocks through the pre-warmed restore
        program — the streamed import's unit of network/device overlap
        (JAX dispatches the scatter asynchronously, so the device works
        while the next frames are still on the wire). Appends to the
        caller's ledgers only on success; False = pool dry or a
        leaf-shape mismatch (the caller keeps its already-scattered
        prefix — a chain prefix is still a valid chain)."""
        blk = self._alloc.alloc(len(batch))
        if blk is None and self._bpx is not None:
            # cold cached chains are reclaimable, exactly as at admission
            self._bpx.evict(len(batch) - self._alloc.free_blocks)
            blk = self._alloc.alloc(len(batch))
        if blk is None:
            return False
        W = self._shadow_restore_w
        pad = (-len(batch)) % W
        ids_padded = blk + [self._P.TRASH_BLOCK] * pad
        try:
            stacked = []
            for j in range(len(batch[0][1])):
                arr = np.stack([leaves[j] for _, leaves in batch])
                if pad:
                    arr = np.concatenate(
                        [arr, np.repeat(arr[:1], pad, axis=0)]
                    )
                stacked.append(jnp.asarray(arr))
            restored = jax.tree.unflatten(
                jax.tree.structure(self.cache), stacked
            )
            self.cache = self.backend.restore_shadow_blocks(
                self.cache, restored, jnp.asarray(ids_padded, jnp.int32)
            )
        except Exception as e:  # noqa: BLE001 - peer leaf-shape drift
            log.warning("fabric_stream_scatter_invalid", error=str(e))
            self._alloc.decref(blk)
            return False
        for (key, leaves), b in zip(batch, blk):
            keys.append(key)
            leaves_kept.append(leaves)
            blocks.append(b)
        # jaxlint: disable=resource-lifecycle -- blk handed to the caller's `blocks` ledger: registered on final-digest verify or decref'd on stream failure
        return True

    def _import_fabric_stream(self, blocks_iter) -> tuple:
        """Consume a verified /kv stream (kv_fabric.fetch_stream's block
        iterator), scattering frames into the pool in restore-width
        batches AS THEY ARRIVE — decode's tail prefill overlaps the
        pull instead of waiting behind a whole-manifest buffer. Nothing
        is REGISTERED until the stream finishes cleanly (the iterator's
        final content-key recheck): on tamper, truncation, or a died
        socket mid-stream the scattered-but-unregistered blocks are
        simply decref'd — unreachable garbage, bit-identical fallback
        to local prefill, the same bar the whole-blob path meets.
        Returns (verified, blocks_imported); budget-truncated imports
        still drain and verify every frame before registering the
        prefix that fit."""
        # cold refcount-1 cached chains count toward the budget — the
        # per-batch scatter evicts them on demand (same reclaim the
        # admission path uses), so a pool full of cold prefixes never
        # starves a streamed import
        budget = (
            self._alloc.free_blocks
            + (self._bpx.evictable_blocks() if self._bpx is not None else 0)
            - self._max_blocks
        )
        if budget <= 0:
            blocks_iter.close()  # settles the client's hit/miss + span
            return False, 0
        W = self._shadow_restore_w
        keys: list = []  # scattered, parents-first
        leaves_kept: list = []
        blocks: list = []  # their pool ids, aligned
        batch: list = []
        pool_dry = False
        verified = False
        try:
            for key, leaves in blocks_iter:
                if pool_dry or len(keys) + len(batch) >= budget:
                    continue  # verify-drain the tail; import what fit
                batch.append((key, leaves))
                if len(batch) == W:
                    if not self._scatter_stream_batch(
                        batch, keys, leaves_kept, blocks
                    ):
                        pool_dry = True
                    batch = []
            if batch and not pool_dry:
                self._scatter_stream_batch(
                    batch, keys, leaves_kept, blocks
                )
            verified = True
        except Exception as e:  # noqa: BLE001 - FabricPayloadError /
            # socket death mid-stream: one outcome, local prefill
            log.warning("fabric_stream_rejected", error=str(e))
        finally:
            blocks_iter.close()
        if not verified or not keys:
            if blocks:
                self._alloc.decref(blocks)
            return verified, 0
        self._bpx.import_chain(list(keys[-1]), blocks)
        if self._shadow is not None:
            self._shadow.put_host(
                keys, leaves_kept, self._mutation_seq
            )
            self._shadow.count_pool_promotion(len(keys))
        self._alloc.decref(blocks)
        log.info(
            "fabric_stream_imported", blocks=len(keys),
            free_blocks=self._alloc.free_blocks,
        )
        return True, len(keys)

    def _promote_local_chain(self, req: _Request, ids: list):
        """Tier promotion at admission (worker thread, after any fabric
        prefetch, strictly BEFORE the prefix plan): when the shadow
        hierarchy — host tier or DISK tier — holds a deeper contiguous
        chain for this prompt than the pool's block-prefix index does,
        load it (disk hits promote host-ward inside entries_for, each
        chunk file content-key-verified) and scatter it through the
        same import path a fabric fetch uses. A disk-resident warm
        prefix re-enters in one restore launch instead of a cold
        re-prefill; a corrupt chunk file rejects into exactly that cold
        re-prefill. Nothing here can fail the request."""
        if (
            self._shadow is None or self._bpx is None or not self.paged
            or req.adapter is not None  # adapter KV is fenced from
            # every token-keyed reuse surface (PR 16)
        ):
            return
        bs = self.kv_block_size
        cap = max(0, (len(ids) - 1) // bs) * bs
        if cap <= 0:
            return
        p0_local, _, _ = self._bpx.lookup(ids)
        if p0_local >= cap:
            return
        depth = 0
        for nb in range(cap // bs, p0_local // bs, -1):
            if self._shadow.has_resident(tuple(ids[: nb * bs])):
                depth = nb
                break
        if depth == 0:
            return
        keys = [tuple(ids[: (i + 1) * bs]) for i in range(depth)]
        entries = self._shadow.entries_for(keys)
        if entries is None:
            return  # churned out / corrupt chunk file: cold prefill
        imported = self._import_fabric_chain(
            keys, [e.leaves for e in entries]
        )
        if imported:
            req.promoted_blocks = imported
            self.engine.flight.record(
                "tier_promote", request_id=req.trace.request_id,
                blocks=imported, depth=depth * bs,
            )

    def _fabric_push(self, req: _Request, peer_url: str) -> int:
        """Phase 1.5 of the prefill->decode handoff: encode this
        finished request's deepest shadow chain and POST it to the
        decode replica the router pre-picked (X-KV-Push-To), so phase
        2's admission finds the prefix already host-resident there —
        no pull round-trip on the decode critical path. Runs on the
        submit() caller's HTTP thread AFTER the shadow flush (the chain
        is resident by construction), never the scheduler loop. Any
        failure returns 0 — the pull path remains the fallback."""
        res = req.result if isinstance(req.result, dict) else None
        ds = (res or {}).get("kv_digests") or []
        if not ds or self._fabric is None:
            return 0
        digest = ds[-1]  # deepest chain the decode peer will want
        data = self.fabric_chain(digest)
        if data is None:
            return 0
        accepted = self._fabric.push_chain(
            peer_url, data, ctx=req.trace_ctx,
            request_id=req.trace.request_id,
            store=self.engine.trace_store,
        )
        self.engine.flight.record(
            "fabric_push", request_id=req.trace.request_id,
            peer=peer_url, digest=str(digest)[:16],
            accepted=-1 if accepted is None else accepted,
        )
        return accepted or 0

    # -- SLO-aware KV preemption (graceful degradation under memory
    # pressure; ARCHITECTURE.md "Preemption & cancellation") ----------------
    def _alloc_with_pressure(self, req: _Request) -> Optional[list]:
        """`req.need` fresh blocks through the full memory-pressure
        ladder: plain alloc → evict unreferenced cached chains → preempt
        a victim (whose chains the next evict round can reclaim) → None
        (the caller requeues with _BLOCKED). Worker thread only."""
        blk_ids = self._alloc.alloc(req.need)
        while blk_ids is None:
            if self._bpx is not None:
                self._bpx.evict(req.need - self._alloc.free_blocks)
                blk_ids = self._alloc.alloc(req.need)
                if blk_ids is not None:
                    return blk_ids
            if not self._preempt_for(req):
                return None
            blk_ids = self._alloc.alloc(req.need)
        return blk_ids

    def _victim_for(self, req: _Request) -> Optional[_Request]:
        """The decoding tenant to evict so `req` can be placed, or None.
        Candidates: assigned, still running, NOT mid-prefill (a chunked
        job's partial blocks are not yet a restorable chain), below the
        preemption cap, and not outranking the beneficiary's SLO weight.
        The scheduler's policy object picks lowest-weight / youngest."""
        with self._cv:
            cands = [
                r for b, r in enumerate(self._assignment)
                if r is not None and not r.done.is_set() and r is not req
                and b not in self._prefilling
                and r.preemptions < self.max_preemptions
            ]
        if not cands:
            return None
        return self._sched.select_victim(
            [(r, self._sched.classify(r.slo), r.enqueued) for r in cands],
            self._sched.classify(req.slo),
        )

    def _preempt_for(self, req: _Request) -> bool:
        """Evict one decoding victim to make pool room for `req` (worker
        thread, called when allocation failed even after the
        evict-unreferenced-chains retry). Returns True when a victim was
        preempted (its blocks decref'd — the caller re-runs the evict +
        alloc retry, which can now reclaim the victim's index-cached
        chains too).

        The victim's host-side record (prompt + fetched tokens) is the
        same salvage contract a supervisor restart uses, so its resume
        re-admission is greedy bit-identical; under preempt_policy
        "swap" its filled blocks are pushed to the host shadow FIRST
        (synchronous flush) so the resume restores them in one scatter
        and re-prefills only the tail — a backlogged copier falls back
        to drop-and-recompute. Emissions from the victim's still-in-
        flight chunks are dropped via the drop_seq barrier (regenerated
        after resume), exactly like unfetched chunks across a crash."""
        if self.preempt_policy == "off":
            return False
        victim = self._victim_for(req)
        if victim is None:
            return False
        faults.check("preempt", tag=victim.prompt)
        swapped = False
        if self.preempt_policy == "swap" and self._shadow is not None:
            # capture any blocks filled since the last fetch, then wait
            # for every pending copy to LAND — only resident entries are
            # restorable, and a half-shadowed chain is worthless
            self._shadow_capture(victim)
            swapped = self._shadow.flush(timeout_s=5.0)
        # fold the fetched token stream into the salvage record before
        # releasing anything (the continuation re-prefill's source)
        head = (
            [victim.first_id]
            if victim.first_id is not None
            and victim.first_id not in self.cfg.all_stop_ids else []
        )
        if swapped and victim.ids is not None and victim.adapter is None:
            victim.resume_seq = list(victim.ids) + head + victim.tokens
        else:
            # adapter victims always drop-and-recompute: their KV never
            # enters the shadow (base-keyed content store), so there is
            # no chain to restore — the recompute resume is still greedy
            # bit-identical via the salvage record
            victim.resume_seq = None
        victim.salvaged = victim.salvaged + head + victim.tokens
        victim.first_id = None
        victim.tokens = []
        victim.preemptions += 1
        victim.preempted_at = time.time()
        # launch-seq barrier: chunks launched before this point may still
        # fetch emissions for the victim's old slot — drop them (they are
        # regenerated after resume; appending them post-fold would
        # corrupt the salvage order)
        self._mutation_seq += 1
        victim.drop_seq = self._mutation_seq
        if victim.slot is not None:
            self.state = G.kill_slot(self.state, victim.slot)
        self._free_slot_resources(victim)
        victim.slot = None
        victim.need = None
        victim.prefix_hit_tokens = 0
        victim.ids = None
        victim.shadow_depth = 0
        self.preempted_total += 1
        self._m_preempt.labels(reason="pool").inc()
        self.engine.flight.record(
            "preempt", request_id=victim.trace.request_id,
            policy=self.preempt_policy, swap=swapped,
            preemptions=victim.preemptions, slo_class=victim.slo,
            beneficiary=req.trace.request_id,
            **self._alloc.span_attrs(),
        )
        log.info(
            "request_preempted", policy=self.preempt_policy, swap=swapped,
            preemptions=victim.preemptions, slo_class=victim.slo,
            beneficiary_class=req.slo, request_id=victim.trace.request_id,
        )
        with self._cv:
            self._resume.append(victim)
            self._cv.notify_all()
        return True

    def _prepare_resume(self, req: _Request):
        """Swap-preemption's warm half (worker thread, just before the
        resume re-admission): scatter the victim's shadowed chain back
        into freshly allocated pool blocks (the pre-warmed fixed-width
        restore program) and re-register it into the block-prefix index,
        so the ordinary admission path below prefix-hits it and
        re-prefills ONLY the tail past the deepest restored block. Any
        shortfall (entries evicted from the shadow, pool still tight)
        degrades to a colder re-prefill — never an error."""
        seq = req.resume_seq
        if seq is None or self._shadow is None or self._bpx is None:
            req.resume_seq = None
            return
        bs = self.kv_block_size
        # same reuse cap as BlockPrefixIndex.lookup: at least one tail
        # token must remain for the sampling chunk
        cap_full = max(0, (len(seq) - 1) // bs)
        p0, entry, _ = self._bpx.lookup(seq)
        have = p0 // bs
        keys = []
        for i in range(have, cap_full):
            key = tuple(seq[: (i + 1) * bs])
            if not self._shadow.has_resident(key):
                break  # a chain with a hole cannot be registered
            keys.append(key)
        if not keys:
            req.resume_seq = None  # nothing restorable, ever
            return
        blocks = self._alloc.alloc(len(keys))
        if blocks is None and self._bpx is not None:
            self._bpx.evict(len(keys) - self._alloc.free_blocks)
            blocks = self._alloc.alloc(len(keys))
        if blocks is None:
            # pool still tight (the admission below will _BLOCK and
            # requeue): KEEP resume_seq so the retry after the next
            # release still restores warm instead of recomputing
            return
        entries = self._shadow.entries_for(keys)
        if entries is None:
            self._alloc.decref(blocks)
            req.resume_seq = None
            return
        try:
            W = self._shadow_restore_w
            for off in range(0, len(keys), W):
                ids = blocks[off : off + W]
                batch = entries[off : off + W]
                pad = W - len(ids)
                ids_p = ids + [self._P.TRASH_BLOCK] * pad
                stacked = []
                for i in range(len(batch[0].leaves)):
                    arr = np.stack([e.leaves[i] for e in batch])
                    if pad:
                        arr = np.concatenate(
                            [arr, np.repeat(arr[:1], pad, axis=0)]
                        )
                    stacked.append(jnp.asarray(arr))
                restored = jax.tree.unflatten(
                    jax.tree.structure(self.cache), stacked
                )
                self.cache = self.backend.restore_shadow_blocks(
                    self.cache, restored, jnp.asarray(ids_p, jnp.int32)
                )
        except BaseException:
            # a crash mid-restore is contained by the supervisor, but
            # these blocks are not yet tracked anywhere — release them
            # before the unwind or the pool leaks
            self._alloc.decref(blocks)
            raise
        req.resume_seq = None
        row_blocks = list(entry or []) + blocks
        self._bpx.import_chain(
            list(seq[: len(row_blocks) * bs]), row_blocks
        )
        # the index holds its own reference now; restored chains end at
        # refcount 1 (index-held, evictable) like every cached chain
        self._alloc.decref(blocks)
        self._m_shadow_restored.inc(len(blocks))
        log.info(
            "preempt_resume_restored", blocks=len(blocks),
            request_id=req.trace.request_id,
        )

    def _supervise(self, exc: Exception) -> bool:
        """One crash-containment round. Returns True to restart the loop,
        False to give up (budget exhausted or closing)."""
        self._restarting = True
        self._consecutive_crashes += 1
        log.error(
            "continuous_loop_crashed", exc_info=True, error=str(exc),
            consecutive=self._consecutive_crashes,
        )
        # crash flight recorder (ISSUE 17): the event ring's tail goes
        # into the crash report (the structured log record below) and
        # the FULL dump is persisted next to --restore-dir, so a
        # poison-quarantine or restart-loop episode is reconstructable
        # after the process is gone. Persist failures only cost the
        # forensics file — containment proceeds regardless.
        self.engine.flight.record(
            "crash", error=str(exc),
            consecutive=self._consecutive_crashes,
        )
        flight = self.engine.flight.dump()
        log.error(
            "crash_flight_recorder",
            recorded_total=flight["recorded_total"],
            tail=flight["events"][-20:],
        )
        if self._restore_dir:
            try:
                os.makedirs(self._restore_dir, exist_ok=True)
                with open(
                    os.path.join(self._restore_dir, "flight_crash.json"),
                    "w",
                ) as f:
                    json.dump(
                        {"error": str(exc),
                         "consecutive": self._consecutive_crashes,
                         **flight},
                        f,
                    )
            except OSError as e:
                log.warning("flight_persist_failed", error=str(e))
        casualties = self._casualties()
        for req in casualties:
            if req in self._suspects:
                req.strikes += 1
        self._suspects.clear()
        self._release_fleet_resources(casualties)
        survivors = []
        for req in casualties:
            if req.strikes >= self.poison_strikes:
                # implicated in poison_strikes consecutive crash-restarts:
                # fail it ALONE; its fleet-mates are salvaged below
                self.poisoned_total += 1
                self._m_poison.inc()
                self.engine.flight.record(
                    "quarantine", request_id=req.trace.request_id,
                    strikes=req.strikes,
                )
                log.error(
                    "request_quarantined", strikes=req.strikes,
                    request_id=req.trace.request_id,
                )
                req.result = {
                    "error": f"Error: request quarantined after "
                    f"implication in {req.strikes} scheduler crashes "
                    f"(last: {exc})",
                    "status": "failed",
                    "error_type": "poison",
                }
                self._push_final(req)
            else:
                survivors.append(req)
        if self._closed or self._consecutive_crashes > self.restart_budget:
            with self._cv:
                self._dead = not self._closed
                self._closed = True
                pending = self._queue[:]
                self._queue.clear()
                self._note_queue_locked()
                self._cv.notify_all()
            fail = {
                "error": f"Error: continuous scheduler died after "
                f"{self._consecutive_crashes} consecutive crashes "
                f"(restart budget {self.restart_budget}): {exc}",
                "status": "failed",
                "error_type": "unavailable",
            }
            # self._recovery: salvaged requests a previous round never got
            # to re-admit (a crash mid-recovery) — they hang otherwise.
            # self._resume: preempted requests parked for re-admission
            # (host-side only, resources already released) — same hazard.
            for req in survivors + pending + self._recovery + self._resume:
                if req.result is None:
                    req.result = dict(fail)
                self._push_final(req)
            self._recovery = []
            self._resume = []
            self._restarting = False
            self.engine.flight.record(
                "scheduler_dead", restarts=self.restarts_total,
            )
            log.error(
                "continuous_scheduler_dead", restarts=self.restarts_total
            )
            return False
        # exponential backoff: a crash loop must not spin the host
        time.sleep(min(
            self.restart_backoff_s * (2 ** (self._consecutive_crashes - 1)),
            5.0,
        ))
        self._rebuild_fleet()
        # warm recovery: the restarted loop restores shadowed blocks
        # into the fresh pool BEFORE re-admitting anything. Deliberately
        # not done here: _supervise runs inside _loop's except handler,
        # where a restore crash (the double-fault drill) would escape
        # containment — _loop_inner owns the restore under the
        # supervisor instead.
        self._needs_restore = self._shadow is not None
        # Salvage: prompt + tokens generated so far are host-side. The
        # restarted loop re-admits each request as a CONTINUATION prefill
        # (prompt + salvaged tokens), so greedy decode resumes bit-exactly
        # where the fetched token stream stopped — tokens lost in
        # unfetched in-flight chunks are simply regenerated.
        for req in survivors:
            head = (
                [req.first_id]
                if req.first_id is not None
                and req.first_id not in self.cfg.all_stop_ids else []
            )
            req.salvaged = req.salvaged + head + req.tokens
            req.first_id = None
            req.tokens = []
            req.slot = None
            req.need = None
            req.prefix_hit_tokens = 0
            # shadow bookkeeping resets with the fleet: the re-admission
            # gets fresh blocks (content keys dedup re-captures)
            req.ids = None
            req.shadow_depth = 0
        # a crash mid-recovery leaves earlier salvage in self._recovery
        # (already reset — never re-admitted): keep it, after this round's
        # survivors (who were vindicated tenants before the crash)
        self._recovery = survivors + [
            r for r in self._recovery if not r.done.is_set()
        ]
        self.restarts_total += 1
        self._m_restarts.inc()
        self.engine.flight.record(
            "restart", restart=self.restarts_total,
            salvaged=len(survivors),
        )
        log.info(
            "continuous_scheduler_restarted", restart=self.restarts_total,
            salvaged=len(survivors),
        )
        return True

    def _run_recovery(self):
        """Serialized re-admission of salvaged requests: ONE request per
        healthy chunk, so a recurring crash implicates exactly the
        request just re-admitted (the suspect set narrows to a singleton)
        instead of striking every fleet-mate — the mechanism that
        isolates a poison request within poison_strikes restarts while
        the rest of the fleet survives."""
        try:
            while self._recovery:
                if self._closed:
                    # close() fails queued + assigned requests, but the
                    # not-yet-readmitted salvage is in neither place
                    fail = {
                        "error": "Error: server shutting down",
                        "status": "failed", "error_type": "overloaded",
                    }
                    while self._recovery:
                        r = self._recovery.pop(0)
                        if r.result is None:
                            r.result = dict(fail)
                        self._push_final(r)
                    return
                req = self._recovery[0]
                if (
                    req.allowed is not None
                    and len(req.salvaged) >= req.allowed
                ):
                    # budget already consumed pre-crash (the crash cut the
                    # loop between the last fetch and finalize)
                    self._recovery.pop(0)
                    self._finalize(req)
                    continue
                with self._cv:
                    free = [
                        b for b, r in enumerate(self._assignment)
                        if r is None
                    ]
                if not free:
                    # more casualties than slots (a crash mid-admission):
                    # decode until a tenant completes and frees one
                    chunk = self._launch_chunk()
                    if chunk is None:
                        break  # unreachable: no free slot implies tenants
                    self._process(chunk)
                    continue
                self._recovery.pop(0)
                self._suspects.add(req)
                self._mutation_seq += 1
                # recomputed-prefill accounting: the re-admission below
                # counts its tail into dli_recovery_tokens_recomputed_total
                req.recovering = True
                # survives an exception unwind on purpose — the
                # supervisor's pointer to a request cut mid-re-admission
                self._admitting = req
                first_dev = self._admit_one(req, free[0])
                self._admitting = None
                if first_dev is _BLOCKED:
                    # the rebuilt pool/table cannot take it right now
                    # (another recovered tenant holds the blocks): back to
                    # the FRONT of the normal queue
                    with self._cv:
                        self._queue.insert(0, req)
                        self._note_queue_locked()
                    continue
                if first_dev is None:
                    continue  # failed fast (cancelled/deadline); result set
                req.first_id = int(np.asarray(first_dev)[0])
                if not req.ttft:
                    req.ttft = time.time() - req.t_start
                self.recovered_total += 1
                self._m_recovered.inc()
                self._post_admit(req)
                # one synchronous chunk = the healthy step that vindicates
                # this re-admission before the next one joins the fleet
                chunk = self._launch_chunk()
                if chunk is not None:
                    self._process(chunk)
        finally:
            self._restarting = False

    # -- launch-level device-time attribution (ISSUE 17) ---------------------
    def _prof_note_launch(self, kind: str, t_launch: float, snapshot,
                          **attrs):
        """Open one launch-attribution record (worker thread, called at
        the dispatch boundary ONLY behind the `self._trace_rate > 0`
        guard — at the default rate 0 this method is unreachable from
        the hot path and nothing here ever allocates). The record closes
        at the matching packed fetch (_prof_close_launch), keyed by the
        launch's own perf_counter timestamp: fetches drain the inflight
        deque FIFO in launch order, so lag-pipelined launches attribute
        correctly without any extra device sync."""
        targets = [
            (r.trace_ctx.trace_id, r.trace_ctx.span_id)
            for r in snapshot
            if r is not None and r.profiled and r.trace_ctx is not None
        ]
        self._prof_active = len(targets)
        if not targets:
            return
        self._launch_log.append({
            "t_launch": t_launch,
            "wall": time.time(),
            "kind": kind,
            "targets": targets,
            "attrs": attrs,
        })

    def _prof_close_launch(self, t_launch: float, **attrs):
        """Close the oldest launch record IF it belongs to the fetch
        being processed (exact float equality on the launch timestamp —
        unrecorded launches between recorded ones just don't match), and
        emit one `launch.<kind>` span per profiled tenant into the
        engine's span store, parented under that request's inbound span
        so the assembled tree nests router → replica → launch."""
        if not self._launch_log or self._launch_log[0]["t_launch"] != t_launch:
            return
        rec = self._launch_log.popleft()
        t1 = time.time()
        span_attrs = dict(rec["attrs"])
        span_attrs.update(attrs)
        span_attrs["launch_to_fetch_s"] = round(
            time.perf_counter() - t_launch, 6
        )
        store = self.engine.trace_store
        for trace_id, parent in rec["targets"]:
            store.add_span(
                trace_id, f"launch.{rec['kind']}", rec["wall"], t1,
                parent_id=parent, attrs=span_attrs,
            )

    def _launch_chunk(self):
        """Launch one decode chunk over the current fleet (paged /
        constrained / plain slot program — state, cache, and fsm chain
        device-side between launches, so no fetch is needed to launch the
        next chunk). Returns the inflight tuple (packed results dev
        array, assignment snapshot, launch time, mutation seq) or None
        when no slot is active."""
        if not any(r is not None for r in self._assignment):
            return None
        faults.check("decode_launch", tag=",".join(
            r.prompt for r in self._assignment if r is not None
        ))
        if self.paged:
            if self._table_dev is None:
                self._table_dev = jnp.asarray(self._table)
            # adapter serving: the per-slot page snapshot rides every
            # launch (pages=None when no pool is attached — a DISTINCT
            # compiled program that lowers byte-identically to the
            # pre-adapter build)
            pages = (
                jnp.asarray(self._slot_pages)
                if self._adapters is not None else None
            )
            emitted, mask, self.state, self.cache = (
                self.backend.decode_slots_paged(
                    self.state, self.cache, self._table_dev,
                    self._next_key(), self.sparams,
                    num_steps=self.chunk_steps, pages=pages,
                )
            )
        elif self._ctable.any_active:
            # >= 1 constrained tenant: the constrained slot program
            # (two extra gathers; free rows make it a no-op for
            # unconstrained slots). The fsm chunk output chains
            # device-side exactly like state/cache.
            cm, ct = self._ctable.device_tables()
            emitted, mask, self.state, self.cache, self._fsm = (
                self.backend.decode_slots_constrained(
                    self.state, self.cache, self._next_key(),
                    self.sparams, self._fsm, cm, ct,
                    num_steps=self.chunk_steps,
                )
            )
        else:
            emitted, mask, self.state, self.cache = (
                self.backend.decode_slots(
                    self.state, self.cache, self._next_key(),
                    self.sparams, num_steps=self.chunk_steps,
                )
            )
        packed = G.pack_chunk(emitted, mask, self.state.active)
        snapshot = list(self._assignment)
        t_launch = time.perf_counter()
        if self._trace_rate > 0.0:
            self._prof_note_launch(
                "chunk", t_launch, snapshot, steps=self.chunk_steps,
                rows=sum(1 for r in snapshot if r is not None),
            )
        return (packed, snapshot, t_launch, self._mutation_seq)

    def _loop_inner(self):
        # In-flight decode chunks, oldest first. Launch up to chunk_lag
        # chunks before blocking on the oldest fetch, so the device stays
        # fed even when the fetch RTT exceeds a chunk's compute. Admission
        # (insert_slot) and kill (kill_slot) mutate the FUTURE-most state,
        # which is exactly the one the next launch uses.
        inflight: collections.deque = collections.deque()
        # a restart abandoned any in-flight launches — their attribution
        # records can never be closed (the fetches died with the crash)
        self._launch_log.clear()
        # warm restore FIRST (supervisor restart or --restore-dir start):
        # the rebuilt pool takes the shadowed blocks back in one scatter
        # and the block-prefix index re-learns the chains, so the
        # serialized salvage re-admissions below hit them and re-prefill
        # only their partial tail. Runs under the supervisor: a crash
        # here is contained, resources released, and the restore retried
        # next round (tests/test_recovery.py double-fault leg).
        if self._needs_restore:
            self._needs_restore = False
            self._restore_shadow()
        # after a supervisor restart: serially re-admit salvaged requests
        # (no-op on a clean start; also clears the restarting flag)
        self._run_recovery()
        if self._chunked:
            # SLO-aware chunked-prefill scheduling (engine/scheduler.py):
            # admissions land chunk by chunk inside mixed launches
            # instead of prefilling whole before the fleet advances
            self._sched_loop(inflight)
            return
        while True:
            with self._cv:
                while (
                    not self._queue
                    and not self._resume
                    and not any(self._assignment)
                    and not inflight
                    and not self._closed
                ):
                    self._cv.wait()
                if self._closed:
                    return
                queue_head = bool(self._queue or self._resume)
            if queue_head:
                self._admit()
            chunk = self._launch_chunk()
            launched = chunk is not None
            if launched:
                inflight.append(chunk)
            # Block on the oldest chunk when MORE than chunk_lag chunks
            # are unprocessed (so chunk_lag=1 keeps one outstanding after
            # draining — the classic fetch-N-1-overlaps-compute-N) — or
            # when nothing launched (all slots looked idle to the host:
            # drain so finished requests finalize and new work can wake us)
            while inflight and (len(inflight) > self.chunk_lag
                                or not launched):
                self._process(inflight.popleft())
                launched = True  # drain one per wakeup once non-empty

    # -- chunked-prefill scheduler loop (engine/scheduler.py) ----------------
    def _sched_loop(self, inflight: collections.deque):
        """Token-budget scheduling: each iteration starts any queued
        requests a free slot + pool blocks can take (as PrefillJobs — no
        device work yet), then launches ONE step. With pending prefill
        work the step is a MIXED ragged launch (every active decode row
        plus budget-sliced prefill chunks — engine/paged.
        mixed_step_ragged); a fleet with no prefill pending falls back to
        the amortized multi-step decode chunk, which runs the identical
        slot_step math over the same pool. Lag pipelining, crash
        supervision, drain, and recovery all work exactly as in the
        whole-prefill loop — mixed steps plan from the host position
        model and gather decode tokens from slot state ON DEVICE, so no
        fetch is ever needed to launch the next step."""
        while True:
            with self._cv:
                while (
                    not self._queue
                    and not self._resume
                    and not any(self._assignment)
                    and not inflight
                    and not self._closed
                ):
                    self._cv.wait()
                if self._closed:
                    return
            self._reap_jobs()
            self._start_jobs()
            spec_rows = self._plan_spec()
            if (
                self._jobs or spec_rows or self._spec_inflight
                or self._spec_pending
            ):
                # mixed step: prefill chunks and/or verify rows ride the
                # flat token axis with the decode rows. A slot whose
                # verify row is still unfetched keeps the fleet on the
                # mixed program too (legacy mode: it must stay frozen
                # via dec_on until its position resyncs; device-meta
                # mode: its next row's positions derive from slot state,
                # and staying mixed keeps the per-launch emission
                # bookkeeping uniform while verify fetches are pending)
                step = self._launch_mixed(spec_rows)
            else:
                step = self._launch_chunk()
                if step is not None:
                    # host position model: every believed-active slot
                    # advanced chunk_steps (over-advance on rows that die
                    # mid-chunk is masked garbage, the frozen-row rule).
                    # Drafting pauses until this launch's many-token
                    # emissions are fetched (_chunk_unfetched).
                    self._chunk_unfetched += 1
                    for b, r in enumerate(self._assignment):
                        if r is not None:
                            self._host_pos[b] += self.chunk_steps
            launched = step is not None
            if launched:
                inflight.append(step)
            while inflight and (len(inflight) > self.chunk_lag
                                or not launched):
                self._process_any(inflight.popleft())
                launched = True

    def _process_any(self, step):
        if isinstance(step, tuple) and step and step[0] == "mixed":
            self._process_mixed(step)
        else:
            self._process(step)
            if self._chunk_unfetched > 0:
                self._chunk_unfetched -= 1

    def _reap_jobs(self):
        """Fail pending prefills whose client went away or whose deadline
        passed BEFORE spending more budget on them (the mid-decode
        equivalents live in _distribute)."""
        deadline = self.engine.engine_cfg.request_deadline_s
        now = time.time()
        for job in list(self._jobs):
            req = job.req
            if req.cancelled:
                req.result = self._cancel_env(req)
            elif self._past_deadline(req, now):
                req.result = self._deadline_env(req, where="mid-prefill")
            elif deadline and now - req.t_start > deadline:
                req.result = {
                    "error": f"Error: request exceeded the {deadline:g}s "
                    "deadline",
                    "status": "failed",
                    "error_type": "timeout",
                }
            else:
                continue
            self._m_preempt.labels(
                reason="cancelled" if req.cancelled else "deadline"
            ).inc()
            self._release(req)  # drops the job via the slot mapping

    # -- adapter page lifecycle (engine/adapters.py) -------------------------
    def _acquire_adapter(self, req: _Request) -> bool:
        """Pin req's adapter page (refcount + HBM upload on a miss) for
        the request's whole slot tenure. Acquired FIRST in admission —
        before any block incref — so every unwind path below it only has
        to release what it took. False = every page is referenced by
        other in-flight requests right now (backpressure, same contract
        as pool-block exhaustion). Base requests are a no-op (page 0)."""
        if req.adapter is None or req.adapter_page is not None:
            return True
        page = self._adapters.acquire(req.adapter)
        if page is None:
            return False
        req.adapter_page = page
        return True

    def _release_adapter(self, req: _Request):
        """Drop req's adapter-page reference (idempotent). The page
        stays RESIDENT at refcount 0 (LRU-parked) — the next request for
        the same adapter re-acquires it without a device write."""
        if req.adapter_page is not None and self._adapters is not None:
            self._adapters.release(req.adapter)
        req.adapter_page = None

    def _start_jobs(self):
        """Move queued requests into PrefillJobs while a slot and pool
        blocks are available. Host-side only — tokenize, plan prefix
        reuse, allocate blocks, install the slot's block table; the
        prompt lands chunk by chunk in subsequent mixed launches. Same
        suspect/_admitting crash discipline as whole-prefill admission."""
        while True:
            with self._cv:
                # preempted requests resume first (see _admit)
                from_resume = bool(self._resume)
                if not from_resume and not self._queue:
                    return
                free = [
                    b for b, r in enumerate(self._assignment) if r is None
                ]
                if not free:
                    return
                if not from_resume:
                    head = self._queue[0]
                    if (
                        head.need is not None
                        and head.need > self._alloc.free_blocks + (
                            self._bpx.evictable_blocks()
                            if self._bpx is not None else 0
                        )
                    ):
                        # the admission policy's capacity leg: a previously
                        # sized head that still cannot get blocks (even by
                        # evicting every unreferenced cached chain) waits
                        # for a release — no re-tokenize/replan churn per
                        # step. Preemption happens INSIDE the admission
                        # attempt (the pressure ladder), so a head whose
                        # shortfall a victim could cover is sized with
                        # need=None on its first attempt and reaches it.
                        return
                    req = self._queue.pop(0)
                    self._note_queue_locked()
                else:
                    req = self._resume.pop(0)
            if (
                from_resume and req.allowed is not None
                and len(req.salvaged) >= req.allowed
            ):
                self._finalize(req)
                continue
            try:
                self._suspects.add(req)
                self._mutation_seq += 1
                # survives an exception unwind ON PURPOSE (see _admit)
                self._admitting = req
                if from_resume:
                    # swap-preemption resume: restore the shadowed chain
                    # so the prefix plan below hits it (tail-only chunks)
                    self._prepare_resume(req)
                if req.kwargs.get("constraint") is not None:
                    # constrained requests keep the whole-prefill
                    # admission path (the mixed program carries no
                    # first-token bias operand; _needs_solo routes public
                    # constrained traffic solo anyway — this preserves
                    # the constraint-table backpressure/leak discipline
                    # for embedded callers)
                    first_dev = self._admit_one(req, free[0])
                    self._admitting = None
                    if first_dev is _BLOCKED:
                        with self._cv:
                            if from_resume:
                                self._resume.insert(0, req)
                            else:
                                self._queue.insert(0, req)
                                self._note_queue_locked()
                        return
                    if first_dev is not None:
                        req.first_id = int(np.asarray(first_dev)[0])
                        if not req.ttft:
                            req.ttft = time.time() - req.t_start
                        if from_resume and req.preempted_at:
                            self._m_resume_s.observe(
                                time.time() - req.preempted_at
                            )
                        self._post_admit(req)
                    continue
                started = self._start_job(req, free[0])
                self._admitting = None
                if started is _BLOCKED:
                    with self._cv:
                        if from_resume:
                            self._resume.insert(0, req)
                        else:
                            self._queue.insert(0, req)
                            self._note_queue_locked()
                    return
                if (
                    started is not None and from_resume
                    and req.preempted_at
                ):
                    self._m_resume_s.observe(time.time() - req.preempted_at)
            except ValueError as e:
                self._admitting = None
                # a validation error can fire AFTER the block grant /
                # constraint-row acquire (e.g. a malformed sampling
                # kwarg float()s late): release everything this failed
                # admission holds or the pool bleeds per bad request —
                # the PR-4 _BLOCKED leak shape on the error path
                self._free_slot_resources(req)
                log.warning("invalid_request", error=str(e))
                req.result = {
                    "error": f"Error: {e}", "status": "failed",
                    "error_type": "invalid_request",
                }
                self._push_final(req)
            # any other exception escapes to the supervisor (crash
            # containment + suspect implication), exactly like _admit

    def _start_job(self, req: _Request, slot: int):
        """Plan one chunked admission: tokenize, prefix-reuse lookup at
        EXACT chunk depth, clamp the budget, allocate + map pool blocks,
        and queue the PrefillJob. Returns _BLOCKED when the pool cannot
        take it (caller requeues at the front), None when the request
        failed fast (result already set), or the job."""
        eng, cfg = self.engine, self.cfg
        faults.check("admission", tag=req.prompt)
        req.trace.checkpoint("queue_wait")
        if req.cancelled:
            req.result = self._cancel_env(req)
            self._push_final(req)
            return None
        if self._past_deadline(req):
            # end-to-end deadline_ms expired while queued: zero prefill,
            # zero pool blocks spent on it
            req.result = self._deadline_env(req, where="while queued")
            self._push_final(req)
            return None
        deadline = eng.engine_cfg.request_deadline_s
        if deadline and time.time() - req.enqueued > deadline:
            req.result = {
                "error": f"Error: request exceeded the {deadline:g}s "
                "deadline while queued",
                "status": "failed",
                "error_type": "timeout",
            }
            self._push_final(req)
            return None
        if not self._acquire_adapter(req):
            # every adapter page is referenced by other in-flight
            # requests: backpressure exactly like pool-block exhaustion
            # (the caller requeues at the front; a release frees a page)
            return _BLOCKED
        k = req.kwargs
        text = (
            eng.render_chat(req.prompt)
            if k.get("chat", True) else req.prompt
        )
        ids = eng.tokenizer.encode(text)
        req.prompt_tokens = len(ids)
        if req.salvaged:
            # crash-recovery continuation: prompt + pre-crash tokens
            ids = ids + list(req.salvaged)
        prompt_len = len(ids)
        if req.kv_hint is not None and req.adapter is None:
            # same remote-hit seam as the whole-prefill admission: a
            # fetched chain becomes a deeper exact-depth hit below.
            # Adapter requests never prefetch — the fabric serves BASE
            # KV chains keyed by token content alone.
            self._fabric_prefetch(req, ids)
        # tier promotion: a host/disk-shadowed chain deeper than the
        # pool's becomes a deeper exact-depth hit below, same as a
        # fabric import (self-gates; can never fail the request)
        self._promote_local_chain(req, ids)
        p0, entry, plan = eng._prefix_plan(
            self._bpx, ids, capacity=self.slot_max_seq, ragged=True,
            adapter=req.adapter,
        )
        if plan is None:
            raise ValueError(
                f"prompt length {prompt_len} exceeds the slot capacity "
                f"(slot_max_seq {self.slot_max_seq})"
            )
        max_tokens, _ = eng._clamp_decode(
            prompt_len, int(k.get("max_tokens", 20)) - len(req.salvaged),
            capacity=self.slot_max_seq,
        )
        if req.allowed is None:
            req.allowed = max_tokens
        else:
            max_tokens = min(max_tokens, req.allowed - len(req.salvaged))
        if req.recovering:
            # a salvage that fell back through the queue (_BLOCKED) and
            # re-entered as a chunked job still counts its recomputed tail
            self._m_recovery_recomputed.inc(prompt_len - p0)
            req.recovering = False
        faults.check("alloc", tag=req.prompt)
        need_total = self._P.blocks_needed(
            prompt_len, max_tokens, self.kv_block_size
        )
        shared = list(entry)[: p0 // self.kv_block_size] if p0 else []
        n_shared = len(shared)
        req.need = need_total - n_shared
        if shared:
            # holders land on block_ids immediately (see _admit_one): a
            # crash inside the pressure ladder releases them cleanly
            self._alloc.incref(shared)
            req.block_ids = list(shared)
        # same pressure ladder as the whole-prefill admission: evict
        # cached chains, then preempt a decoding victim before stalling
        blk_ids = self._alloc_with_pressure(req)
        if blk_ids is None:
            if shared:
                self._alloc.decref(shared)
            req.block_ids = None
            self._release_adapter(req)
            return _BLOCKED
        req.block_ids = shared + blk_ids
        table_row = np.zeros((self._max_blocks,), np.int32)
        table_row[:need_total] = req.block_ids
        req.prefix_hit_tokens = p0
        if p0:
            self._m_ragged_exact.inc()
        rp = float(k.get("repetition_penalty", 1.0))
        presence_row = (
            np.asarray(eng._presence_rows([ids])[0]) if rp != 1.0
            else np.zeros((cfg.vocab_size,), bool)
        )
        sampling = (
            float(k.get("temperature", 0.7)), int(k.get("top_k", 50)),
            float(k.get("top_p", 0.9)), bool(k.get("greedy", False)),
            float(k.get("min_p", 0.0)), rp,
            float(k.get("frequency_penalty", 0.0)),
            float(k.get("presence_penalty", 0.0)),
        )
        from .scheduler import PrefillJob

        job = PrefillJob(
            req, ids, p0, prompt_len, max_tokens, slot, sampling,
            presence_row, table_row, self._sched.classify(req.slo),
        )
        self._table[slot] = table_row
        self._table_dev = None
        self._slot_pages[slot] = req.adapter_page or 0
        self._host_pos[slot] = 0
        # a new tenant's stream predicts nothing about the previous
        # one's: its adaptive-K acceptance EWMA starts fresh
        self._sched.spec_reset(slot)
        req.slot = slot
        # the admitted token sequence: shadow capture keys off it, and
        # the n-gram draft planner reads it as the slot's history head
        req.ids = ids
        req.shadow_depth = 0
        with self._cv:
            self._assignment[slot] = req
        self._jobs.append(job)
        self._prefilling[slot] = job
        log.info(
            "prefill_started", slot=slot, prompt_len=prompt_len,
            tail=job.remaining, prefix_hit=p0, slo_class=job.cls.name,
            request_id=req.trace.request_id,
        )
        return job

    # -- speculative decoding: host-side planning (ISSUE 13) -----------------
    # jaxlint: decode-unreachable -- host-side eligibility check over request kwargs (scheduler worker thread only)
    def _spec_req_ok(self, req: Optional[_Request]) -> bool:
        """Is this tenant a speculation candidate? Greedy only (the
        verify compares the model's own argmax) with every logit-
        mutating knob at its disabled value, so the verify argmax and
        slot_step's penalized argmax coincide bitwise; and the request
        (or the fleet, via engine_cfg.spec_decode) opted in."""
        if req is None or not (self._spec_auto or req.spec_want):
            return False
        k = req.kwargs
        return (
            bool(k.get("greedy", False))
            and float(k.get("repetition_penalty", 1.0)) == 1.0
            and float(k.get("frequency_penalty", 0.0)) == 0.0
            and float(k.get("presence_penalty", 0.0)) == 0.0
            and k.get("constraint") is None
        )

    # jaxlint: decode-unreachable -- host-side launch planning over Python lists (scheduler worker thread only)
    def _plan_spec(self) -> dict:
        """Plan this step's verify rows: {slot: (n_draft, drafts|None,
        pred|None)} (drafts None = device draft-model proposals; pred =
        the optimistic window — drafts + predicted correction — pending
        fetches extend the drafting history with).

        Device-meta mode (the default): an unfetched verify row never
        disqualifies its slot — positions derive on device, so the only
        gates are DRAFT QUALITY ones: no amortized decode chunk may be
        unfetched (many-token unpredictable advances), every pending
        launch carrying the slot must be a verify launch of THIS tenant
        with a predicted window (a pending plain row adds one token the
        host cannot predict), and — n-gram mode — the optimistic
        history must offer at least a 2-token window (draft + predicted
        correction) so back-to-back drafts stay frontier-aligned under
        full accept. Legacy mode (spec_device_meta=False) keeps the
        PR-13 gates: previous verify row fetched, history fully fetched.

        The scheduler picks the global K (0 under decode TPOT pressure
        — speculation self-disables under load), each slot's K is then
        sized by its acceptance EWMA (spec_slot_k — adaptive drafting),
        and clamped to its allocated blocks so a verify write can never
        run the lblk clamp into a live block; in device-meta mode the
        clamp uses the PESSIMISTIC frontier (host position + every
        pending launch's maximum advance), since the device may already
        sit that far ahead."""
        if not self._spec_capable:
            return {}
        devmeta = self._spec_devmeta
        cand = []
        for b, req in enumerate(self._assignment):
            if (
                req is None or b in self._prefilling
                or req.done.is_set() or req.cancelled
                or not self._spec_req_ok(req)
            ):
                continue
            if devmeta:
                pending = self._spec_pending.get(b, [])
                if any(e["req"] is not req for e in pending):
                    continue  # stale entries from the slot's previous
                    # tenant: wait for their fetches to drain
                if not self._draft_mode:
                    # the n-gram planner needs an ALIGNED optimistic
                    # history; the draft model needs none of these
                    # gates (it proposes from true device state)
                    if self._chunk_unfetched:
                        continue
                    if self._row_inflight[b] > len(pending):
                        continue  # pending PLAIN rows: 1 unpredictable
                        # token each — drafting would desync the frontier
                    if any(e["pred"] is None for e in pending):
                        continue
            elif b in self._spec_inflight or self._row_inflight[b] != 0:
                continue
            cand.append(b)
        if not cand:
            return {}
        n_active = sum(
            1 for b, r in enumerate(self._assignment)
            if r is not None and b not in self._prefilling
        )
        k = self._sched.spec_draft_len(
            self._spec_k_max, len(cand), n_active - len(cand),
            active_classes={
                r.slo for b, r in enumerate(self._assignment)
                if r is not None and b not in self._prefilling
            },
            jobs_pending=bool(self._jobs),
        )
        if k <= 0:
            return {}
        bs = self.kv_block_size
        out = {}
        for b in cand:
            req = self._assignment[b]
            # never draft past the slot's allocated blocks: the verify
            # writes K/V at pos..pos+k, and positions beyond the table
            # tail-redirect to the trash block, but positions past
            # MB*bs would CLAMP into the slot's own last live block.
            # Device-meta mode: pos is the DEVICE frontier, which may
            # lead the host model by every pending launch's advance —
            # clamp against the upper bound, not the lagged host value.
            from .scheduler import spec_block_cap

            pending = self._spec_pending.get(b, []) if devmeta else []
            frontier = int(self._host_pos[b]) + sum(
                e["adv"] for e in pending
            )
            blocks = len(req.block_ids) if req.block_ids else 0
            cap = spec_block_cap(blocks, bs, frontier)
            kb = min(k, cap)
            if devmeta:
                # adaptive drafting: the slot's acceptance EWMA sizes
                # its next draft (0 = plain decode row, no verify tiles)
                kb = min(kb, self._sched.spec_slot_k(b, k))
            if kb < 1:
                continue
            if self._draft_mode:
                out[b] = (kb, None, None)
                continue
            head = (
                [req.first_id]
                if req.first_id is not None
                and req.first_id not in self.cfg.all_stop_ids else []
            )
            from .scheduler import ngram_draft

            hist = (req.ids or []) + head + req.tokens
            if devmeta:
                # optimistic frontier: assume every pending verify row
                # fully accepts its predicted window. Wrong guesses only
                # reject (the verify admits nothing but the model's own
                # argmax); the fetch replaces prediction with truth.
                # Draft kb tokens and PREDICT the correction too
                # (window[-1]) so the next back-to-back plan stays
                # frontier-aligned under full accept.
                for e in pending:
                    hist = hist + e["pred"]
                window = ngram_draft(hist, kb + 1)
                if len(window) >= 2:
                    out[b] = (len(window) - 1, window[:-1], window)
            else:
                drafts = ngram_draft(hist, kb)
                if drafts:
                    out[b] = (len(drafts), drafts, None)
        return out

    def _launch_mixed(self, spec_rows: Optional[dict] = None):
        """ONE scheduler step: every active decode row plus the budget
        slice of pending prefill chunks — and, for slots in `spec_rows`
        ({slot: (n_draft, drafts|None, pred|None)}), a [current + draft]
        verify row instead of the 1-token decode row — in one mixed
        ragged launch. In device-meta mode every decode/verify row's
        positions are substituted on device (DeviceMeta), so the launch
        is exact even while earlier verify rows are unfetched. Returns
        the inflight tuple ("mixed", packed dev, decode snapshot,
        {slot: req} completions, launch time, mutation seq, spec
        bookkeeping) or None when the fleet is empty."""
        P = self._P
        spec_rows = spec_rows or {}
        assigned = [
            b for b, r in enumerate(self._assignment)
            if r is not None and b not in self._prefilling
        ]
        if self._spec_devmeta:
            # device-derived metadata: positions come from slot state,
            # so an unfetched verify row never freezes its slot — every
            # assigned decode slot rows EVERY step (the whole point)
            active = assigned
        else:
            # legacy: a slot with an UNFETCHED verify row is skipped
            # outright — its device position is unknown to the host
            # until the packed fetch resyncs it, so it gets no row (and
            # stays frozen via dec_on)
            active = [b for b in assigned if b not in self._spec_inflight]
        # speculated tokens debit the step budget exactly like prefill
        # tokens: a verify row reserves ceil((1+k)/tile) query tiles
        tile = self._ragged_tile
        n_decode_tiles = sum(
            -(-(1 + spec_rows[b][0]) // tile) if b in spec_rows else 1
            for b in active
        )
        plan = self._sched.plan(
            n_decode_tiles, self._jobs,
            active_classes={
                self._assignment[b].slo for b in assigned
                if self._assignment[b] is not None
            },
        )
        if not active and not plan:
            return None
        faults.check("decode_launch", tag=",".join(
            r.prompt for r in self._assignment if r is not None
        ))
        if plan:
            faults.check("prefill", tag=",".join(
                job.req.prompt for job, _ in plan
            ))
        W, B = self._sched_width, self.n_slots
        entries = []
        for b in active:
            if b in spec_rows:
                # verify row: [current + k drafts] — a short prefill-kind
                # row over the slot's own block table (the whole point:
                # the ragged kernel already serves it, no new kernel)
                entries.append((
                    b, int(self._host_pos[b]), 1 + spec_rows[b][0],
                    P.RAGGED_PREFILL,
                ))
            else:
                entries.append(
                    (b, int(self._host_pos[b]), 1, P.RAGGED_DECODE)
                )
        chunk_list = []
        for job, n in plan:
            start = job.p0 + job.done
            entries.append((job.slot, start, n, P.RAGGED_PREFILL))
            chunk_list.append((job, n, start))
        meta, tok_row, tok_pos, offsets, stats = P.build_ragged_meta(
            entries, width=W, tile=tile,
        )
        dev_dev = None
        if self._spec_devmeta:
            # mark every decode/verify entry (the first n_dec) for
            # on-device position substitution — the host start values
            # above are placeholders for those rows
            t_on, t_off, k_on, k_off = P.build_device_meta(
                entries, offsets, len(active), width=W, tile=tile,
            )
            dev_dev = P.DeviceMeta(
                jnp.asarray(t_on), jnp.asarray(t_off),
                jnp.asarray(k_on), jnp.asarray(k_off),
            )
        toks = np.zeros((W,), np.int32)
        dec_flag = np.zeros((W,), bool)
        dec_idx = np.zeros((B,), np.int32)
        n_dec = len(active)
        K1 = self._spec_k_max + 1
        sp_on = np.zeros((B,), bool)
        sp_idx = np.zeros((B, K1), np.int32)
        sp_nd = np.zeros((B,), np.int32)
        dec_on = np.zeros((B,), bool)
        for b, off in zip(active, offsets[:n_dec]):
            # the entry's FIRST flat slot is dec_flag-substituted from
            # device state (token AND position) for plain decode rows
            # and verify rows alike
            dec_flag[off] = True
            if b in spec_rows:
                kb, drafts, _pred = spec_rows[b]
                sp_on[b] = True
                sp_nd[b] = kb
                idxs = off + np.arange(K1, dtype=np.int32)
                idxs[kb + 1:] = off + kb  # pad by repeating the last
                sp_idx[b] = idxs
                if drafts is not None:  # n-gram drafts ride the host plan
                    toks[off + 1 : off + 1 + kb] = drafts
            else:
                dec_on[b] = True
                dec_idx[b] = off
        completions = {}
        arm = self._idle_arm
        arm_np = None
        for (job, n, start), off in zip(chunk_list, offsets[n_dec:]):
            toks[off : off + n] = job.ids[start : start + n]
            job.done += n
            if job.remaining == 0:
                # final chunk: the launch samples this admission's first
                # token and arms its slot ON DEVICE (vectorized arm_slot
                # in mixed_step_ragged); the host learns the first token
                # from the same packed fetch as the decode results
                if arm_np is None:
                    arm_np = self._fresh_arm()
                (on, idx, plen, mtk, sp, presence) = arm_np
                s = job.slot
                on[s] = True
                idx[s] = off + n - 1
                plen[s] = job.prompt_len
                mtk[s] = job.max_tokens
                (sp[0][s], sp[1][s], sp[2][s], sp[3][s], sp[4][s],
                 sp[5][s], sp[6][s], sp[7][s]) = job.sampling
                presence[s] = job.presence_row
                completions[s] = job.req
                job.req.budget = job.max_tokens - 1
        if arm_np is not None:
            (on, idx, plen, mtk, sp, presence) = arm_np
            arm = P.MixedArm(
                jnp.asarray(on), jnp.asarray(idx), jnp.asarray(plen),
                jnp.asarray(mtk),
                G.SlotParams(*(jnp.asarray(a) for a in sp)),
                jnp.asarray(presence),
            )
        if self._table_dev is None:
            self._table_dev = jnp.asarray(self._table)
        # the spec operands ride only when needed: launches with neither
        # a verify row nor a frozen (unfetched-verify) slot dispatch the
        # plain program — the pre-speculation fast path, byte-identical
        spec_plan_dev = spec_toks_dev = None
        spec_meta = None
        if self._draft_mode:
            # keep the DRAFT pool tracking the canonical stream: every
            # mixed step lands its prefill chunks and each decode row's
            # current token (dec_flag-substituted from slot state, like
            # the target) in the draft model's pool — so the propose
            # chain's context matches the target's position for
            # position. Launches the fleet serves through the amortized
            # chunk program leave draft-pool holes; those only ever
            # degrade draft QUALITY (acceptance is verified against the
            # target's own argmax).
            self._dpool = P.mixed_fill_draft(
                self._dcfg, self._dparams, jnp.asarray(toks),
                jnp.asarray(tok_row), jnp.asarray(tok_pos),
                jnp.asarray(dec_flag), jnp.asarray(meta), self._dpool,
                self._table_dev, self.state.token, self.state.pos,
                dev=dev_dev,
            )
        if spec_rows or any(b in self._spec_inflight for b in assigned):
            spec_plan_dev = P.SpecPlan(
                jnp.asarray(dec_on), jnp.asarray(sp_on),
                jnp.asarray(sp_idx), jnp.asarray(sp_nd),
            )
            spec_meta = {
                b: (self._assignment[b], spec_rows[b][0])
                for b in spec_rows
            }
            if self._draft_mode and spec_rows:
                # batched greedy draft chain from every slot's current
                # (token, pos) over the shared block tables; the
                # proposals feed the mixed program as a device operand —
                # zero host syncs anywhere in the draft path
                spec_toks_dev, self._dpool = P.draft_propose_paged(
                    self._dcfg, self._dparams, self.state.token,
                    self.state.pos, self._dpool, self._table_dev,
                    draft_len=self._spec_k_max,
                )
        # adapter serving: the per-slot page snapshot rides the launch
        # (row -> page via the same tok_row indirection as the block
        # table; page 0 = base). pages=None when no pool is attached —
        # a distinct program that lowers byte-identically to before.
        pages_dev = (
            jnp.asarray(self._slot_pages)
            if self._adapters is not None else None
        )
        packed, self.state, self.sparams, self.cache = (
            self.backend.mixed_step_ragged(
                jnp.asarray(toks), jnp.asarray(tok_row),
                jnp.asarray(tok_pos), jnp.asarray(dec_flag),
                jnp.asarray(meta), self.cache, self._table_dev,
                self.state, self.sparams, self._next_key(),
                jnp.asarray(dec_idx), arm,
                spec=spec_plan_dev, spec_toks=spec_toks_dev,
                dev=dev_dev, pages=pages_dev,
            )
        )
        # host position model + completion bookkeeping AFTER the launch
        # is enqueued (the arming rode the program itself). Verify rows
        # do NOT advance here: their advance is data-dependent (the
        # accept count), so the host resyncs from the packed fetch —
        # legacy mode freezes the slot until then (_spec_inflight),
        # device-meta mode records the pending launch (predicted window
        # + advance bound) and keeps submitting rows.
        for b in active:
            self._row_inflight[b] += 1
            if b in spec_rows:
                if self._spec_devmeta:
                    nd, _drafts, pred = spec_rows[b]
                    lst = self._spec_pending.setdefault(b, [])
                    if lst:
                        self.spec_pipelined += 1
                    lst.append({
                        "req": self._assignment[b], "nd": nd,
                        "pred": pred, "adv": nd + 1,
                    })
                else:
                    self._spec_inflight[b] = spec_meta[b]
            else:
                self._host_pos[b] += 1
        if spec_rows:
            mode = "draft_model" if self._draft_mode else "ngram"
            drafted = sum(nd for nd, _, _ in spec_rows.values())
            self._m_spec_launches.labels(mode=mode).inc(len(spec_rows))
            self._m_spec_drafted.inc(drafted)
            self.spec_launches += len(spec_rows)
            self.spec_drafted += drafted
            for b, (nd, _, _) in spec_rows.items():
                self._sched.count_spec_plan(nd)
                req = self._assignment[b]
                if req is not None:
                    req.spec_launches += 1
                    req.spec_drafted += nd
        for slot, req in completions.items():
            job = self._prefilling.pop(slot)
            self._jobs.remove(job)
            self._host_pos[slot] = job.prompt_len
            if self._bpx is not None:
                # full prompt blocks are complete + immutable once this
                # launch lands; later gathers serialize behind it on
                # device — same register point as the whole-prefill path.
                # Adapter requests register under their ADAPTER root:
                # the KV bytes are adapter-conditioned, so only requests
                # of the same adapter may reuse them.
                self._bpx.register(
                    job.ids, job.prompt_len, req.block_ids,
                    adapter=req.adapter,
                )
        if self._shadow is not None:
            # chunk crossed a block boundary -> those blocks are now
            # immutable; the capture gather dispatches BEHIND the mixed
            # launch above, so it reads their final content
            for job, _, _ in chunk_list:
                self._shadow_capture(job.req, written=job.p0 + job.done)
        # launch-composition observability
        n_pf_tokens = sum(n for _, n, _ in chunk_list)
        # flight recorder: the scheduler plan with its budget split —
        # only steps that actually interleaved prefill work are recorded
        # (pure-decode steps would flood the ring with no forensic value)
        if chunk_list or spec_rows:
            self.engine.flight.record(
                "plan", seq=self._mutation_seq, decode_rows=n_dec,
                prefill_chunks=len(chunk_list),
                prefill_tokens=n_pf_tokens, spec_rows=len(spec_rows),
                budget=self._sched.last_plan,
            )
        self._m_sched_rows.inc(n_dec)
        self._m_sched_chunks.inc(len(chunk_list))
        self._m_sched_tokens.labels(kind="decode").inc(n_dec)
        self._m_sched_tokens.labels(kind="prefill").inc(n_pf_tokens)
        if stats["prefill_rows"]:
            self._m_ragged_rows.labels(kind="prefill").inc(
                stats["prefill_rows"]
            )
        if stats["decode_rows"]:
            self._m_ragged_rows.labels(kind="decode").inc(
                stats["decode_rows"]
            )
        self._m_ragged_tiles.labels(state="pad").inc(stats["pad_tiles"])
        self._m_ragged_tiles.labels(state="live").inc(
            stats["tiles"] - stats["pad_tiles"]
        )
        self._m_ragged_launches.labels(phase="mixed").inc()
        # decode snapshot: only rows DECODING at launch (mid-prefill rows
        # emit nothing; the completing slot's first decode token arrives
        # with the NEXT launch; legacy-mode slots frozen behind an
        # unfetched verify row carry no row at all) — attribution
        # discipline as ever
        snapshot = [
            self._assignment[b] if b in active else None for b in range(B)
        ]
        t_launch = time.perf_counter()
        if self._trace_rate > 0.0:
            self._prof_note_launch(
                "mixed", t_launch, snapshot, seq=self._mutation_seq,
                decode_rows=n_dec, prefill_chunks=len(chunk_list),
                prefill_tokens=n_pf_tokens,
                spec_drafted=sum(nd for nd, _, _ in spec_rows.values()),
            )
        return (
            "mixed", packed, snapshot, completions, t_launch,
            self._mutation_seq,
            spec_meta if spec_plan_dev is not None else None,
        )

    def _fresh_arm(self):
        """Mutable numpy MixedArm builder (one per launch WITH
        completions; completion-free steps reuse the device-resident
        idle arm and ship no [B, V] presence buffer)."""
        B, V = self.n_slots, self.cfg.vocab_size
        return (
            np.zeros((B,), bool), np.zeros((B,), np.int32),
            np.zeros((B,), np.int32), np.zeros((B,), np.int32),
            [
                np.ones((B,), np.float32), np.zeros((B,), np.int32),
                np.ones((B,), np.float32), np.ones((B,), bool),
                np.zeros((B,), np.float32), np.ones((B,), np.float32),
                np.zeros((B,), np.float32), np.zeros((B,), np.float32),
            ],
            np.zeros((B, V), bool),
        )

    def _process_mixed(self, step):
        """Fetch one mixed step's packed results: first-token bookkeeping
        for admissions that completed their prefill in that launch,
        verify-row resync/accounting (position advance, accept counts),
        then the shared decode distribution (stop/cancel/deadline/
        finalize) over the combined emission matrix."""
        _, packed_dev, snapshot, completions, t_launch, seq, spec_meta = step
        faults.check("fetch", tag=",".join(
            r.prompt for r in snapshot if r is not None
        ))
        # [5, B] plain / [5 + 2*(K+1) + 1, B] with a SpecPlan — still the
        # ONE fetch per step
        packed = np.asarray(packed_dev)
        self._m_step.observe(max(0.0, time.perf_counter() - t_launch))
        emitted, mask, active, firsts, armed = packed[:5]
        sp_emit = sp_mask = sp_adv = None
        if spec_meta is not None:
            K1 = self._spec_k_max + 1
            sp_emit = packed[5 : 5 + K1]
            sp_mask = packed[5 + K1 : 5 + 2 * K1].astype(bool)
            sp_adv = packed[5 + 2 * K1]
        now = time.time()
        for slot, req in completions.items():
            if req.done.is_set() or req.drop_seq > seq:
                # drop_seq: the tenant was preempted after this step
                # launched — its completion bookkeeping is stale (the
                # resume re-admission regenerates the first token)
                continue
            req.first_id = int(firsts[slot])
            if not req.ttft:
                req.ttft = now - req.t_start
            req.trace.checkpoint("admission")  # chunked prefill span
            with self._cv:
                self.admitted += 1
                if req.record:
                    self.engine.request_count += 1
                occ = sum(r is not None for r in self._assignment)
                self.peak_occupancy = max(self.peak_occupancy, occ)
            self._m_occupied.set(occ)
            if req.record:
                self._m_admission_wait.observe(now - req.enqueued)
            log.info(
                "admitted", slot=slot, prompt_len=req.prompt_tokens,
                budget=req.budget, occupancy=occ, chunked=True,
                request_id=req.trace.request_id,
            )
            self._post_admit(req)
        em = emitted[None, :]
        mk = mask[None, :].astype(bool)
        prof_acc = 0  # accepted draft tokens in THIS launch (attribution)
        if spec_meta:
            # combined emission matrix: decode rows keep their one
            # token in row 0, verify rows splice their whole emission
            # stream — _distribute then applies the shared stop/cancel/
            # deadline/finalize/shadow discipline to both uniformly
            B = self.n_slots
            K1 = self._spec_k_max + 1
            em = np.zeros((K1, B), emitted.dtype)
            mk = np.zeros((K1, B), bool)
            em[0] = emitted
            mk[0] = mask.astype(bool)
            for slot, (req, nd) in spec_meta.items():
                em[:, slot] = sp_emit[:, slot]
                mk[:, slot] = sp_mask[:, slot]
                self._spec_inflight.pop(slot, None)
                pend = self._spec_pending.get(slot)
                if pend:
                    # device-meta mode: this fetch confirms the slot's
                    # OLDEST pending verify launch (fetches are FIFO) —
                    # its predicted window retires; the actual emissions
                    # land in req.tokens via _distribute below
                    pend.pop(0)
                    if not pend:
                        del self._spec_pending[slot]
                n_emit = int(sp_mask[:, slot].sum())
                acc = max(0, n_emit - 1)
                if (
                    self._assignment[slot] is req
                    and not req.done.is_set() and req.drop_seq <= seq
                ):
                    # position resync: the verify advanced the slot by
                    # the accepted count (+1 on an EOS step) — the host
                    # model catches up (and in legacy mode the slot
                    # re-enters the next launch plan)
                    self._host_pos[slot] += int(sp_adv[slot])
                    # adaptive-K feedback: the slot's acceptance EWMA
                    # sizes its next draft (same packed fetch, zero
                    # extra syncs)
                    self._sched.observe_spec(slot, nd, acc)
                self._m_spec_accepted.inc(acc)
                self._m_spec_rejected.inc(max(0, nd - acc))
                self._m_spec_hist.observe(n_emit)
                self.spec_accepted += acc
                req.spec_accepted += acc
                prof_acc += acc
        self._distribute(em, mk, active.astype(bool), snapshot, seq=seq)
        for b, r in enumerate(snapshot):
            if r is not None and self._row_inflight[b] > 0:
                self._row_inflight[b] -= 1
        # close this launch's attribution record (empty deque at sample
        # rate 0 — the guard is one truthiness check, no allocation)
        if self._launch_log:
            self._prof_close_launch(t_launch, spec_accepted=prof_acc)
        self._consecutive_crashes = 0
        if seq >= self._mutation_seq:
            self._suspects.clear()

    def _admit(self):
        """Prefill + splice every queued request a free slot can take.

        The whole admission wave's first tokens come back in ONE stacked
        fetch at the end (the EOS/budget decision already happened on
        device inside insert_slot) — per-request blocking fetches would pay
        the tunnel RTT once per admission.
        """
        wave = []  # (req, first_dev [1]) admitted this round
        while True:
            with self._cv:
                # preempted requests resume FIRST: a victim must not also
                # lose its place behind the queue that evicted it
                from_resume = bool(self._resume)
                if not from_resume and not self._queue:
                    break
                free = [b for b, r in enumerate(self._assignment) if r is None]
                if not free:
                    break
                if (
                    not from_resume
                    and self.paged
                    and self._queue[0].need is not None
                    and self._queue[0].need > self._alloc.free_blocks + (
                        self._bpx.evictable_blocks()
                        if self._bpx is not None else 0
                    )
                ):
                    # a prior attempt already sized this request (need =
                    # FRESH blocks after any mapped shared head) and the
                    # pool still can't take it even by evicting every
                    # unreferenced cached chain — don't re-tokenize/replan
                    # on every chunk iteration; wait for a release
                    break
                if from_resume:
                    req = self._resume.pop(0)
                else:
                    req = self._queue.pop(0)
                    self._note_queue_locked()
            if (
                from_resume and req.allowed is not None
                and len(req.salvaged) >= req.allowed
            ):
                # budget fully consumed before the preemption landed:
                # finalize straight from the salvage record
                self._finalize(req)
                continue
            try:
                # suspect-set bookkeeping: this request mutates the fleet
                # now; until a chunk launched after this point fetches
                # clean, a scheduler crash implicates it (_supervise)
                self._suspects.add(req)
                self._mutation_seq += 1
                # _admitting stays set through an exception unwind ON
                # PURPOSE: the supervisor reads it to salvage the request
                # a crash cut mid-admission (a finally here would erase
                # the crash's only pointer to it and hang the caller)
                self._admitting = req
                if from_resume:
                    # swap-preemption resume: restore the shadowed chain
                    # into the pool first so _admit_one's prefix plan
                    # hits it and re-prefills only the tail
                    self._prepare_resume(req)
                first_dev = self._admit_one(req, free[0])
                self._admitting = None
                if first_dev is _BLOCKED:
                    # paged pool exhausted: requeue at the FRONT (FIFO
                    # fairness) and stop admitting until a release frees
                    # blocks — the fleet keeps decoding meanwhile
                    with self._cv:
                        if from_resume:
                            self._resume.insert(0, req)
                        else:
                            self._queue.insert(0, req)
                            self._note_queue_locked()
                    break
                if first_dev is not None:  # None: failed fast (e.g. queued
                    if from_resume and req.preempted_at:
                        self._m_resume_s.observe(
                            time.time() - req.preempted_at
                        )
                    wave.append((req, first_dev))  # past deadline), result set
            except ValueError as e:
                self._admitting = None
                # release the failed admission's grants (pool blocks,
                # constraint row): a validation error raised between the
                # grant and the insert (late float() of a malformed
                # sampling kwarg, a constraint compile) must not leak —
                # the PR-4 _BLOCKED leak shape on the error path
                self._free_slot_resources(req)
                log.warning("invalid_request", error=str(e))
                req.result = {
                    "error": f"Error: {e}", "status": "failed",
                    "error_type": "invalid_request",
                }
                self._push_final(req)
            # any OTHER exception escapes to the supervisor: the crash is
            # contained there (restart + salvage via _admitting), the
            # request is implicated via the suspect set, and a
            # deterministic admission failure quarantines it within
            # poison_strikes restarts instead of failing fleet-mates
        if not wave:
            return
        firsts = np.asarray(jnp.concatenate([f for _, f in wave]))
        now = time.time()
        for (req, _), first_id in zip(wave, firsts):
            req.first_id = int(first_id)
            if not req.ttft:  # resumed victims keep their first TTFT
                req.ttft = now - req.t_start
            self._post_admit(req)

    def _post_admit(self, req: _Request):
        """First-token bookkeeping shared by the admission wave and the
        recovery path: stop-token-first / zero-budget requests finalize
        immediately (mirroring insert_slot's on-device decision);
        constrained slots arm their fleet-table FSM row — the DFA
        advanced over any salvaged continuation tokens, then the first
        token — BEFORE the next chunk launch (same future-most-state
        contract as insert_slot); streaming clients get their first
        event right after TTFT."""
        self.engine.flight.record(
            "admit", request_id=req.trace.request_id, slot=req.slot,
            prompt_tokens=req.prompt_tokens, budget=req.budget,
            slo_class=req.slo,
            **(self._alloc.span_attrs() if self.paged else {}),
        )
        if req.first_id in self.cfg.all_stop_ids or req.budget == 0:
            self._finalize(req)
            return
        if req.cart is not None:
            cart, off = req.cart
            st = cart.start
            for t in req.salvaged:
                st = cart.advance(st, t)
            self._fsm = self._fsm.at[req.slot].set(
                off + cart.advance(st, req.first_id)
            )
        if req.stream_q is not None:
            self._stream_tokens(req)

    def _admit_one(self, req: _Request, slot: int):
        eng, cfg = self.engine, self.cfg
        faults.check("admission", tag=req.prompt)
        # everything before this point (bounded queue + worker pickup) is
        # queueing delay; a _BLOCKED retry folds its re-wait in here too
        req.trace.checkpoint("queue_wait")
        if req.cancelled:
            # a _BLOCKED requeue can carry a request whose client already
            # went away (stream teardown races the pop) — drop it here
            # instead of letting it head-of-line-block the queue and then
            # burn pool blocks + a prefill on a dead request
            req.result = self._cancel_env(req)
            self._push_final(req)
            return None
        if self._past_deadline(req):
            # end-to-end deadline_ms expired while queued: fail before
            # any prefill launch or pool-block grant
            req.result = self._deadline_env(req, where="while queued")
            self._push_final(req)
            return None
        deadline = eng.engine_cfg.request_deadline_s
        if deadline and time.time() - req.enqueued > deadline:
            req.result = {
                "error": f"Error: request exceeded the {deadline:g}s deadline "
                "while queued",
                "status": "failed",
                "error_type": "timeout",
            }
            self._push_final(req)
            return
        if not self._acquire_adapter(req):
            # every adapter page is referenced by other in-flight
            # requests: backpressure, caller requeues at the front.
            # Acquired BEFORE any block incref so the unwind paths below
            # only release what they took on top of it.
            return _BLOCKED
        k = req.kwargs
        text = (
            eng.render_chat(req.prompt)
            if k.get("chat", True) else req.prompt
        )
        ids = eng.tokenizer.encode(text)
        req.prompt_tokens = len(ids)
        if req.salvaged:
            # crash-recovery continuation: prefill prompt + the tokens
            # generated before the crash (all host-side), so greedy decode
            # resumes bit-exactly where the fetched stream stopped
            ids = ids + list(req.salvaged)
        prompt_len = len(ids)
        if req.kv_hint is not None and req.adapter is None:
            # router handoff hint: pull the prefix chain from the
            # resident peer BEFORE planning, so the plan below sees it
            # as an ordinary (deeper) block-prefix hit; every fetch
            # failure degrades to the cold plan. Adapter requests never
            # prefetch — fabric chains are BASE-model KV keyed by token
            # content alone.
            self._fabric_prefetch(req, ids)
        # tier promotion: a host/disk-shadowed chain deeper than the
        # pool's block-prefix index becomes a deeper exact-depth hit in
        # the plan below — the disk tier's re-entry point (self-gates;
        # can never fail the request)
        self._promote_local_chain(req, ids)
        # prefix lookup + ingest plan: the solo engine's shared planner
        # helper (one copy of the lookup/cold-fallback/mark discipline);
        # the planner is mode-specific — block-chain index (paged) or
        # dense snapshot cache. ragged=True (paged ragged ingest) plans
        # the tail as fixed-width launches with NO bucket ladder, so the
        # deepest cached chain is reused at EXACT chunk depth — the
        # degradation walk only runs for the bucketed fallback.
        p0, entry, plan = eng._prefix_plan(
            self._bpx if self.paged else self._prefix, ids,
            capacity=self.slot_max_seq, ragged=self._ragged,
            adapter=req.adapter,
        )
        if plan is None:
            raise ValueError(
                f"prompt length {prompt_len} exceeds the slot capacity "
                f"(slot_max_seq {self.slot_max_seq})"
            )
        max_tokens, _ = eng._clamp_decode(
            prompt_len, int(k.get("max_tokens", 20)) - len(req.salvaged),
            capacity=self.slot_max_seq,
        )
        if req.allowed is None:
            req.allowed = max_tokens  # total generated-token cap, fixed once
        else:
            # re-admission: never exceed the cap fixed at first admission
            max_tokens = min(max_tokens, req.allowed - len(req.salvaged))
        if req.recovering:
            # warm recovery's headline number: the tail this salvage
            # re-admission actually re-prefills (everything past the
            # restored/mapped head; cold recovery recomputes it all)
            self._m_recovery_recomputed.inc(prompt_len - p0)
            req.recovering = False
        table_row = insert_row = None
        if self.paged:
            faults.check("alloc", tag=req.prompt)
            need_total = self._P.blocks_needed(
                prompt_len, max_tokens, self.kv_block_size
            )
            # entry may be deeper than the PLANNED depth (bucket limits
            # degrade p0 — engine._prefix_plan): map exactly p0 worth
            shared = list(entry)[: p0 // self.kv_block_size] if p0 else []
            n_shared = len(shared)
            # need records the FRESH-block shortfall for the head-of-queue
            # backpressure check — the mapped head costs no new blocks
            req.need = need_total - n_shared
            if shared:
                # hold the mapped chain NOW: this admission's own eviction
                # (below) must never reclaim the blocks it is about to
                # map. block_ids carries the holders immediately so a
                # crash inside the pressure ladder (the preempt fault
                # point) releases them through the supervisor's unwind.
                self._alloc.incref(shared)
                req.block_ids = list(shared)
            # full pressure ladder: evict unreferenced cached chains,
            # then PREEMPT a decoding victim (engine_cfg.preempt_policy)
            # instead of stalling — "pool full" is a policy decision now
            blk_ids = self._alloc_with_pressure(req)
            if blk_ids is None:
                if shared:
                    self._alloc.decref(shared)
                req.block_ids = None
                self._release_adapter(req)
                return _BLOCKED  # pool exhausted; caller requeues at front
            req.block_ids = shared + blk_ids
            table_row = np.zeros((self._max_blocks,), np.int32)
            table_row[: need_total] = req.block_ids  # tail stays at trash
            # insert scatters the WHOLE scratch row; the shared head must
            # not be rewritten (other tables read those exact blocks), so
            # the insert's view of the row redirects head entries to the
            # write-only trash block — the DECODE table keeps the real row
            insert_row = table_row
            if n_shared:
                insert_row = table_row.copy()
                insert_row[:n_shared] = self._P.TRASH_BLOCK
        if k.get("constraint") is not None:
            # compiled-artifact reuse by constraint hash (the engine LRU),
            # then residency in the fleet's combined table; a full table
            # backpressures exactly like the paged pool
            cart = eng._compile_constraint(k["constraint"])
            req.trace.checkpoint("constraint_compile")
            off = self._ctable.acquire(cart)
            if off is None:
                if req.block_ids is not None:
                    # blocks were granted above: release them (decref —
                    # the mapped head just loses this holder) or every
                    # constraint-backpressure retry would re-allocate and
                    # orphan the first grant
                    self._alloc.decref(req.block_ids)
                    req.block_ids = None
                self._release_adapter(req)
                return _BLOCKED  # retry after a release frees rows
            req.cart = (cart, off)
        sampling = G.default_sampling(
            k.get("temperature", 0.7), k.get("top_k", 50),
            k.get("top_p", 0.9), k.get("greedy", False),
            k.get("min_p", 0.0), k.get("repetition_penalty", 1.0),
            k.get("frequency_penalty", 0.0), k.get("presence_penalty", 0.0),
        )
        key = self._next_key()
        use_ragged = self.paged and self._ragged
        scratch = None
        if not use_ragged:
            scratch = self._scratch
            self._scratch = None
        req.prefix_hit_tokens = p0
        # repetition-penalty state: the prompt's token-id set, host-built.
        # The fleet always carries presence (a 1.0 penalty is an exact
        # no-op in the sampler), but the prefill's first-token sample only
        # gets it when the penalty is on — keeping the default prefill
        # program identical to the solo path's.
        rp = float(k.get("repetition_penalty", 1.0))
        presence = eng._presence_rows([ids]) if rp != 1.0 else None
        try:
            faults.check("prefill", tag=req.prompt)
            bias = None
            if req.cart is not None:
                # first-token mask from the DFA state the salvaged
                # continuation lands on (the cold path's start state when
                # salvaged is empty — state_bias(start) == start_bias)
                art = req.cart[0]
                st = art.start
                for t in req.salvaged:
                    st = art.advance(st, t)
                bias = jnp.asarray(art.state_bias(st))
            if use_ragged:
                # ragged ingest: the tail prefills STRAIGHT INTO THE POOL
                # (flat-token launches through the ragged kernel) — no
                # scratch, no shared-head gather, no insert scatter. A
                # prefix hit attends the mapped blocks in place, at the
                # exact depth the planner found.
                if p0:
                    self._m_ragged_exact.inc()
                first = self._ragged_ingest(
                    ids, p0, table_row, key, sampling, presence, bias,
                    page=req.adapter_page,
                )
            elif self.paged:
                if p0:
                    # block-level hit: the shared physical blocks are
                    # already MAPPED into table_row — no splice, no copy
                    # into the pool. One gather assembles the scratch's
                    # contiguous view of the shared head so the chunked
                    # tail prefill below attends real KV; garbage past the
                    # head is overwritten by the tail or never attended.
                    scratch = self.backend.fill_scratch_paged(
                        self.cache, jnp.asarray(table_row)
                    )
                first, _, scratch = eng._ingest(
                    ids, p0, plan, scratch, key, sampling,
                    presence=presence, bias=bias,
                )
            else:
                # shared splice/ingest/store sequence (engine/engine.py) —
                # same machinery, same ordering as the solo path. A
                # grammar constraint masks the FIRST token through the
                # bias operand (engine._constraint_bias), same as solo.
                first, _, scratch = eng._ingest_with_prefix(
                    self._prefix, ids, p0, entry, plan, scratch, key,
                    sampling, presence=presence, bias=bias,
                )
            # prefill token is emitted token #0 (unless EOS — break-before-
            # append); the EOS check happens inside insert_slot on device
            req.budget = max_tokens - 1
            presence_row = (
                presence[0] if presence is not None
                else jnp.zeros((cfg.vocab_size,), bool)
            )
            # one arming-argument tuple for both modes (the dense and
            # paged inserts share generate.arm_slot; sharing the argument
            # list here keeps the call sites from drifting either)
            arm = (
                first[0], jnp.int32(prompt_len), jnp.int32(max_tokens),
                sampling.temperature, sampling.top_k, sampling.top_p,
                sampling.greedy, sampling.min_p, sampling.rep_penalty,
                sampling.freq_penalty, sampling.pres_penalty,
                presence_row,
            )
            if use_ragged:
                # the prompt's K/V is ALREADY in the pool blocks: arm the
                # slot's state only (shared generate.arm_slot semantics)
                self.state, self.sparams = self.backend.arm_slot_paged(
                    self.state, self.sparams, slot, *arm
                )
                self._table[slot] = table_row
                self._table_dev = None  # rebuilt at the next chunk launch
                # the slot decodes under the request's adapter page from
                # its first chunk launch (0 = base)
                self._slot_pages[slot] = req.adapter_page or 0
                # chunked mode reaches here through RECOVERY's serialized
                # whole-prefill re-admissions: seed the host position
                # model so subsequent mixed launches plan this row exactly
                self._host_pos[slot] = prompt_len
            elif self.paged:
                self.cache, self.state, self.sparams = (
                    self.backend.insert_slot_paged(
                        self.cache, scratch, self.state, self.sparams, slot,
                        jnp.asarray(insert_row), *arm,
                    )
                )
                self._table[slot] = table_row
                self._table_dev = None  # rebuilt at the next chunk launch
            else:
                self.cache, self.state, self.sparams = G.insert_slot(
                    cfg, self.cache, scratch, self.state, self.sparams, slot,
                    *arm,
                )
            if not use_ragged:
                self._scratch = scratch
        except BaseException:
            if req.block_ids is not None:
                # admission died after the block grant (failed prefill,
                # device error): release the blocks (decref — the mapped
                # shared head just loses this holder) or the pool leaks
                self._alloc.decref(req.block_ids)
                req.block_ids = None
            if req.cart is not None:
                # same discipline for the constraint residency refcount
                self._ctable.release(req.cart[0].key)
                req.cart = None
            self._release_adapter(req)  # and the adapter-page refcount
            raise
        finally:
            if not use_ragged and self._scratch is None:
                # a failed extend/prefill may have consumed (donated) the
                # scratch buffer mid-sequence; a permanently-None scratch
                # would fail every later admission — reallocate (the
                # ragged path never holds a scratch at all)
                self._scratch = self.backend.init_cache(1, self._scratch_seq)
        if self.paged and self._bpx is not None:
            # index the prompt's full blocks (complete + immutable once
            # the insert scatter above lands — decode and tail writes only
            # target later positions): the request's own fresh blocks
            # become cached chains, the mapped head is promoted. Later
            # admissions' gathers serialize behind this insert on device.
            # Adapter requests register under their adapter root — the
            # KV bytes are adapter-conditioned.
            self._bpx.register(ids, prompt_len, req.block_ids,
                               adapter=req.adapter)
        # the admitted token sequence: shadow capture keys off it, the
        # n-gram draft planner reads it as the slot's history head
        req.ids = ids
        req.shadow_depth = 0
        if self._shadow is not None:
            # shadow the prompt's full blocks (same immutability point
            # as the register above); the gather rides the launch queue
            # behind the prefill, the copy lands on the shadow thread
            self._shadow_capture(req, written=prompt_len)
        req.slot = slot
        req.trace.checkpoint("admission")  # prefill + splice into the slot
        with self._cv:
            self._assignment[slot] = req
            self.admitted += 1
            if req.record:
                eng.request_count += 1
            occ = sum(r is not None for r in self._assignment)
            self.peak_occupancy = max(self.peak_occupancy, occ)
        self._m_occupied.set(occ)
        if req.record:
            self._m_admission_wait.observe(time.time() - req.enqueued)
        log.info(
            "admitted", slot=slot, prompt_len=prompt_len,
            budget=req.budget, occupancy=occ,
            request_id=req.trace.request_id,
        )
        return first  # [1] device array; the wave fetches these together

    def _ragged_launch_args(self, chunk_ids, start):
        """Build one ragged launch's device operands (host-side planning —
        engine/paged.build_ragged_meta — plus the flat token buffer) and
        count its composition into the dli_ragged_* families."""
        P = self._P
        W, tile = self._ragged_width, self._ragged_tile
        meta, tok_row, tok_pos, _, stats = P.build_ragged_meta(
            [(0, start, len(chunk_ids), P.RAGGED_PREFILL)],
            width=W, tile=tile,
        )
        toks = np.zeros((W,), np.int32)
        toks[: len(chunk_ids)] = chunk_ids
        self._m_ragged_rows.labels(kind="prefill").inc(stats["prefill_rows"])
        if stats["decode_rows"]:
            self._m_ragged_rows.labels(kind="decode").inc(
                stats["decode_rows"]
            )
        self._m_ragged_tiles.labels(state="pad").inc(stats["pad_tiles"])
        self._m_ragged_tiles.labels(state="live").inc(
            stats["tiles"] - stats["pad_tiles"]
        )
        return (
            jnp.asarray(toks), jnp.asarray(tok_row), jnp.asarray(tok_pos),
            jnp.asarray(meta),
        )

    def _ragged_ingest(self, ids, p0, table_row, key, sampling, presence,
                       bias, page=None):
        """Prefill ids[p0:] straight into the pool through the ragged
        launch programs: whole-width extend launches for the body of the
        tail, then ONE width-padded prefill launch that samples the first
        token off the tail's last flat position. Exactly two compiled
        programs serve EVERY tail length (the recompile guard the
        analysis ragged rule pins), and a prefix hit's mapped shared head
        is attended in place through the block table — no gather, no
        insert scatter, no bucket ladder. Returns the [1] first-token
        device array (the admission wave's stacked-fetch contract).

        `page`: the admission's adapter page id (engine/adapters.py) —
        rides every TARGET launch as the [1] per-row pages operand so
        prompt KV is computed under the adapter's delta. Draft-model
        twins stay base-only (draft quality, never correctness)."""
        be = self.backend
        W = self._ragged_width
        tail = ids[p0:]
        n_full = max(0, (len(tail) - 1) // W)  # leaves >= 1 sampling token
        table1 = jnp.asarray(
            np.asarray(table_row, np.int32)[None, :]
        )  # [1, MB]: this admission's single fleet row
        pages1 = (
            jnp.asarray(np.asarray([page or 0], np.int32))
            if self._adapters is not None else None
        )
        for c in range(n_full):
            toks, tok_row, tok_pos, meta = self._ragged_launch_args(
                tail[c * W : (c + 1) * W], p0 + c * W
            )
            self.cache = be.extend_ragged_paged(
                toks, tok_row, tok_pos, meta, self.cache, table1,
                pages=pages1,
            )
            if self._draft_mode:
                # draft-model speculation: the prompt must land in the
                # draft pool too (draft_spec_loop's prefill-into-BOTH
                # contract) — same launch plan, draft weights
                self._dpool = self._P.extend_ragged_paged(
                    self._dcfg, self._dparams, toks, tok_row, tok_pos,
                    meta, self._dpool, table1,
                )
            self._m_ragged_launches.labels(phase="extend").inc()
        rem = tail[n_full * W :]
        toks, tok_row, tok_pos, meta = self._ragged_launch_args(
            rem, p0 + n_full * W
        )
        if self._draft_mode:
            self._dpool = self._P.extend_ragged_paged(
                self._dcfg, self._dparams, toks, tok_row, tok_pos,
                meta, self._dpool, table1,
            )
        first, _, self.cache = be.prefill_ragged_paged(
            toks, tok_row, tok_pos, meta, self.cache, table1,
            jnp.int32(len(rem) - 1), key, sampling,
            presence=presence, bias=bias, pages=pages1,
        )
        self._m_ragged_launches.labels(phase="prefill").inc()
        if hasattr(be, "ragged_program_count"):
            # warmup compiles show as the gauge's settle point; a gauge
            # that keeps climbing under steady traffic is a
            # recompile-per-admission regression (also machine-checked by
            # the analysis ragged rule on the lowered programs)
            self._m_ragged_programs.set(be.ragged_program_count())
        return first

    def _process(self, chunk):
        """Fetch one decode chunk's packed results and distribute/finalize."""
        packed_dev, snapshot, t_launch, seq = chunk
        faults.check("fetch", tag=",".join(
            r.prompt for r in snapshot if r is not None
        ))
        packed = np.asarray(packed_dev)  # [2K+1, B] — the ONE fetch per chunk
        # launch-to-fetch over the chunk's steps: under lag-N pipelining
        # this includes queue wait behind earlier chunks, so it is the
        # EFFECTIVE per-token step time the fleet delivers, not raw compute
        self._m_step.observe(
            max(0.0, time.perf_counter() - t_launch) / self.chunk_steps
        )
        K = self.chunk_steps
        emitted = packed[:K]
        mask = packed[K : 2 * K].astype(bool)
        active = packed[2 * K].astype(bool)
        self._distribute(emitted, mask, active, snapshot, seq=seq)
        if self._launch_log:
            self._prof_close_launch(t_launch)
        # healthy step: the fleet (as launched) fetched clean — reset the
        # supervisor's consecutive-crash window, and vindicate suspects
        # when no admission happened after this chunk's launch (an older
        # chunk's clean fetch says nothing about a newer tenant)
        self._consecutive_crashes = 0
        if seq >= self._mutation_seq:
            self._suspects.clear()

    def _distribute(self, emitted, mask, active, snapshot, seq=None):
        """Attribute one fetched launch's emissions ([K, B] + final
        active row) to the snapshot's tenants and handle stop / cancel /
        deadline / finalize — ONE copy for the decode-chunk and mixed-
        scheduler fetch paths. `seq` is the chunk's launch-time mutation
        seq: a preempted victim's drop_seq barrier discards emissions
        from chunks launched before its eviction (they are regenerated
        after resume — appending them would corrupt the salvage order)."""
        deadline = self.engine.engine_cfg.request_deadline_s
        now = time.time()
        for b, req in enumerate(snapshot):
            if req is None or req.done.is_set():
                continue  # freed/killed tenant's masked leftovers
            if seq is not None and req.drop_seq > seq:
                continue  # preempted after this chunk launched
            new = emitted[mask[:, b], b]
            req.tokens.extend(int(t) for t in new)
            if len(new) and self._shadow is not None:
                # decode crossed a block boundary? shadow the newly
                # immutable blocks (token content is host-side now, the
                # filling launch was fetched — device order guarantees
                # the gathered bytes are final)
                self._shadow_capture(req)
            gen = None
            if len(new) and req.kwargs.get("stop"):
                gen = self._gen_text(req)  # ONE full decode per chunk
                if gen[2]:
                    # a textual stop sequence fired: kill the slot NOW —
                    # the fleet serves queued work instead of decoding
                    # text the client will never see (solo truncates
                    # post-hoc; the chunk boundary makes early
                    # termination actually save here)
                    if self._assignment[b] is req:
                        self.state = G.kill_slot(self.state, b)
                        self._m_preempt.labels(reason="stop").inc()
                    self._finalize(req, pre=gen)
                    continue
                if req.stream_q is not None:
                    self._stream_tokens(req, pre=gen)
            elif req.stream_q is not None and len(new):
                self._stream_tokens(req)
            if self._assignment[b] is req and not active[b]:
                self._finalize(req, pre=gen)  # reuse this chunk's decode
            elif req.cancelled and self._assignment[b] is req:
                # client gone: kill the slot so the fleet admits the next
                # queued request instead of decoding to the dead request's
                # full budget
                self.state = G.kill_slot(self.state, b)
                self._m_preempt.labels(reason="cancelled").inc()
                log.info("request_cancelled", slot=b, cause=req.cancel_cause)
                req.result = self._cancel_env(req)
                self._release(req)
            elif self._past_deadline(req, now) and self._assignment[b] is req:
                # end-to-end deadline_ms overrun mid-decode: kill the
                # slot, free blocks/constraint row NOW (checked at the
                # launch boundary only — never inside compiled code)
                self.state = G.kill_slot(self.state, b)
                self._m_preempt.labels(reason="deadline").inc()
                log.info("request_deadline_ms_exceeded", slot=b)
                req.result = self._deadline_env(req)
                self._release(req)
            elif deadline and now - req.t_start > deadline:
                # in-flight overrun: kill the slot, fail the request; the
                # fleet keeps decoding for everyone else
                self.state = G.kill_slot(self.state, b)
                self._m_preempt.labels(reason="deadline").inc()
                log.error("request_deadline_exceeded", slot=b, deadline_s=deadline)
                req.result = {
                    "error": f"Error: request exceeded the {deadline:g}s deadline",
                    "status": "failed",
                    "error_type": "timeout",
                }
                self._release(req)

    def _gen_text(self, req: _Request) -> tuple:
        """(generated ids — crash-salvaged continuation included — then
        stop-truncated text, stop hit) for req."""
        head = (
            [req.first_id]
            if req.first_id is not None
            and req.first_id not in self.cfg.all_stop_ids else []
        )
        gen_ids = list(req.salvaged) + head + req.tokens
        text = self.engine.tokenizer.decode(gen_ids, skip_special_tokens=True)
        cut, hit = self.engine._truncate_at_stop(
            text, req.kwargs.get("stop")
        )
        return gen_ids, cut, hit

    def _finalize(self, req: _Request, pre=None):
        req.trace.checkpoint("decode")  # admission end -> last chunk fetched
        gen_ids, response, stopped = (
            pre if pre is not None else self._gen_text(req)
        )
        req.trace.checkpoint("detokenize")
        if req.stream_q is not None:
            # flush the held-back tail (U+FFFD / stop hold-back), exactly
            # up to the truncation
            self._stream_tokens(req, final=True, pre=(gen_ids, response, stopped))
        elapsed = time.time() - req.t_start
        n = len(gen_ids)
        tps = n / elapsed if elapsed > 0 else 0.0
        if req.record:
            self.engine._record_sample(
                req.ttft, tps, n, elapsed=elapsed, engine="continuous",
                trace_id=(
                    req.trace_ctx.trace_id
                    if req.trace_ctx is not None else None
                ),
            )
            # SLO feedback: the same per-request TTFT/TPOT samples the
            # timing histograms record feed the scheduler's per-class
            # EWMAs — drain estimates, urgency, and decode protection
            self._sched.observe(
                req.slo, req.ttft or None,
                max(0.0, elapsed - req.ttft) / (n - 1) if n > 1 else None,
            )
            # per-tenant twin of the same samples (tenant EWMAs for the
            # operator's fairness view; no-op for anonymous requests)
            self._sched.observe_tenant(
                req.tenant, req.ttft or None,
                max(0.0, elapsed - req.ttft) / (n - 1) if n > 1 else None,
            )
        req.result = {
            "prompt": req.prompt,
            "response": response,
            "status": "success",
            "time_taken": f"{elapsed:.2f}s",
            "tokens_generated": n,
            "prompt_tokens": req.prompt_tokens,
            "tokens_per_sec": f"{tps:.2f}",
            "ttft_s": round(req.ttft, 4),
            "backend": "continuous",
            "continuous": True,
            # allowed is the total generated-token cap fixed at first
            # admission (budget + 1 there; re-admissions shrink budget but
            # keep allowed, so recovered requests report honestly)
            "finish_reason": (
                "stop" if stopped or n < (
                    req.allowed if req.allowed is not None
                    else req.budget + 1
                ) else "length"
            ),
        }
        if req.slo is not None:
            req.result["slo_class"] = req.slo
        if req.adapter is not None:
            req.result["adapter"] = req.adapter
        if req.tenant is not None:
            req.result["tenant"] = req.tenant
        if req.salvaged:
            # served across a scheduler restart (continuation prefill)
            req.result["recovered"] = True
        if req.preemptions:
            # evicted for pool pressure and resumed (swap or recompute)
            req.result["preempted"] = req.preemptions
        if req.spec_launches or (req.spec_want and self._spec_req_ok(req)):
            # which path served + the draft/accept counts (the solo
            # loops report spec_path "solo" with acceptance on device;
            # a non-greedy/penalized "speculative" request decodes
            # plainly and — like solo — carries no speculative marker)
            req.result["speculative"] = True
            req.result["spec_path"] = "fleet"
            req.result["spec_drafted"] = req.spec_drafted
            req.result["spec_accepted"] = req.spec_accepted
        if req.prefix_hit_tokens:
            req.result["prefix_cached_tokens"] = req.prefix_hit_tokens
        if req.fabric_blocks:
            # prefix blocks pulled over the KV fabric instead of
            # prefilled: the router scores handoff outcomes off this
            req.result["kv_fabric_blocks"] = req.fabric_blocks
        if req.promoted_blocks:
            # prefix blocks promoted out of the local shadow hierarchy
            # (a pushed chain, or a host/disk-tier warm hit) instead of
            # prefilled — a handoff served by a push scores off this
            req.result["kv_promoted_blocks"] = req.promoted_blocks
        if (
            self.fabric_serving and req.ids is not None
            and req.adapter is None
        ):
            # the prompt chain's parent-chained digests (deepest last):
            # the router learns digest->replica residency from these,
            # and a handoff's phase-2 hint carries the deepest one.
            # Adapter requests export NONE — their KV was never
            # shadowed (content keys are base-model-only), so
            # advertising residency would hand out wrong-model bytes
            ds = chunk_digests(
                req.ids, self.kv_block_size,
                max_chunks=len(req.ids) // self.kv_block_size,
            )
            if ds:
                req.result["kv_digests"] = ds[-8:]
        if req.cart is not None:
            req.result["constrained"] = True
        if stopped:
            req.result["stopped"] = True  # a textual stop sequence fired
        log.info(
            "completed", slot=req.slot, tokens=n, elapsed_s=round(elapsed, 3),
            tokens_per_sec=round(tps, 2),
        )
        self._release(req)

    def _free_slot_resources(self, req: _Request):
        """Return every fleet-held resource of `req` (constraint row +
        FSM reset, pool blocks, block-table row, slot assignment) WITHOUT
        finalizing it — shared by _release (completion/cancel/deadline)
        and _preempt_for (the request lives on, parked for resume)."""
        if self._chunked and req.slot is not None:
            # mid-prefill teardown (cancel / deadline / EOS-on-first of a
            # just-armed admission): drop the job so the planner stops
            # scheduling chunks for a dead tenant
            job = self._prefilling.pop(req.slot, None)
            if job is not None and job in self._jobs:
                self._jobs.remove(job)
        if req.cart is not None:
            # refcount down; the slot's FSM row back to the free state so
            # the row is inert under any still-constrained chunk program
            self._ctable.release(req.cart[0].key)
            if req.slot is not None:
                self._fsm = self._fsm.at[jnp.int32(req.slot)].set(0)
            req.cart = None
        if self.paged and req.block_ids is not None:
            # Worker-thread-only mutation (like all allocator use). DECREF,
            # not free: blocks cached by the block-prefix index (or mapped
            # by other live tables) survive this request and keep serving
            # prefix hits; only sole-holder blocks return to the free
            # list. Those freed blocks may be re-granted before in-flight
            # chunks drain: safe, because device execution is serialized
            # in launch order and the new tenant's insert scatter
            # overwrites its whole logical extent before any later decode
            # chunk — and this slot's table row reverts to trash at the
            # next table rebuild, so its frozen row can't touch the old
            # blocks in any chunk launched after this point. (A frozen
            # row's overrun clamp only ever writes the request's OWN last
            # allocated block, which is never a registered/shared one —
            # see ARCHITECTURE.md "Block sharing".)
            self._alloc.decref(req.block_ids)
            req.block_ids = None
            if req.slot is not None:
                self._table[req.slot] = 0
                self._table_dev = None
        if self.paged and req.slot is not None:
            # the slot reverts to the base page; later launches carrying
            # the frozen row read page 0 (the all-zero delta — inert)
            self._slot_pages[req.slot] = 0
        self._release_adapter(req)
        with self._cv:
            if req.slot is not None and self._assignment[req.slot] is req:
                self._assignment[req.slot] = None
            occ = sum(r is not None for r in self._assignment)
            self._cv.notify_all()
        self._m_occupied.set(occ)

    def _release(self, req: _Request):
        self._free_slot_resources(req)
        with self._cv:
            self.completed += 1
        self._push_final(req)

    def _push_final(self, req: _Request):
        """Single completion point: attach the trace (request_id +
        timings), count + log the request (warmup traffic excluded via
        record=False — same exclusion as /stats), then deliver. Streaming
        clients get the terminal envelope event (done: true) on their
        queue, then the done flag unblocks submit()."""
        if req.result is not None:
            self.engine._finish_request(
                req.result, req.trace, engine="continuous",
                record=req.record,
            )
        if req.stream_q is not None and req.result is not None:
            out = dict(req.result)
            out["done"] = True
            req.stream_q.put(out)
        req.done.set()
