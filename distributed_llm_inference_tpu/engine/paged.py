"""Block-paged KV cache for continuous batching (vLLM-style, TPU-first).

The dense slot fleet (engine/continuous.py) pins `n_slots x slot_max_seq`
of KV in HBM for the server's lifetime — every slot pays for the worst
case even when typical requests use a fraction of the window. Here KV
lives in a shared pool of fixed-size blocks:

    pool k/v [L, n_blocks, KV, block_size, Dh]

and each slot's logical sequence is a *block table* — an int32 row mapping
logical block j to a physical pool block. Admission allocates exactly
ceil((prompt_len + max_tokens) / block_size) blocks from a host-side
REFCOUNTED free list; release decrefs them. Fleet memory is a function of
the POOL size (aggregate tokens actually in flight), not n_slots x
window, and the pool naturally backpressures: a request that cannot get
blocks waits in the queue until a running request completes (after the
block-prefix index has evicted what it can — engine/block_prefix.py).

Block-level prefix sharing rides the refcounts: full prompt blocks are
immutable once the insert scatter lands, so a prefix hit MAPS the cached
physical blocks into the new request's table (one more holder each),
gathers a contiguous scratch view of the shared head
(gather_scratch_blocks) for the tail prefill, and scatters the scratch
back with the head entries of the insert's row redirected to the trash
block. Both decode paths run unchanged over shared tables. See
ARCHITECTURE.md "Block sharing" for the invariant walk-through.

TPU/XLA design notes (why this shape, not a translation of vLLM's CUDA
paged attention):
  * Static shapes everywhere: every table is a fixed [B, max_blocks]
    int32 array (unused tail entries point at a reserved TRASH block);
    the decode program is compiled once per (n_slots, num_steps), exactly
    like the dense fleet.
  * The per-step attention has two paths. attn_impl="xla": GATHER the
    slot's blocks into a contiguous [B, KV, max_blocks*bs, Dh] view and
    run the stock masked attention — the gather reads the same bytes a
    dense cache read would, plus one materialization (~+2 x
    cache-bytes/step of HBM traffic vs dense while weight streaming
    still dominates at small batch). attn_impl="pallas": the fused
    paged-attention kernel (ops/paged_attention.py) walks the block
    table directly with an online softmax — one DMA per LIVE block, no
    materialized view, dead blocks never leave HBM.
  * Writes are scatters: token K/V lands at
    pool[table[b, pos_b // bs], :, pos_b % bs] per slot row b. Distinct
    live slots never share a block, so scatter indices never collide
    (the shared trash block only ever receives writes from slots whose
    position has run past their budget — masked garbage, never attended;
    the same stale-region argument as the dense fleet's).

Paged mode serves BOTH families: the hook seam is shared
(models/llama.default_attn_hook; gpt2's block routes through it since
round 5). It runs on the single device AND on dp=1 pp/tp meshes: the pool
shards its layer axis over pp / kv heads over tp exactly like the dense
cache (parallel/partition.pool_spec), the scratch→pool scatter is
layer-local, and ungated ring microsteps redirect their block writes to
the trash block (parallel/pipeline._build_decode_slots_paged).

Reference contrast: /root/reference has no KV cache at all
(Worker1.py:132-134 — full-sequence recompute per token); this module is
north-star scope (serving HBM discipline), not parity scope.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.attention import attend
from ..ops.kv_quant import KVQuant
from ..ops.kv_quant import dequantize as kv_dequantize
from ..ops.kv_quant import quantize_chunk
from . import generate as G

TRASH_BLOCK = 0  # reserved pool block: write-only spill for table tails


def init_pool(cfg: ModelConfig, n_blocks: int, block_size: int,
              n_layers: Optional[int] = None):
    """Zeroed block pool, stacked on the layer axis like the dense cache.
    Block 0 is the reserved trash block (never allocated to a slot).
    With cfg.kv_quant the pool leaves are KVQuant pytrees — int8 blocks
    plus per-(token, head) scales [L, N, KV, bs] — so BOTH HBM levers
    compose: the pool tracks in-flight tokens AND each token costs half
    the bytes. n_layers overrides the layer count (the pp mesh pads the
    layer axis to ceil(L/pp)*pp, matching the padded stacked layers)."""
    shape = (
        n_layers or cfg.n_layers, n_blocks, cfg.n_kv_heads, block_size,
        cfg.head_dim,
    )
    if cfg.kv_quant == "int8":
        sshape = shape[:-1]
        leaf = lambda: KVQuant(  # noqa: E731 - two identical leaves
            jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)
        )
        return {"k": leaf(), "v": leaf()}
    dt = cfg.jnp_dtype
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


class BlockAllocator:
    """Host-side REFCOUNTED free list over pool blocks 1..n_blocks-1 (0 is
    trash).

    Every allocated block carries a reference count: alloc() hands blocks
    out at refcount 1, incref() adds a holder (a request mapping a SHARED
    block into its table, or the block-prefix index caching a chain —
    engine/block_prefix.py), and decref() removes one — a block returns
    to the free list only when its LAST holder lets go. Pool-memory
    accounting therefore counts shared blocks once: free_blocks is the
    physical free list, however many tables map the resident blocks.

    Not thread-safe by itself — the continuous engine calls it only from
    its single worker thread (admission/release), matching the engine's
    single-owner design.

    registry (utils/metrics.MetricsRegistry, optional): pool-occupancy
    gauges (`dli_kv_pool_blocks_total` / `_free`), a shared-block gauge
    (`dli_kv_pool_shared_blocks` — blocks held by more than one
    referencer: live tables and/or the prefix index) and an exhaustion
    counter (`dli_kv_pool_exhausted_total` — alloc refusals, i.e. the
    admission backpressure events) for /metrics.
    """

    def __init__(self, n_blocks: int, registry=None):
        if n_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (one is the trash block)")
        self.n_blocks = n_blocks
        self._free = list(range(1, n_blocks))
        self._ref: dict = {}  # block id -> holders (allocated blocks only)
        self._shared = 0  # blocks at refcount >= 2
        self._m_free = self._m_exhausted = self._m_shared = None
        if registry is not None:
            registry.gauge(
                "dli_kv_pool_blocks_total",
                "paged-KV pool size (excluding the trash block)",
            ).labels().set(n_blocks - 1)
            self._m_free = registry.gauge(
                "dli_kv_pool_blocks_free", "unallocated paged-KV blocks"
            ).labels()
            self._m_free.set(len(self._free))
            self._m_exhausted = registry.counter(
                "dli_kv_pool_exhausted_total",
                "admissions refused because the pool had too few blocks",
            ).labels()
            self._m_shared = registry.gauge(
                "dli_kv_pool_shared_blocks",
                "pool blocks held by more than one referencer "
                "(live block tables and/or the block-prefix index)",
            ).labels()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def outstanding(self) -> int:
        """Blocks currently held by anyone (leak accounting: after every
        holder releases — requests done, prefix index cleared — this must
        be 0, i.e. free_blocks == n_blocks - 1)."""
        return len(self._ref)

    def reset(self):
        """Forget every allocation and rebuild the full free list. The
        scheduler supervisor's DEFENSIVE path only: after a crash it
        releases every holder explicitly (the accounting is the leak
        regression the chaos suite pins) and calls this solely when the
        books still disagree, because a rebuilt pool must never start
        with phantom holders."""
        self._free = list(range(1, self.n_blocks))
        self._ref.clear()
        self._shared = 0
        if self._m_free is not None:
            self._m_free.set(len(self._free))
            self._m_shared.set(0)

    @property
    def shared_blocks(self) -> int:
        return self._shared

    def span_attrs(self) -> dict:
        """Pool occupancy as flat span/flight-event attributes (ISSUE
        17): the tracing span and flight-recorder payloads want a
        JSON-ready snapshot, not live gauge objects. Cheap — three ints
        already maintained by alloc/decref bookkeeping."""
        return {
            "pool_free": len(self._free),
            "pool_outstanding": len(self._ref),
            "pool_shared": self._shared,
        }

    def refcount(self, block: int) -> int:
        """Current holder count (0 = on the free list / never allocated)."""
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> Optional[list]:
        """n blocks at refcount 1, or None (caller keeps the request
        queued — or evicts unreferenced cached chains and retries)."""
        if n > len(self._free):
            if self._m_exhausted is not None:
                self._m_exhausted.inc()
            return None
        out = self._free[:n]
        del self._free[:n]
        for b in out:
            self._ref[b] = 1
        if self._m_free is not None:
            self._m_free.set(len(self._free))
        return out

    def incref(self, ids: list):
        """Add a holder to each block (mapping a shared block into another
        request's table, or caching it in the block-prefix index)."""
        for b in ids:
            c = self._ref[b]  # KeyError on a free block = caller bug
            self._ref[b] = c + 1
            if c == 1:
                self._shared += 1
        if self._m_shared is not None:
            self._m_shared.set(self._shared)

    def decref(self, ids: list):
        """Drop one holder per block; blocks reaching zero return to the
        free list. Replaces unconditional free(): a completed request
        decrefs its whole table and shared blocks simply lose one mapper.
        """
        for b in ids:
            c = self._ref[b] - 1
            if c == 0:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = c
                if c == 1:
                    self._shared -= 1
        if self._m_free is not None:
            self._m_free.set(len(self._free))
            self._m_shared.set(self._shared)

    def free(self, ids: list):
        """Back-compat spelling of decref() — single-holder blocks behave
        exactly as the pre-refcount free list did."""
        self.decref(ids)


def blocks_needed(prompt_len: int, max_tokens: int, block_size: int) -> int:
    """Physical blocks a request occupies: prompt positions plus decode
    writes (the last emitted token's K/V is never written, but the frozen
    inactive row keeps re-writing at its final position — bound by
    prompt_len + max_tokens)."""
    return -(-(prompt_len + max_tokens) // block_size)


def make_paged_hook(table: jnp.ndarray):
    """attn_hook for models/llama.decoder_layer over a paged pool.

    table: [B, max_blocks] int32 physical block ids. The hook sees this
    layer's pool slice (cache_k/v [N, KV, bs, Dh], the layer axis unstacked
    by forward_layers' scan) and per-row positions pos [B]; the chunk is
    always T=1 (decode — prefill runs on a contiguous scratch cache and is
    spliced in by insert_slot_paged).
    """

    def hook(cfg, q, k, v, cache_k, cache_v, pos, mask, update_gate,
             valid_start, window_flag=None):
        del valid_start  # slots never left-pad
        # window_flag (mixed per-layer patterns): the XLA gather path
        # ignores it — decoder_layer resolved `mask` per layer already —
        # but the fused kernel derives its traced width from it below
        B, T, H, Dh = q.shape
        assert T == 1, "paged hook serves decode steps (T=1) only"
        bs = cache_k.shape[2]
        MB = table.shape[1]
        # Write: token K/V -> pool[table[b, pos_b//bs], :, pos_b%bs].
        # The lblk clamp is the overrun guard: an inactive slot's frozen
        # row keeps forwarding its pad token and its pos can sit one past
        # the budget — the clamped write lands garbage in the slot's OWN
        # last block at a position only its own (masked, discarded) rows
        # ever attend. Same argument as the dense fleet's
        # dynamic_update_slice clamp (ops/attention.update_kv_cache_slots).
        lblk = jnp.minimum(pos // bs, MB - 1)  # [B]
        blk = jnp.take_along_axis(table, lblk[:, None], axis=1)[:, 0]  # [B]
        if update_gate is not None:
            # pp ring: a stage applies its layer shard EVERY microstep but
            # owns the live buffer on exactly one — ungated microsteps
            # redirect their scatter to the write-only TRASH block (table
            # tails only map logical positions past every slot's budget,
            # so trash content is never attended). Same slice-granularity
            # discard as the dense pipeline's gated cache writes.
            blk = jnp.where(update_gate, blk, TRASH_BLOCK)
        off = pos % bs
        if isinstance(cache_k, KVQuant):
            # int8 pool: quantize the token's K/V, scatter data + scale
            # into the slot's block
            qk, sk = quantize_chunk(k)
            qv, sv = quantize_chunk(v)
            new_k = KVQuant(
                cache_k.q.at[blk, :, off, :].set(qk[:, 0]),
                cache_k.s.at[blk, :, off].set(sk[:, 0]),
            )
            new_v = KVQuant(
                cache_v.q.at[blk, :, off, :].set(qv[:, 0]),
                cache_v.s.at[blk, :, off].set(sv[:, 0]),
            )
        else:
            new_k = cache_k.at[blk, :, off, :].set(k[:, 0])
            new_v = cache_v.at[blk, :, off, :].set(v[:, 0])
        if cfg.attn_impl == "pallas":
            # Fused Pallas paged attention (ops/paged_attention.py) for
            # BOTH leaf types: walks the table block by block with an
            # online softmax — no contiguous-view materialization, dead
            # blocks never leave HBM; int8 pools dequantize in the block
            # prologue (half the bytes per live block). The full variant
            # surface runs fused since round 5: softcap and scale
            # overrides are static kernel params, and mixed per-layer
            # window patterns feed this layer's width through the
            # window_dyn scalar-prefetch operand (window_flag only
            # exists for mixed configs — models/llama.make_window_flags).
            from ..models.llama import kernel_window
            from ..ops.paged_attention import paged_flash_attend

            w, wd = kernel_window(cfg, window_flag)
            attn = paged_flash_attend(
                q, new_k, new_v, table, pos, wd, window=w,
                scale=cfg.query_scale, softcap=cfg.attn_softcap,
            )
            return attn, new_k, new_v

        # Gather the whole table -> ONE contiguous per-slot view recipe
        # for both leaf types (int8 slabs dequantize through the dense
        # path's ops/kv_quant.dequantize; raw slabs gather as-is). Each
        # gathered slab is a [KV, bs, Dh] contiguous run of HBM; stale
        # content at logical positions > pos[b] (trash block included) is
        # masked by the slot causal mask, which forward_layers built to
        # the LOGICAL length MB*bs via attn_seq_len.
        KV_ = cache_k.shape[1]

        def gathered(leaf):
            g = (
                kv_dequantize(KVQuant(leaf.q[table], leaf.s[table]))
                if isinstance(leaf, KVQuant) else leaf[table]
            )  # [B, MB, KV, bs, Dh]
            return g.transpose(0, 2, 1, 3, 4).reshape(B, KV_, MB * bs, Dh)

        attn = attend(
            q, gathered(new_k), gathered(new_v), mask,
            scale=cfg.query_scale, softcap=cfg.attn_softcap,
        )
        return attn, new_k, new_v

    return hook


def scatter_scratch(pool, scratch, table_row):
    """Scatter a CONTIGUOUS batch-1 scratch cache into `table_row`'s pool
    blocks, leaf by leaf (shared by the single-device insert and the pp
    backend's shard_map insert — the scatter is layer-local, so it runs
    unchanged on a layer-sharded pool slice)."""

    def scatter(pl, sc):
        # sc [L, 1, KV, S, Dh] -> [L, MB, KV, bs, Dh] block view; the
        # int8 pool's scale leaves ride the same recipe one rank down
        # ([L, 1, KV, S] -> [L, MB, KV, bs])
        bs = pl.shape[3]
        if sc.ndim == 5:
            L, _, KV, S, Dh = sc.shape
            MB = S // bs
            blocks = (
                sc[:, 0].reshape(L, KV, MB, bs, Dh).transpose(0, 2, 1, 3, 4)
            )
        else:
            L, _, KV, S = sc.shape
            MB = S // bs
            blocks = sc[:, 0].reshape(L, KV, MB, bs).transpose(0, 2, 1, 3)
        return pl.at[:, table_row].set(blocks)

    return jax.tree.map(scatter, pool, scratch)


def _gather_blocks(shared_pool, table_row):
    """Core of gather_scratch_blocks (un-jitted so the pp backend's
    shard_map body can trace it layer-locally — the gather reads whole
    blocks, so it runs unchanged on a layer-sharded pool slice)."""

    def g(pl):
        # pl [L, N, KV, bs(, Dh)] -> row blocks [L, MB, KV, bs(, Dh)] ->
        # contiguous batch-1 scratch layout [L, 1, KV, MB*bs(, Dh)]; the
        # int8 pool's scale leaves ride the same recipe one rank down
        blocks = pl[:, table_row]
        if pl.ndim == 5:
            L, MB, KV, bs, Dh = blocks.shape
            flat = blocks.transpose(0, 2, 1, 3, 4).reshape(L, KV, MB * bs, Dh)
        else:
            L, MB, KV, bs = blocks.shape
            flat = blocks.transpose(0, 2, 1, 3).reshape(L, KV, MB * bs)
        return flat[:, None]

    return jax.tree.map(g, shared_pool)


@jax.jit
def gather_scratch_blocks(shared_pool, table_row):
    """Assemble a CONTIGUOUS batch-1 scratch cache from `table_row`'s pool
    blocks — the exact inverse of scatter_scratch. Block-level prefix
    sharing uses it on a hit: the request's table maps the shared physical
    blocks directly (no splice, no copy into the pool), and this one
    gather hands the tail prefill a contiguous view of the shared head so
    the chunked-prefill machinery runs unchanged. Entries past the shared
    head (fresh private blocks, trash tails) gather stale garbage that
    the tail prefill/scatter overwrite or the slot mask discards — same
    stale-region argument as insert_slot_paged's whole-row scatter.

    shared_pool is a READ-ONLY view of live mapped blocks and must NOT be
    donated: other requests' block tables keep reading these exact
    buffers (analysis/rules/donation.py enforces the inverse of its usual
    donate-your-cache rule for this parameter name).
    """
    return _gather_blocks(shared_pool, table_row)


def _gather_shadow(shared_pool, block_ids):
    """Core of gather_shadow_blocks (un-jitted so the pp backend's
    shard_map body can trace it layer-locally — the gather reads whole
    blocks of the LOCAL layer shard, so it runs unchanged on a
    layer-sharded pool slice)."""

    def g(pl):
        return pl[:, block_ids].swapaxes(0, 1)

    return jax.tree.map(g, shared_pool)


def _restore_shadow(pool, blocks, block_ids):
    """Core of restore_shadow_blocks (un-jitted for the same shard_map
    reuse: the scatter is layer-local — each stage writes its own layer
    slice of every restored block)."""

    def s(pl, bl):
        return pl.at[:, block_ids].set(bl.swapaxes(0, 1))

    return jax.tree.map(s, pool, blocks)


@jax.jit
def gather_shadow_blocks(shared_pool, block_ids):
    """Read `block_ids`' pool blocks into a fresh stacked buffer for the
    warm-recovery shadow store (engine/shadow.py): each leaf comes back
    [N, L, KV, bs(, Dh)] — one row per requested block, whole layer
    axis. Dispatched by the scheduler worker right AFTER the launch that
    filled the blocks, so device execution order guarantees the gathered
    bytes are the blocks' final (immutable) content; the device->host
    transfer happens on the shadow copier thread, never here.

    shared_pool is a READ-ONLY view of live mapped blocks and must NOT
    be donated: live block tables keep reading these exact buffers
    (same inverse-donation rule as gather_scratch_blocks). block_ids is
    a fixed-width operand (callers pad by repeating a real id) so one
    compiled program serves every capture batch.
    """
    return _gather_shadow(shared_pool, block_ids)


@functools.partial(jax.jit, donate_argnames=("pool",))
def restore_shadow_blocks(pool, blocks, block_ids):
    """Scatter host-restored shadow blocks back into a rebuilt pool in
    ONE launch — the exact inverse of gather_shadow_blocks. `blocks` is
    the pool-structured pytree of stacked per-block leaves
    [N, L, KV, bs(, Dh)]; block_ids [N] the freshly allocated physical
    destinations. The pool is donated (updated in place); restored
    blocks are complete by construction, so later tail prefills and
    decode writes only ever land at positions past them — the same
    immutability contract live blocks carry."""
    return _restore_shadow(pool, blocks, block_ids)


def _forward_step_paged(cfg, params, tokens, pool, table, pos, pages=None):
    """One decode step through the stack over the paged pool (family-
    dispatched: gpt2 rides the same hook seam). pages: optional [B] i32
    adapter-pool page ids (0 = base) — traced, so adapter mixes never
    recompile."""
    from ..models import api as M

    bs = pool["k"].shape[3]
    MB = table.shape[1]
    x = M.embed(cfg, params, tokens, pos)
    x, pool = M.forward_layers(
        cfg, params["layers"], x, pool, pos,
        attn_hook=make_paged_hook(table), attn_seq_len=MB * bs,
        lora_pages=pages,
    )
    logits = M.unembed(cfg, params, x[:, -1:, :])
    return logits[:, 0, :], pool


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_steps"), donate_argnames=("pool",)
)
def decode_slots_paged(
    cfg: ModelConfig,
    params,
    state: G.SlotState,
    pool,
    table: jnp.ndarray,
    key,
    sparams: G.SlotParams,
    *,
    num_steps: int,
    pages=None,
):
    """Paged twin of generate.decode_slots: advance every slot num_steps
    tokens over the block pool. Same slot_step, same emitted/emit_mask
    contract — only the cache strategy differs, so cross-mode token parity
    is structural. The table is a plain (traced) input: admission changes
    it without recompiling. pages: optional [B] i32 per-slot adapter
    pages (0 = base), traced like the table."""

    def body(carry, sub):
        state, pool = carry
        logits, pool = _forward_step_paged(
            cfg, params, state.token[:, None], pool, table, state.pos,
            pages=pages,
        )
        new, emit, can_emit = G.slot_step(cfg, state, sparams, logits, sub)
        return (new, pool), (emit, can_emit)

    subs = jax.random.split(key, num_steps)
    (state, pool), (emitted, emit_mask) = jax.lax.scan(
        body, (state, pool), subs
    )
    return emitted, emit_mask, state, pool


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def insert_slot_paged(
    cfg: ModelConfig,
    pool,
    scratch,
    state: G.SlotState,
    sparams: G.SlotParams,
    slot,
    table_row: jnp.ndarray,
    first_token,
    prompt_len,
    max_tokens,
    temperature,
    top_k,
    top_p,
    greedy,
    min_p,
    rep_penalty,
    freq_penalty,
    pres_penalty,
    presence_row,
):
    """Scatter a freshly prefilled CONTIGUOUS scratch cache (batch=1,
    max_seq = max_blocks*bs) into the slot's pool blocks and arm its state
    (generate.arm_slot — shared with the dense fleet).

    table_row: [max_blocks] int32 — the slot's physical blocks; tail
    entries past the allocation point at the trash block, whose colliding
    writes are write-only garbage (positions there are beyond every
    owner's budget). One compiled program per prompt bucket is avoided the
    same way insert_slot does it: the WHOLE scratch row is scattered, and
    stale high blocks are never attended. On a block-sharing hit the
    caller passes a row whose SHARED HEAD entries are redirected to the
    trash block too (the decode table keeps the real ids): the mapped
    blocks already hold exactly this content and must not be rewritten
    while other tables read them.
    """
    slot = jnp.int32(slot)
    pool = scatter_scratch(pool, scratch, table_row)
    state, sparams = G.arm_slot(
        cfg, state, sparams, slot, first_token, prompt_len, max_tokens,
        temperature, top_k, top_p, greedy, min_p, rep_penalty,
        freq_penalty, pres_penalty, presence_row,
    )
    return pool, state, sparams


# -- ragged ingest: prefill straight into the pool, no bucket ladder ----------
#
# The bucketed admission path above prefills a request on a CONTIGUOUS
# batch-1 scratch cache (chunked through the prefill-bucket ladder), then
# scatters the whole scratch row into the slot's pool blocks — and on a
# block-prefix hit first GATHERS the mapped shared head back out of the
# pool so the tail chunks can attend it. The ragged path deletes all
# three moves: the prompt tail is laid out on a FLAT token axis (each
# token is a batch row of one — forward_layers' slots mode, so RoPE and
# the learned-position families take per-token positions for free), each
# token's K/V scatters directly into its row's pool block, and attention
# runs over the pool through the ragged kernel
# (ops/paged_attention.ragged_paged_attend) — or its XLA gather twin on
# CPU — reading the mapped shared head IN PLACE. One compiled program per
# launch width covers ANY tail length (the last launch pads with dead
# tiles whose DMA Pallas skips), so the block-prefix planner reuses at
# exact chunk depth instead of degrading to a bucket boundary.

RAGGED_PREFILL = 0  # launch-entry kind: a prompt chunk (length >= 1)
RAGGED_DECODE = 1  # launch-entry kind: one decode token at its own pos


def build_ragged_meta(entries, *, width: int, tile: int):
    """HOST-side launch planner for the ragged ingest programs (strictly
    decode-unreachable — pinned in the test_analysis.py callgraph
    fixture, like utils/faults.py).

    entries: [(row, start, length, kind)] — each fleet row's contribution
    to this launch, in flat-token order; a decode row is (row, pos, 1,
    RAGGED_DECODE), a prefill chunk (row, chunk_start, chunk_len,
    RAGGED_PREFILL). Every entry starts on a query-tile boundary, so an
    entry's tokens occupy flat slots [offset, offset + length)
    contiguously (all its tiles but the last are full).

    Returns (meta [G, 4] int32, tok_row [W] int32, tok_pos [W] int32,
    offsets, stats): meta is the per-tile (row, q_start, q_len, kind)
    array the kernel prefetches; tok_row / tok_pos are the per-token row
    index (-1 = launch padding, scattered to the trash block) and
    absolute position; offsets[i] is entry i's flat token offset; stats
    counts tiles/pad_tiles/rows-by-kind for the dli_ragged_* metrics.
    Dead tiles copy their predecessor's (row, q_start) with q_len 0, so
    their clamped KV walk repeats the predecessor's physical indices and
    Pallas skips the DMA (see ops/paged_attention._ragged_live_range).

    The plan's POSITIONAL half is only authoritative where the host
    position model is exact. For decode/verify rows in the mixed
    scheduler launch the serving path marks the tiles/slots with
    build_device_meta and the program substitutes state.pos on device
    (apply_device_meta) — the start values planned here become
    placeholders there, which is what lets verify rows launch
    back-to-back without waiting for their fetch (ISSUE 15).
    """
    import numpy as np

    if width % tile != 0:
        raise ValueError(f"ragged width {width} must be a multiple of the "
                         f"query tile {tile}")
    G = width // tile
    meta = np.zeros((G, 4), np.int32)
    tok_row = np.full((width,), -1, np.int32)
    tok_pos = np.zeros((width,), np.int32)
    offsets = []
    stats = {"tiles": G, "pad_tiles": 0, "prefill_rows": 0, "decode_rows": 0}
    g = 0
    for row, start, length, kind in entries:
        if length < 1:
            raise ValueError("ragged launch entries need length >= 1")
        need = -(-length // tile)
        if g + need > G:
            raise ValueError(
                f"launch overflow: {length} tokens need {need} tiles, "
                f"{G - g} left of {G}"
            )
        offsets.append(g * tile)
        stats["decode_rows" if kind == RAGGED_DECODE else "prefill_rows"] += 1
        for t in range(need):
            q_len = min(tile, length - t * tile)
            q_start = start + t * tile
            meta[g] = (row, q_start, q_len, kind)
            w = g * tile
            tok_row[w : w + q_len] = row
            tok_pos[w : w + q_len] = q_start + np.arange(q_len)
            g += 1
    # launch padding: dead tiles inherit the predecessor's placement so
    # the kernel's clamped index repeats (DMA skipped), q_len 0 gates the
    # compute off
    stats["pad_tiles"] = G - g
    while g < G:
        if g > 0:
            meta[g] = meta[g - 1]
            meta[g, 2] = 0
        g += 1
    return meta, tok_row, tok_pos, offsets, stats


def _ragged_attend_xla(cfg, q, cache_k, cache_v, table, tok_row, tok_pos,
                       window_flag):
    """XLA twin of the ragged kernel: per-token gather of the owning
    row's blocks into a contiguous logical view, then the stock masked
    attention. This is the CPU / debug reference (the kernel's interpret
    mode is the bit-exactness oracle); on TPU the kernel path avoids
    materializing the W x MB*bs view entirely. q [W, 1, H, Dh]."""
    from ..models.llama import kernel_window

    W = q.shape[0]
    KV, bs = cache_k.shape[1], cache_k.shape[2]
    MB = table.shape[1]
    Dh = cache_k.shape[-1]
    S = MB * bs
    w, wd = kernel_window(cfg, window_flag)

    def win_mask(mask, kv_pos, q_pos):
        if wd is not None:
            mask &= (wd <= 0) | (kv_pos > q_pos - wd)
        elif w is not None:
            mask &= kv_pos > q_pos - w
        return mask

    if table.shape[0] == 1:
        # Single fleet row (the admission launch shape): gather the row's
        # logical view ONCE and attend the whole flat token axis as one
        # [1, W, S] batch — the same attention shape the bucketed scratch
        # prefill runs, with none of its gather/scatter bookends.
        def gathered1(leaf):
            g = (
                kv_dequantize(KVQuant(leaf.q[table[0]], leaf.s[table[0]]))
                if isinstance(leaf, KVQuant) else leaf[table[0]]
            )  # [MB, KV, bs, Dh]
            return g.transpose(1, 0, 2, 3).reshape(1, KV, S, Dh)

        kv_pos = jnp.arange(S, dtype=jnp.int32)[None, :]
        q_pos = tok_pos[:, None]
        mask = (kv_pos <= q_pos) & (tok_row >= 0)[:, None]  # [W, S]
        mask = win_mask(mask, kv_pos, q_pos)
        out = attend(
            q[:, 0][None], gathered1(cache_k), gathered1(cache_v),
            mask[None], scale=cfg.query_scale, softcap=cfg.attn_softcap,
        )  # [1, W, H, Dh]
        return out[0][:, None]

    rows = jnp.maximum(tok_row, 0)
    row_table = table[rows]  # [W, MB]

    def gathered(leaf):
        g = (
            kv_dequantize(KVQuant(leaf.q[row_table], leaf.s[row_table]))
            if isinstance(leaf, KVQuant) else leaf[row_table]
        )  # [W, MB, KV, bs, Dh]
        return g.transpose(0, 2, 1, 3, 4).reshape(W, KV, S, Dh)

    kv_pos = jnp.arange(S, dtype=jnp.int32)[None, None, :]
    q_pos = tok_pos[:, None, None]
    mask = (kv_pos <= q_pos) & (tok_row >= 0)[:, None, None]
    mask = win_mask(mask, kv_pos, q_pos)
    return attend(
        q, gathered(cache_k), gathered(cache_v), mask,
        scale=cfg.query_scale, softcap=cfg.attn_softcap,
    )


def make_ragged_fill_hook(table, meta, tok_row):
    """attn_hook for the ragged ingest programs: flat-token layout
    ([W, 1] chunks — each token is a batch row at its own position, the
    slots-mode contract), per-token K/V scatter into the owning row's
    pool block, attention over the pool via the ragged kernel
    (attn_impl="pallas") or its XLA gather twin.

    table [R, MB]: the launch's fleet rows' block tables; meta [G, 4]:
    the per-tile launch plan (build_ragged_meta); tok_row [W]: per-token
    owning row, -1 for launch padding — padding writes are redirected to
    the write-only TRASH block, exactly like ungated pp microsteps.
    """

    def hook(cfg, q, k, v, cache_k, cache_v, pos, mask, update_gate,
             valid_start, window_flag=None):
        del mask, valid_start  # mask derived from pos/tok_row in-kernel
        W, T = q.shape[0], q.shape[1]
        assert T == 1, "ragged fill runs the flat token layout (T=1 rows)"
        bs = cache_k.shape[2]
        MB = table.shape[1]
        # Write: token w's K/V -> pool[table[row_w, pos_w // bs], :,
        # pos_w % bs]. Launch padding (row -1) — and, on the pp ring,
        # microsteps whose stage doesn't own the buffer (update_gate) —
        # redirect to the trash block: colliding trash writes are
        # write-only garbage at positions nothing ever attends.
        rows_ix = jnp.maximum(tok_row, 0)
        lblk = jnp.minimum(pos // bs, MB - 1)  # [W]
        blk = table[rows_ix, lblk]  # [W]
        live = tok_row >= 0
        if update_gate is not None:
            live = live & update_gate
        blk = jnp.where(live, blk, TRASH_BLOCK)
        off = pos % bs
        if isinstance(cache_k, KVQuant):
            qk, sk = quantize_chunk(k)
            qv, sv = quantize_chunk(v)
            new_k = KVQuant(
                cache_k.q.at[blk, :, off, :].set(qk[:, 0]),
                cache_k.s.at[blk, :, off].set(sk[:, 0]),
            )
            new_v = KVQuant(
                cache_v.q.at[blk, :, off, :].set(qv[:, 0]),
                cache_v.s.at[blk, :, off].set(sv[:, 0]),
            )
        else:
            new_k = cache_k.at[blk, :, off, :].set(k[:, 0])
            new_v = cache_v.at[blk, :, off, :].set(v[:, 0])
        if cfg.attn_impl == "pallas":
            from ..models.llama import kernel_window
            from ..ops.paged_attention import ragged_paged_attend

            w, wd = kernel_window(cfg, window_flag)
            attn = ragged_paged_attend(
                q[:, 0], new_k, new_v, table, meta, wd, window=w,
                scale=cfg.query_scale, softcap=cfg.attn_softcap,
            )[:, None]
        else:
            attn = _ragged_attend_xla(
                cfg, q, new_k, new_v, table, tok_row, pos, window_flag
            )
        return attn, new_k, new_v

    return hook


def _token_pages(pages, tok_row):
    """Per-flat-token adapter pages from a per-row page vector: token w
    rides pages[tok_row[w]]; launch padding (row -1) rides the base page
    (0), whose delta is skipped anyway. None passes through — programs
    without a pages operand lower byte-identically to today's."""
    if pages is None:
        return None
    return jnp.where(
        tok_row >= 0, pages[jnp.maximum(tok_row, 0)], jnp.int32(0)
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def extend_ragged_paged(cfg: ModelConfig, params, tokens, tok_row, tok_pos,
                        meta, pool, table, pages=None):
    """One full ragged launch with no sampling — the chunked-prefill
    extend() twin over the pool. tokens [W] int32 flat launch tokens;
    tok_row/tok_pos [W]; meta [G, 4]; table [R, MB]. The pool is donated
    (updated in place); the table is read-only. pages: optional [R] i32
    per-table-row adapter pages — each flat token reads its owning row's
    page; launch padding (row -1) rides the base page."""
    from ..models import api as M

    x = M.embed(cfg, params, tokens[:, None], tok_pos)
    _, pool = M.forward_layers(
        cfg, params["layers"], x, pool, tok_pos,
        attn_hook=make_ragged_fill_hook(table, meta, tok_row),
        attn_seq_len=1, lora_pages=_token_pages(pages, tok_row),
    )
    return pool


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def prefill_ragged_paged(cfg: ModelConfig, params, tokens, tok_row, tok_pos,
                         meta, pool, table, sample_at, key, sampling,
                         presence=None, bias=None, pages=None):
    """Final ragged launch: run the tail chunk, unembed ONE flat position
    (`sample_at` — the entry's last valid token, traced so every tail
    length shares this compiled program) and sample the first token.
    Returns (first [1], logits [1, V], pool) — the G.prefill contract the
    admission wave's stacked fetch expects."""
    from ..models import api as M
    from ..ops.sampling import sample_token

    x = M.embed(cfg, params, tokens[:, None], tok_pos)
    x, pool = M.forward_layers(
        cfg, params["layers"], x, pool, tok_pos,
        attn_hook=make_ragged_fill_hook(table, meta, tok_row),
        attn_seq_len=1, lora_pages=_token_pages(pages, tok_row),
    )
    last = jax.lax.dynamic_slice_in_dim(x, sample_at, 1, axis=0)  # [1, 1, D]
    logits = M.unembed(cfg, params, last)[:, 0, :]
    first = sample_token(key, logits, *sampling, presence=presence, bias=bias)
    return first, logits, pool


@functools.partial(jax.jit, static_argnames=("cfg",))
def arm_slot_only(cfg: ModelConfig, state: G.SlotState,
                  sparams: G.SlotParams, slot, *arm):
    """Arm a slot with NO cache movement — the ragged ingest already wrote
    the prompt's K/V into the pool blocks, so admission needs only the
    state-side half of insert_slot_paged (same shared generate.arm_slot,
    so the budget / EOS-on-first semantics cannot drift)."""
    state, sparams = G.arm_slot(cfg, state, sparams, jnp.int32(slot), *arm)
    return state, sparams


# -- mixed launch: all decode rows + prefill chunks in ONE program ------------
#
# The chunked-prefill scheduler (engine/scheduler.py) stops prefilling an
# admission whole before it joins the decode fleet: each scheduler step is
# ONE launch of this program, carrying every active slot's decode token
# plus budget-sliced PREFILL chunks of pending admissions on the same flat
# token axis. Decode tokens/positions are gathered FROM THE SLOT STATE on
# device (the host never fetches to plan the next step — lag pipelining
# and the zero-host-sync launch invariant both survive), decode sampling
# is the shared generate.slot_step (cross-mode token parity is
# structural), and an admission whose FINAL chunk rides this launch
# samples its first token and arms its slot entirely on device
# (vectorized generate.arm_slot semantics) — the host learns the first
# token from the same packed fetch that carries the decode chunk.


class MixedArm(NamedTuple):
    """Per-slot arming operands for prefill chunks COMPLETING in a mixed
    launch (all [B]-shaped; rows with on=False are untouched). The
    sampling knobs ride a stacked SlotParams so the armed slot's decode
    sampling state is set in the same pass."""

    on: jnp.ndarray  # bool [B]: slot completes its prefill this launch
    idx: jnp.ndarray  # i32 [B]: flat index of its last prompt token
    prompt_len: jnp.ndarray  # i32 [B]
    max_tokens: jnp.ndarray  # i32 [B]
    params: G.SlotParams  # [B]-shaped sampling knobs
    presence: jnp.ndarray  # bool [B, V]: prompt token sets (host-built)


def idle_mixed_arm(n_slots: int, vocab_size: int) -> MixedArm:
    """An all-off MixedArm (no admission completes this launch)."""
    z = jnp.zeros((n_slots,), jnp.int32)
    _, sp = G.init_slots(n_slots, 1)
    return MixedArm(
        jnp.zeros((n_slots,), bool), z, z, z,
        sp, jnp.zeros((n_slots, vocab_size), bool),
    )


class SpecPlan(NamedTuple):
    """Per-slot speculation operands for one mixed launch (draft-then-
    verify inside the existing program — ISSUE 13). A speculating slot's
    launch entry is a [current + K-token draft] VERIFY row: a short
    prefill-kind row over the block table whose first flat slot is
    dec_flag-substituted from device state (token AND position, like any
    decode row) and whose draft slots carry host-planned (n-gram) or
    draft-model tokens. Shapes are fixed by the fleet's max draft length,
    so ONE compiled program serves every accept pattern and every
    per-slot draft length — the host only moves int32 plan data.

    With device-derived launch metadata (ISSUE 15; DeviceMeta below),
    a verify row's positions come from the device-resident slot state,
    so the host submits verify rows EVERY step, back to back — the
    packed fetch only confirms emissions. The PR-13 skip-until-fetched
    freeze (a slot with an unfetched verify row carries no row, host
    q_start stays exact) remains only behind
    EngineConfig.spec_device_meta=False as the bench baseline."""

    dec_on: jnp.ndarray  # bool [B]: slot has a PLAIN decode row this
    # launch — slot_step advances exactly these rows; verify rows
    # advance through spec_verify instead (and, in the legacy
    # host-planned mode, frozen unfetched-verify slots not at all)
    on: jnp.ndarray  # bool [B]: slot carries a verify row this launch
    idx: jnp.ndarray  # i32 [B, K+1]: flat launch indices of the row's
    # [current, draft...] slots (entries past the slot's own draft
    # length repeat the last valid index — duplicate gathers, never read)
    n_draft: jnp.ndarray  # i32 [B]: drafted tokens in the row (<= K)


def idle_spec_plan(n_slots: int, draft_len: int) -> SpecPlan:
    """An all-off SpecPlan with every slot marked as a plain decode row
    (the compiled shape for a fleet whose speculation is armed but idle
    this launch)."""
    return SpecPlan(
        jnp.ones((n_slots,), bool),
        jnp.zeros((n_slots,), bool),
        jnp.zeros((n_slots, draft_len + 1), jnp.int32),
        jnp.zeros((n_slots,), jnp.int32),
    )


class DeviceMeta(NamedTuple):
    """Device-derivation masks for one mixed launch (ISSUE 15): which
    tiles/flat slots of the host tile plan read their POSITIONS from the
    device-resident slot state instead of the host position model.

    The host still owns the STRUCTURAL half of the plan — which fleet
    row each tile serves, how many flat slots it spans, the launch
    width — because those are shapes/indices the program needs before
    dispatch. The POSITIONAL half (a decode/verify row's q_start and
    per-token write/RoPE positions) is data, and for decode and verify
    rows it is exactly `state.pos[row] (+ offset within the row)` — a
    value the device already holds post-previous-launch. Marking those
    tiles/slots here and substituting on device (apply_device_meta)
    means the host never needs the fetched result of launch N to plan
    launch N+1: verify rows ride lag pipelining like plain decode rows,
    and the SpecPlan.dec_on freeze is deleted. All leaves are plain
    traced operands — one compiled program for every derivation pattern.
    """

    tile_on: jnp.ndarray  # bool [G]: tile's q_start = pos[row] + tile_off
    tile_off: jnp.ndarray  # i32 [G]: tile's offset within its row entry
    tok_on: jnp.ndarray  # bool [W]: slot's position = pos[row] + tok_off
    tok_off: jnp.ndarray  # i32 [W]: flat slot's offset within its entry


def idle_device_meta(width: int, tile: int) -> DeviceMeta:
    """An all-off DeviceMeta (every position host-planned — the legacy
    contract, as a fixed-shape operand)."""
    G_ = width // tile
    return DeviceMeta(
        jnp.zeros((G_,), bool), jnp.zeros((G_,), jnp.int32),
        jnp.zeros((width,), bool), jnp.zeros((width,), jnp.int32),
    )


def build_device_meta(entries, offsets, n_dev: int, *, width: int,
                      tile: int):
    """HOST-side companion to build_ragged_meta (strictly decode-
    unreachable, same derivation): mark the first `n_dev` entries'
    tiles and flat slots for on-device position substitution. `entries`
    / `offsets` are the SAME lists build_ragged_meta consumed/returned —
    the walk here only recomputes each tile's offset within its entry.
    Launch-padding tiles inherit their predecessor's flags exactly like
    build_ragged_meta copies its (row, q_start): a pad tile behind a
    derived tile must derive the SAME value so its clamped KV walk keeps
    repeating physical indices and Pallas keeps skipping the DMA.

    Returns numpy (tile_on [G] bool, tile_off [G] i32, tok_on [W] bool,
    tok_off [W] i32) — wrap in a DeviceMeta for the launch."""
    import numpy as np

    G = width // tile
    tile_on = np.zeros((G,), bool)
    tile_off = np.zeros((G,), np.int32)
    tok_on = np.zeros((width,), bool)
    tok_off = np.zeros((width,), np.int32)
    g = 0
    for i, ((row, start, length, kind), off) in enumerate(
        zip(entries, offsets)
    ):
        need = -(-length // tile)
        if i < n_dev:
            for t in range(need):
                tile_on[g + t] = True
                tile_off[g + t] = t * tile
            tok_on[off : off + length] = True
            tok_off[off : off + length] = np.arange(length, dtype=np.int32)
        g += need
    while g < G:
        if g > 0:
            tile_on[g] = tile_on[g - 1]
            tile_off[g] = tile_off[g - 1]
        g += 1
    return tile_on, tile_off, tok_on, tok_off


def apply_device_meta(meta, tok_row, tok_pos, dev: DeviceMeta, pos):
    """TRACED half of the device-derived launch metadata: substitute
    `pos[row] + offset` into the marked tiles' q_start column and the
    marked flat slots' positions. Runs inside the mixed program BEFORE
    the kernel/hook sees either array, so the scalar-prefetch metadata
    the ragged kernel's index maps read — and the write/RoPE positions
    of the XLA twin — are exact device values with zero host syncs.
    Unmarked tiles/slots (prefill chunks, launch padding) keep the host
    plan verbatim."""
    rows = jnp.maximum(meta[:, 0], 0)
    q_dev = pos[rows].astype(jnp.int32) + dev.tile_off
    meta = meta.at[:, 1].set(jnp.where(dev.tile_on, q_dev, meta[:, 1]))
    rix = jnp.maximum(tok_row, 0)
    p_dev = pos[rix].astype(jnp.int32) + dev.tok_off
    tok_pos = jnp.where(dev.tok_on, p_dev, tok_pos)
    return meta, tok_pos


def spec_verify(cfg: ModelConfig, state: G.SlotState, window, draft,
                n_draft, live):
    """Traced accept/reject for the mixed launch's verify rows — the
    whole speculation decision stays on device (zero host syncs; the
    host learns the outcome from the packed fetch it already does).

    window [B, K+1] i32: greedy argmax at the verify row's flat
    positions (position j's argmax is the model's next token after
    consuming [current, draft[:j]]); draft [B, K] i32: the drafted
    tokens; n_draft [B]: drafts actually planned per row; live [B]:
    rows carrying a verify row AND still active on device.

    Emits the longest draft prefix matching the model's own argmax plus
    the model's correction token, replicating generate.slot_step's
    greedy semantics token for token so the STATE after a verify step is
    bit-identical to having decoded the same tokens one-by-one:
    break-before-append EOS (the EOS step still advances pos by one,
    like the plain step that sampled it), remaining-budget clamp
    (can_emit requires remaining > 0; budget exhaustion deactivates
    without the extra EOS-step position bump), pad token on
    deactivation. Rejected draft positions' K/V is overwritten before it
    can ever be attended or shadow-captured — the pool-rewind invariant
    (ARCHITECTURE.md "Speculative decoding").

    Returns (state', spec_emit [B, K+1], spec_mask [B, K+1], adv [B] —
    the per-row position advance the host position model resyncs from).
    """
    pad = jnp.int32(cfg.pad_token_id)
    K1 = window.shape[1]
    K = K1 - 1
    j = jnp.arange(K1, dtype=jnp.int32)[None, :]
    jk = jnp.arange(K, dtype=jnp.int32)[None, :]
    match = (draft == window[:, :K]) & (jk < n_draft[:, None])
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    valid = j <= n_acc[:, None]  # candidate emission stream: accepted
    # drafts + the correction token (all of them the model's own argmax)
    cum_eos = (
        jnp.cumsum(G.stop_mask(cfg, window).astype(jnp.int32), axis=1) > 0
    )
    emit_pre = valid & ~cum_eos  # break BEFORE appending a stop token
    n_pre = jnp.sum(emit_pre.astype(jnp.int32), axis=1)
    room = state.remaining
    n_emit = jnp.where(live, jnp.minimum(n_pre, room), 0)
    # the EOS "step" only happens when plain decode would have reached
    # it: budget exhaustion first means no EOS step (and no extra pos)
    saw_eos = live & jnp.any(valid & cum_eos, axis=1) & (n_pre < room)
    emit_ok = emit_pre & (j < n_emit[:, None]) & live[:, None]
    spec_emit = jnp.where(emit_ok, window, pad)
    adv = n_emit + saw_eos.astype(jnp.int32)
    last = jnp.take_along_axis(
        window, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
    )[:, 0]
    new_token = jnp.where(saw_eos | (n_emit <= 0), pad, last)
    new_rem = state.remaining - n_emit
    new_active = live & ~saw_eos & (new_rem > 0)
    # presence marks every token plain decode would have SAMPLED (the
    # emitted stream + the final EOS); counts only the emitted ones —
    # the exact slot_step bookkeeping, batched over the window. Inert
    # for eligible rows (speculation requires the penalties disabled),
    # kept exact so the state merge has one discipline.
    mark = emit_ok | (saw_eos[:, None] & (j == n_emit[:, None]))
    vocab = jnp.arange(state.presence.shape[-1], dtype=jnp.int32)
    onehot = window[:, :, None] == vocab[None, None, :]  # [B, K+1, V]
    pres_add = jnp.any(onehot & mark[:, :, None], axis=1)
    cnt_add = jnp.sum(
        onehot & emit_ok[:, :, None], axis=1
    ).astype(jnp.int32)
    state = G.SlotState(
        token=jnp.where(live, new_token, state.token),
        pos=state.pos + jnp.where(live, adv, 0),
        active=jnp.where(live, new_active, state.active),
        remaining=jnp.where(live, new_rem, state.remaining),
        presence=state.presence | pres_add,
        counts=state.counts + cnt_add,
    )
    return state, spec_emit, emit_ok, adv


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("pool",))
def mixed_step_ragged(cfg: ModelConfig, params, tokens, tok_row, tok_pos,
                      dec_flag, meta, pool, table, state: G.SlotState,
                      sparams: G.SlotParams, key, dec_idx, arm: MixedArm,
                      spec: Optional[SpecPlan] = None, spec_toks=None,
                      dev: Optional[DeviceMeta] = None, pages=None):
    """One scheduler step: advance every active slot one decode token AND
    write the launch's prefill chunks into the pool, in one program.

    tokens/tok_pos [W]: host-planned flat launch (prefill chunk contents;
    decode positions hold placeholders). dec_flag [W]: True where the
    flat slot is a decode-row token — its token/position are REPLACED by
    the owning slot's device state (state.token / state.pos), so the host
    plans launches ahead of its fetches without ever syncing. meta [G,4] /
    tok_row [W]: the build_ragged_meta plan. With `dev` (DeviceMeta, the
    default serving mode) the decode/verify tiles' q_start and flat-slot
    positions are DERIVED ON DEVICE from state.pos (apply_device_meta) —
    the host plan carries placeholders there and the host never needs a
    fetch to plan the next launch, even for verify rows whose advance is
    data-dependent. Without `dev` the host position model must be exact
    (the PR-13 contract: over-advance on rows that went inactive since
    the last fetch is masked garbage, the frozen-row argument).
    dec_idx [B]: flat index of each slot's decode token (0 for slots
    without one — their sampled garbage is gated by state.active exactly
    like idle rows in decode_slots_paged). arm: completing-prefill
    operands (MixedArm; all-off most steps).

    pages ([B] i32, optional): per-slot adapter-pool pages (engine/
    adapters) — every flat token (decode, verify, prefill chunk alike)
    computes with its owning slot's adapter delta; page 0 = base. A
    TRACED operand like the table, so one compiled program serves any
    adapter mix across launches.

    spec (SpecPlan, optional): draft-then-verify rows for eligible
    decode slots — each is a [current + draft] prefill-kind row whose
    first flat slot is dec_flag-substituted like any decode row, whose
    accept/reject runs fully traced (spec_verify), and whose emissions
    extend the packed fetch. spec_toks ([B, K] i32, optional): device-
    generated draft-model proposals scattered into the flat token axis
    (n-gram drafts arrive host-planned in `tokens` instead — either way
    zero extra host syncs).

    Returns (packed int32 — [5, B] plain, [5 + 2*(K+1) + 1, B] with
    spec: emitted / emit_mask / active / firsts / armed [/ spec_emit /
    spec_mask / position advance], ONE fetch per step — state, sparams,
    pool)."""
    from ..models import api as M

    if dev is not None:
        meta, tok_pos = apply_device_meta(meta, tok_row, tok_pos, dev,
                                          state.pos)
    rows_ix = jnp.maximum(tok_row, 0)
    toks = jnp.where(dec_flag, state.token[rows_ix], tokens)
    if spec is not None and spec_toks is not None:
        # draft-model proposals: scatter each verify row's drafts into
        # its flat slots (rows without a verify row — and draft slots
        # past a row's own draft length — target an out-of-range index,
        # which the scatter drops)
        K = spec_toks.shape[1]
        jk = jnp.arange(K, dtype=jnp.int32)[None, :]
        want = spec.on[:, None] & (jk < spec.n_draft[:, None])
        tgt = jnp.where(want, spec.idx[:, 1:], jnp.int32(toks.shape[0]))
        toks = toks.at[tgt.reshape(-1)].set(
            spec_toks.reshape(-1), mode="drop"
        )
    pos = jnp.where(dec_flag, state.pos[rows_ix], tok_pos)
    x = M.embed(cfg, params, toks[:, None], pos)
    x, pool = M.forward_layers(
        cfg, params["layers"], x, pool, pos,
        attn_hook=make_ragged_fill_hook(table, meta, tok_row),
        attn_seq_len=1, lora_pages=_token_pages(pages, tok_row),
    )
    # decode: gather each slot's flat position, one shared slot_step —
    # the same sampler/bookkeeping the whole-chunk decode programs run
    logits = M.unembed(cfg, params, x[dec_idx])[:, 0, :]  # [B, V]
    # completing prefills: sample each one's FIRST token off its last
    # prompt position with its own (stacked) sampling knobs, then arm the
    # slot in place — vectorized generate.arm_slot (budget / EOS-on-first
    # decided on device, same as insert_slot)
    pf_logits = M.unembed(cfg, params, x[arm.idx])[:, 0, :]  # [B, V]
    sp_logits = sp_draft = None
    if spec is not None:
        B, K1 = spec.idx.shape
        sel = x[spec.idx.reshape(-1)]  # [B*(K+1), 1, D]
        sp_logits = M.unembed(cfg, params, sel)[:, 0, :].reshape(B, K1, -1)
        sp_draft = toks[spec.idx[:, 1:]]  # [B, K] the verified drafts
    packed, state, sparams = mixed_epilogue(
        cfg, state, sparams, logits, pf_logits, key, arm,
        spec=spec, sp_logits=sp_logits, sp_draft=sp_draft,
    )
    return packed, state, sparams, pool


@functools.partial(
    jax.jit, static_argnames=("dcfg",), donate_argnames=("dpool",)
)
def mixed_fill_draft(dcfg: ModelConfig, dparams, tokens, tok_row, tok_pos,
                     dec_flag, meta, dpool, table, token, pos_state,
                     dev: Optional[DeviceMeta] = None):
    """Draft-pool twin of the mixed step's forward (no sampling): land
    this step's prefill chunks AND every decode row's current token in
    the DRAFT model's pool, with the same dec_flag substitution from the
    (replicated) slot state — so the draft chain's context tracks the
    canonical stream position by position. draft slots of verify rows
    carry placeholder zeros here; the propose chain rewrites exactly
    those positions before anything attends them (write-then-attend).
    `dev` rides the same apply_device_meta substitution as the target's
    mixed step, so the draft pool's positions track the device frontier
    under back-to-back verify rows too."""
    from ..models import api as M

    if dev is not None:
        meta, tok_pos = apply_device_meta(meta, tok_row, tok_pos, dev,
                                          pos_state)
    rows_ix = jnp.maximum(tok_row, 0)
    toks = jnp.where(dec_flag, token[rows_ix], tokens)
    pos = jnp.where(dec_flag, pos_state[rows_ix], tok_pos)
    x = M.embed(dcfg, dparams, toks[:, None], pos)
    _, dpool = M.forward_layers(
        dcfg, dparams["layers"], x, dpool, pos,
        attn_hook=make_ragged_fill_hook(table, meta, tok_row),
        attn_seq_len=1,
    )
    return dpool


@functools.partial(
    jax.jit, static_argnames=("dcfg", "draft_len"), donate_argnames=("dpool",)
)
def draft_propose_paged(dcfg: ModelConfig, dparams, token, pos, dpool,
                        table, *, draft_len: int):
    """Batched greedy draft chain over the fleet (the cfg-gated
    spec_draft_model flavor): `draft_len`+1 decode steps of the SMALL
    draft model from every slot's current (token, pos), over the draft
    model's own pool leaves indexed by the SAME block tables as the
    target pool — draft KV shares the target's allocation lifecycle for
    free. The +1 step writes the last proposal's K/V (draft_spec_loop's
    hole-free-full-accept discipline); its proposal is discarded.

    Rows not speculating this launch ride along: their chain writes
    their current token's K/V (canonical for the draft pool) plus
    proposal K/V beyond the frontier that later canonical writes
    overwrite — the same stale-region argument as the target pool, and
    in the draft pool even a violation could only degrade draft QUALITY
    (acceptance is verified against the target's own argmax).

    Returns (proposals [B, draft_len] i32, dpool)."""

    def body(carry, _):
        tok, p, dpool = carry
        logits, dpool = _forward_step_paged(
            dcfg, dparams, tok[:, None], dpool, table, p
        )
        nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(
            jnp.int32
        )
        return (nxt, p + 1, dpool), nxt

    (_, _, dpool), props = jax.lax.scan(
        body, (token, pos, dpool), None, length=draft_len + 1
    )
    return props[:draft_len].swapaxes(0, 1), dpool


def mixed_epilogue(cfg: ModelConfig, state: G.SlotState,
                   sparams: G.SlotParams, logits, pf_logits, key,
                   arm: MixedArm, spec: Optional[SpecPlan] = None,
                   sp_logits=None, sp_draft=None):
    """Sampling/arming tail of the mixed step, ONE copy for the single-
    device program above and the pp shard_map twin (parallel/pipeline.
    _build_mixed_step_ragged — both hand replicated [B, V] logits in):
    slot_step advances the decoding rows, completing prefills sample
    their first token and arm via the vectorized arm_slot recipe. With a
    SpecPlan, slot_step's advance is gated to the rows that actually
    carried a plain decode row (spec.dec_on), verify rows advance
    through the traced spec_verify instead, and the packed fetch grows
    the spec emission block. Returns (packed, state, sparams)."""
    from ..ops.sampling import sample_token

    k_dec, k_arm = jax.random.split(key)
    prev = state
    state, emit, can_emit = G.slot_step(cfg, state, sparams, logits, k_dec)
    if spec is not None:
        # rows without a plain decode row this launch (verify rows, and
        # rows skipped while their previous verify row is unfetched)
        # must not advance through slot_step's garbage logits: freeze
        # them back to the pre-step state, then run the traced verify
        dec_col = spec.dec_on[:, None]
        state = G.SlotState(*(
            jnp.where(dec_col if n.ndim > 1 else spec.dec_on, n, o)
            for n, o in zip(state, prev)
        ))
        emit = jnp.where(spec.dec_on, emit, jnp.int32(cfg.pad_token_id))
        can_emit = can_emit & spec.dec_on
        # greedy argmax over the verify row's positions — the identical
        # argmax sample_token's all-greedy bypass computes (speculation
        # eligibility requires the penalties disabled, so the penalized
        # and raw logits coincide bitwise)
        window = jnp.argmax(
            sp_logits.astype(jnp.float32), axis=-1
        ).astype(jnp.int32)
        live = spec.on & prev.active
        state, spec_emit, spec_mask, spec_adv = spec_verify(
            cfg, state, window, sp_draft, spec.n_draft, live
        )
    firsts = sample_token(
        k_arm, pf_logits,
        arm.params.temperature[:, None], arm.params.top_k[:, None],
        arm.params.top_p[:, None], arm.params.greedy | ~arm.on,
        arm.params.min_p[:, None], arm.params.rep_penalty[:, None],
        arm.params.freq_penalty[:, None], arm.params.pres_penalty[:, None],
        presence=arm.presence,
    )
    budget = jnp.where(
        G.stop_mask(cfg, firsts), jnp.int32(0),
        jnp.maximum(arm.max_tokens - 1, 0),
    )
    vocab = jnp.arange(cfg.vocab_size, dtype=jnp.int32)
    first_onehot = vocab[None, :] == firsts[:, None]  # [B, V]
    on, on_col = arm.on, arm.on[:, None]
    state = G.SlotState(
        token=jnp.where(on, firsts, state.token),
        pos=jnp.where(on, arm.prompt_len, state.pos),
        active=jnp.where(on, budget > 0, state.active),
        remaining=jnp.where(on, budget, state.remaining),
        presence=jnp.where(on_col, arm.presence | first_onehot,
                           state.presence),
        counts=jnp.where(on_col, first_onehot.astype(jnp.int32),
                         state.counts),
    )
    sparams = G.SlotParams(*(
        jnp.where(on, new, old)
        for new, old in zip(arm.params, sparams)
    ))
    rows = [
        emit[None], can_emit.astype(jnp.int32)[None],
        state.active.astype(jnp.int32)[None], firsts[None],
        on.astype(jnp.int32)[None],
    ]
    if spec is not None:
        # verify-row results ride the SAME packed fetch: emissions,
        # their mask, and the per-row position advance the host position
        # model resyncs from — zero extra device->host round trips
        rows += [
            spec_emit.T, spec_mask.astype(jnp.int32).T, spec_adv[None],
        ]
    packed = jnp.concatenate(rows, axis=0)
    return packed, state, sparams
