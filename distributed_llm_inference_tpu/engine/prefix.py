"""Prefix KV cache: skip re-prefilling shared prompt prefixes.

Chat traffic re-sends the same system prompt + conversation history with
every request; the reference re-embeds and re-runs ALL of it through every
stage per token (/root/reference/orchestration.py:109-141). Our prefill
already makes that one compiled call — this store removes even that for
the shared part: after a prefill, the KV of a chunk-aligned prompt prefix
is snapshotted (an on-device slice); a later request whose prompt starts
with the same token prefix splices the snapshot back into the cache
(one donated dynamic_update_slice) and prefills only the tail from the
cached offset via the chunked-prefill machinery (engine/generate.extend /
prefill-at-pos). TTFT then scales with the NEW tokens, not the whole
prompt.

Causal correctness: KV at slot i depends only on tokens[:i+1], so the
first P slots of a snapshot are byte-valid for any prompt whose first P
tokens match the snapshot's. Lookup reuses the longest common token
prefix (floored to the chunk alignment), splicing only those slots — so
a snapshot whose own tail diverges still donates its shared head and no
stale slot is ever attended.

Store discipline: entries are device arrays [L, B=1, KV, P, Dh] (sharded
like the live cache on SPMD backends; int8 KVQuant leaves snapshot their
scales alongside — same seq axis), LRU-bounded by entry count; P is
rounded DOWN to a multiple of `chunk` so the slice/splice programs
compile once per (P, cache) shape. Only backends with the plain
{"k", "v"} cache layout participate (the context-parallel backend's
slot-tagged cache does not).
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp


# Both helpers are tree-mapped so every {"k", "v"} cache layout rides
# them: raw [L, B, KV, S, Dh] arrays AND int8 KVQuant leaves
# (ops/kv_quant.py), whose per-(token, head) scales [L, B, KV, S] share
# the same seq axis 3 — one slice/splice recipe covers both leaves.


@functools.partial(jax.jit, static_argnames=("p",))
# jaxlint: disable=donate-cache -- pure snapshot READ: the live cache must survive extraction (the engine keeps decoding on it)
def _extract(cache, p: int):
    return jax.tree.map(
        lambda x: jax.lax.slice_in_dim(x, 0, p, axis=3), cache
    )


@functools.partial(jax.jit, static_argnames=("p",), donate_argnames=("cache",))
def _splice(cache, entry, p: int):
    def spl(big, small):
        sl = jax.lax.slice_in_dim(small, 0, p, axis=3)
        return jax.lax.dynamic_update_slice(
            big, sl, (jnp.int32(0),) * big.ndim
        )

    return jax.tree.map(spl, cache, entry)


class PrefixCache:
    """LRU store of chunk-aligned prompt-prefix KV snapshots.

    registry (utils/metrics.MetricsRegistry, optional): hit/miss/eviction
    counters + an entry gauge, labeled by `scope` — the solo engine and
    the continuous engine own SEPARATE instances, and a scrape must tell
    them apart."""

    def __init__(self, max_entries: int, chunk: int, registry=None,
                 scope: str = "solo"):
        if max_entries < 1:
            raise ValueError("prefix cache needs max_entries >= 1")
        if chunk < 1:
            raise ValueError("prefix cache needs chunk >= 1")
        self.max_entries = int(max_entries)
        self.chunk = int(chunk)
        self._entries: "collections.OrderedDict[tuple, dict]" = collections.OrderedDict()
        # guards _entries + counters: lookup/mark/store run under the
        # engine lock, but stats() serves /stats//health from OTHER
        # threads (same reason the engine keeps a separate samples lock)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._m_hits = self._m_misses = self._m_evictions = None
        self._m_entries = None
        if registry is not None:
            self._m_hits = registry.counter(
                "dli_prefix_cache_hits_total",
                "prefix-cache hits (tail actually planned and spliced)",
                ("scope",),
            ).labels(scope=scope)
            self._m_misses = registry.counter(
                "dli_prefix_cache_misses_total", "prefix-cache misses",
                ("scope",),
            ).labels(scope=scope)
            self._m_evictions = registry.counter(
                "dli_prefix_cache_evictions_total",
                "prefix snapshots evicted by the LRU bound", ("scope",),
            ).labels(scope=scope)
            self._m_entries = registry.gauge(
                "dli_prefix_cache_entries", "resident prefix snapshots",
                ("scope",),
            ).labels(scope=scope)

    @staticmethod
    def compatible(cache) -> bool:
        """Only plain {k, v} cache layouts can snapshot/splice."""
        return isinstance(cache, dict) and set(cache) == {"k", "v"}

    def lookup(self, ids: list) -> tuple[int, Optional[dict], Optional[tuple]]:
        """(P, entry, key) for the deepest reusable snapshot; (0, None,
        None) on miss. Pure — no counters or LRU promotion; the engine
        calls mark() once it knows whether the reuse actually planned
        (a hit that falls back to cold must not count as a hit).

        Reuse depth = the longest common TOKEN prefix between a stored
        snapshot's ids and the request, compared a CHUNK at a time (tuple
        slice equality, C speed — only the chunk-floored depth is usable
        anyway) and capped to leave at least one tail token to prefill —
        a snapshot whose own tail diverges still donates its shared head
        (slots < P are valid because the tokens match exactly).
        """
        ids_t = tuple(ids)
        cap = ((len(ids_t) - 1) // self.chunk) * self.chunk
        best_p, best_key, best = 0, None, None
        with self._lock:
            for key, entry in self._entries.items():
                limit = min(len(key), cap)
                p = 0
                while (
                    p < limit
                    and key[p : p + self.chunk] == ids_t[p : p + self.chunk]
                ):
                    p += self.chunk
                p = min(p, limit)
                if p > best_p:
                    best_p, best_key, best = p, key, entry
        if best is None or best_p < self.chunk:
            return 0, None, None
        return best_p, best, best_key

    def mark(self, key: Optional[tuple], hit: bool, depth: int = 0) -> None:
        """Record the request outcome; promotes the entry on a REAL hit
        (one whose tail actually planned and spliced). depth (the planned
        reuse offset, which bucket limits may have degraded below the
        lookup depth) is part of the planner protocol; snapshots don't
        account per-token, so it is unused here."""
        del depth
        with self._lock:
            if hit:
                self.hits += 1
                if key in self._entries:
                    self._entries.move_to_end(key)
            else:
                self.misses += 1
        m = self._m_hits if hit else self._m_misses
        if m is not None:
            m.inc()

    def splice(self, entry: dict, cache, p: int):
        """Write the snapshot's first `p` slots into slots [0, p) of the
        (donated) cache."""
        return _splice(cache, entry, p)

    def store(self, ids: list, prompt_len: int, cache) -> int:
        """Snapshot the chunk-aligned prefix of a just-prefilled prompt.
        Returns the stored length (0 if below one chunk / already stored)."""
        p = (prompt_len // self.chunk) * self.chunk
        if p < self.chunk:
            return 0
        key = tuple(ids[:p])
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return 0
        snapshot = _extract(cache, p)
        evicted = 0
        with self._lock:
            if key in self._entries:
                # two threads can race past the first key check and both
                # snapshot (the device _extract runs OUTSIDE the lock on
                # purpose); re-check under the insert lock and drop the
                # loser's snapshot instead of double-inserting — the
                # winner's entry keeps its LRU position and no eviction
                # is charged for a duplicate
                self._entries.move_to_end(key)
                return 0
            self._entries[key] = snapshot
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1
                evicted += 1
            n_entries = len(self._entries)
        if self._m_evictions is not None:
            if evicted:
                self._m_evictions.inc(evicted)
            self._m_entries.set(n_entries)
        return p

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "cached_tokens": sum(len(k) for k in self._entries),
            }
