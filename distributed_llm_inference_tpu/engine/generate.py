"""Single-device decode engine: prefill + early-exit decode loop.

TPU-native replacement for the reference's hot loop
(/root/reference/orchestration.py:109-196), which re-embeds and re-runs the
*full* sequence through every stage per token with no KV cache. Here:

  * **prefill** is one jit call over the (bucket-padded) prompt — this is
    the TTFT-critical path; right-padding is safe without extra masking
    because pad slots sit at positions > prompt_len-1, are never attended
    by valid queries (causal mask), and are overwritten by decode tokens
    before any valid query can reach them;
  * **decode** is one jit call: a `lax.while_loop` over steps with the KV
    cache threaded through (donated, so XLA updates it in place in HBM),
    the fused sampler inside the loop, and early exit when every row hits
    EOS — zero Python per token;
  * logits are only computed for the positions that get sampled (the
    reference runs lm_head over the whole sequence every step,
    orchestration.py:140-144).

Batch rows share one prompt length (serving uses batch=1; the batched bench
configs use equal-length prompts).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..models import api as M
from ..ops.sampling import sample_token


class SamplingParams(NamedTuple):
    """Traced sampling knobs (one compiled program serves all values)."""

    temperature: jnp.ndarray  # f32 scalar
    top_k: jnp.ndarray  # i32 scalar, <=0 disables
    top_p: jnp.ndarray  # f32 scalar, >=1 disables
    greedy: jnp.ndarray  # bool scalar


def default_sampling(temperature=0.7, top_k=50, top_p=0.9, greedy=False) -> SamplingParams:
    return SamplingParams(
        jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p), jnp.bool_(greedy)
    )


def _forward_step(cfg, params, tokens, cache, pos, valid_start=None):
    """One chunk through the stack; logits only at the final chunk position."""
    x = M.embed(cfg, params, tokens, pos)
    x, cache = M.forward_layers(
        cfg, params["layers"], x, cache, pos, valid_start=valid_start
    )
    logits = M.unembed(cfg, params, x[:, -1:, :])
    return logits[:, 0, :], cache


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill(
    cfg: ModelConfig, params, tokens, prompt_len, cache, key,
    sampling: SamplingParams, valid_start=None, pos=None,
):
    """Run the padded prompt (or final chunked-prefill chunk), sample the
    first token.

    tokens: [B, T_bucket] right-padded (or LEFT-padded for ragged batches,
    with valid_start [B] = each row's first real slot); prompt_len: scalar
    int32 — the number of valid tokens IN THIS CHUNK (shared by the batch;
    for left-padded batches this is the bucket length). pos: traced chunk
    offset into the cache (None == 0) — the chunked-prefill engine passes
    the running offset after its extend() calls, and because pos is traced
    the same compiled program serves every offset.
    Returns (first_token [B], logits [B,V], cache).
    """
    if pos is None:
        pos = jnp.int32(0)
    x = M.embed(cfg, params, tokens, pos)
    x, cache = M.forward_layers(
        cfg, params["layers"], x, cache, pos, valid_start=valid_start
    )
    # logits only at the last *valid* chunk position (traced start is fine
    # for dynamic_slice; prompt_len >= 1 by the engine's contract)
    last = jax.lax.dynamic_slice_in_dim(x, prompt_len - 1, 1, axis=1)  # [B,1,D]
    logits = M.unembed(cfg, params, last)[:, 0, :]
    first = sample_token(key, logits, *sampling)
    return first, logits, cache


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def extend(cfg: ModelConfig, params, tokens, pos, cache):
    """Chunked-prefill step: run a FULL chunk of prompt at offset `pos`
    into the cache, producing no logits/samples. The engine feeds prompts
    longer than the largest prefill bucket through repeated extend() calls
    before a final `prefill(..., pos=...)` chunk — compile cost stays one
    program per chunk shape, while supported prompt length grows to
    max_seq_len. (The reference caps everything at 30 output tokens and
    O(n²) recompute instead, /root/reference/orchestration.py:347.)"""
    x = M.embed(cfg, params, tokens, pos)
    _, cache = M.forward_layers(cfg, params["layers"], x, cache, pos)
    return cache


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_steps"), donate_argnames=("cache",)
)
def decode(
    cfg: ModelConfig,
    params,
    first_token,
    cache,
    start_pos,
    limit,
    key,
    sampling: SamplingParams,
    valid_start=None,
    *,
    max_steps: int,
):
    """Early-exit decode loop after prefill.

    first_token: [B] (already counted as generated token #0 unless EOS).
    start_pos: scalar int32 = prompt_len (first_token's K/V lands there).
    limit: traced cap on steps this call (clamped to the static max_steps),
    so one compiled program serves every requested max_tokens in the bucket.

    Returns (tokens [B, max_steps] — pad-masked after EOS, EOS excluded,
    matching the reference's break-before-append at orchestration.py:181-186
    — and n_gen [B] counting tokens emitted by THIS loop).
    """
    B = first_token.shape[0]
    # clamp: limit > max_steps would walk dynamic_update_slice off the end
    # of `out` (the start index clamps, corrupting the last column) and
    # inflate n_gen past the buffer
    limit = jnp.minimum(limit, jnp.int32(max_steps))
    pad = jnp.int32(cfg.pad_token_id)
    eos = jnp.int32(cfg.eos_token_id)
    out0 = jnp.full((B, max_steps), pad, jnp.int32)
    finished0 = first_token == eos

    def cond(c):
        step, _, _, _, _, finished, _, _ = c
        return (step < limit) & ~jnp.all(finished)

    def body(c):
        step, token, pos, cache, key, finished, out, n_gen = c
        logits, cache = _forward_step(
            cfg, params, token[:, None], cache, pos, valid_start
        )
        key, sub = jax.random.split(key)
        nxt = sample_token(sub, logits, *sampling)
        is_eos = nxt == eos
        newly_finished = finished | is_eos
        emit = jnp.where(newly_finished, pad, nxt)
        out = jax.lax.dynamic_update_slice(out, emit[:, None], (jnp.int32(0), step))
        n_gen = n_gen + (~newly_finished).astype(jnp.int32)
        token = jnp.where(newly_finished, pad, nxt)
        return step + 1, token, pos + 1, cache, key, newly_finished, out, n_gen

    init = (
        jnp.int32(0),
        jnp.where(finished0, pad, first_token),
        start_pos,
        cache,
        key,
        finished0,
        out0,
        jnp.zeros((B,), jnp.int32),
    )
    _, _, _, cache, _, _, out, n_gen = jax.lax.while_loop(cond, body, init)
    return out, n_gen, cache


def pick_bucket(buckets: tuple, n: int) -> int:
    """Smallest bucket >= n (compile-once-per-bucket shape discipline)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"length {n} exceeds largest bucket {buckets[-1]}")
