"""Single-device decode engine: prefill + early-exit decode loop.

TPU-native replacement for the reference's hot loop
(/root/reference/orchestration.py:109-196), which re-embeds and re-runs the
*full* sequence through every stage per token with no KV cache. Here:

  * **prefill** is one jit call over the (bucket-padded) prompt — this is
    the TTFT-critical path; right-padding is safe without extra masking
    because pad slots sit at positions > prompt_len-1, are never attended
    by valid queries (causal mask), and are overwritten by decode tokens
    before any valid query can reach them;
  * **decode** is one jit call: a `lax.while_loop` over steps with the KV
    cache threaded through (donated, so XLA updates it in place in HBM),
    the fused sampler inside the loop, and early exit when every row hits
    EOS — zero Python per token;
  * logits are only computed for the positions that get sampled (the
    reference runs lm_head over the whole sequence every step,
    orchestration.py:140-144).

Batch rows share one prompt length (serving uses batch=1; the batched bench
configs use equal-length prompts).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..models import api as M
from ..ops.sampling import sample_token


class SamplingParams(NamedTuple):
    """Traced sampling knobs (one compiled program serves all values).

    Field order matches ops/sampling.sample_token's positional tail, so
    `sample_token(key, logits, *sampling, presence)` is the universal call.
    min_p / rep_penalty are HF-parity extensions (MinPLogitsWarper /
    RepetitionPenaltyLogitsProcessor); their disabled values (0.0 / 1.0)
    reproduce the reference's exact stack.
    """

    temperature: jnp.ndarray  # f32 scalar
    top_k: jnp.ndarray  # i32 scalar, <=0 disables
    top_p: jnp.ndarray  # f32 scalar, >=1 disables
    greedy: jnp.ndarray  # bool scalar
    min_p: jnp.ndarray  # f32 scalar, <=0 disables
    rep_penalty: jnp.ndarray  # f32 scalar, 1.0 disables
    freq_penalty: jnp.ndarray  # f32 scalar, 0.0 disables (OpenAI)
    pres_penalty: jnp.ndarray  # f32 scalar, 0.0 disables (OpenAI)


def default_sampling(
    temperature=0.7, top_k=50, top_p=0.9, greedy=False, min_p=0.0,
    rep_penalty=1.0, freq_penalty=0.0, pres_penalty=0.0,
) -> SamplingParams:
    return SamplingParams(
        jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p),
        jnp.bool_(greedy), jnp.float32(min_p), jnp.float32(rep_penalty),
        jnp.float32(freq_penalty), jnp.float32(pres_penalty),
    )


def count_update(
    counts: jnp.ndarray, tokens: jnp.ndarray, active: jnp.ndarray = None
) -> jnp.ndarray:
    """Increment tokens [B]'s generated-count in counts [B, V] (OpenAI
    frequency/presence-penalty state). active [B]: rows whose emission
    really happened (finished rows keep forwarding pad; their counts are
    frozen so a later tenant of the row starts clean arithmetic)."""
    V = counts.shape[-1]
    hit = (
        jnp.arange(V, dtype=jnp.int32)[None, :] == tokens[:, None]
    ).astype(counts.dtype)
    if active is not None:
        hit = hit * active.astype(counts.dtype)[:, None]
    return counts + hit


def presence_update(presence: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mark tokens [B] as seen in presence [B, V] (repetition penalty
    state). One [B, V] compare-or per decode step — trivia next to the
    forward."""
    V = presence.shape[-1]
    hit = jnp.arange(V, dtype=jnp.int32)[None, :] == tokens[:, None]
    return presence | hit


def fsm_allowed(cmask: jnp.ndarray, fsm: jnp.ndarray) -> jnp.ndarray:
    """Allowed-token mask rows for the current FSM states: one gather
    ([S, V] table x [B] states -> [B, V]) inside the compiled loop — the
    grammar constraint's entire per-token mask cost (constrain/)."""
    return jnp.take(cmask, fsm, axis=0)


def fsm_advance(ctrans: jnp.ndarray, fsm: jnp.ndarray, tokens: jnp.ndarray,
                active: jnp.ndarray) -> jnp.ndarray:
    """Advance FSM states through the sampled tokens ([S, V] transition
    table gather); rows with active=False (finished / idle slots) keep
    their state frozen."""
    nxt = jnp.take_along_axis(
        jnp.take(ctrans, fsm, axis=0), tokens[:, None], axis=-1
    )[:, 0]
    return jnp.where(active, nxt, fsm)


def stop_mask(cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """True where a token is a stop token (eos OR any cfg.stop_token_ids,
    e.g. Gemma-it's <end_of_turn> — instruct checkpoints end their turn
    with it and rarely emit <eos> mid-chat). cfg is static under jit, so
    the comparisons unroll to a handful of fused equals."""
    m = tokens == jnp.int32(cfg.eos_token_id)
    for t in cfg.stop_token_ids:
        m = m | (tokens == jnp.int32(t))
    return m


def _forward_step(cfg, params, tokens, cache, pos, valid_start=None):
    """One chunk through the stack; logits only at the final chunk position."""
    x = M.embed(cfg, params, tokens, pos)
    x, cache = M.forward_layers(
        cfg, params["layers"], x, cache, pos, valid_start=valid_start
    )
    logits = M.unembed(cfg, params, x[:, -1:, :])
    return logits[:, 0, :], cache


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def prefill(
    cfg: ModelConfig, params, tokens, prompt_len, cache, key,
    sampling: SamplingParams, valid_start=None, pos=None, presence=None,
    bias=None,
):
    """Run the padded prompt (or final chunked-prefill chunk), sample the
    first token.

    tokens: [B, T_bucket] right-padded (or LEFT-padded for ragged batches,
    with valid_start [B] = each row's first real slot); prompt_len: scalar
    int32 — the number of valid tokens IN THIS CHUNK (shared by the batch;
    for left-padded batches this is the bucket length). pos: traced chunk
    offset into the cache (None == 0) — the chunked-prefill engine passes
    the running offset after its extend() calls, and because pos is traced
    the same compiled program serves every offset.
    Returns (first_token [B], logits [B,V], cache).
    """
    if pos is None:
        pos = jnp.int32(0)
    x = M.embed(cfg, params, tokens, pos)
    x, cache = M.forward_layers(
        cfg, params["layers"], x, cache, pos, valid_start=valid_start
    )
    # logits only at the last *valid* chunk position (traced start is fine
    # for dynamic_slice; prompt_len >= 1 by the engine's contract)
    last = jax.lax.dynamic_slice_in_dim(x, prompt_len - 1, 1, axis=1)  # [B,1,D]
    logits = M.unembed(cfg, params, last)[:, 0, :]
    # presence [B, V]: the prompt's token-id set (host-built from the FULL
    # id list, so chunked prefill and prefix-cache hits see every token) —
    # feeds the HF-parity repetition penalty; None = penalty off
    # bias [V] or [B, V]: OpenAI logit_bias added to raw logits (None = off)
    first = sample_token(key, logits, *sampling, presence=presence, bias=bias)
    return first, logits, cache


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def extend(cfg: ModelConfig, params, tokens, pos, cache):
    """Chunked-prefill step: run a FULL chunk of prompt at offset `pos`
    into the cache, producing no logits/samples. The engine feeds prompts
    longer than the largest prefill bucket through repeated extend() calls
    before a final `prefill(..., pos=...)` chunk — compile cost stays one
    program per chunk shape, while supported prompt length grows to
    max_seq_len. (The reference caps everything at 30 output tokens and
    O(n²) recompute instead, /root/reference/orchestration.py:347.)"""
    x = M.embed(cfg, params, tokens, pos)
    _, cache = M.forward_layers(cfg, params["layers"], x, cache, pos)
    return cache


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_steps", "with_logprobs"),
    donate_argnames=("cache",),
)
def decode(
    cfg: ModelConfig,
    params,
    first_token,
    cache,
    start_pos,
    limit,
    key,
    sampling: SamplingParams,
    valid_start=None,
    presence=None,
    counts=None,
    bias=None,
    constraint=None,
    *,
    max_steps: int,
    with_logprobs: bool = False,
):
    """Early-exit decode loop after prefill.

    first_token: [B] (already counted as generated token #0 unless EOS).
    start_pos: scalar int32 = prompt_len (first_token's K/V lands there).
    limit: traced cap on steps this call (clamped to the static max_steps),
    so one compiled program serves every requested max_tokens in the bucket.

    Returns (tokens [B, max_steps] — pad-masked after EOS, EOS excluded,
    matching the reference's break-before-append at orchestration.py:181-186
    — and n_gen [B] counting tokens emitted by THIS loop). With
    with_logprobs=True a 4th output [B, max_steps] f32 carries each
    emitted token's log-probability under the RAW model distribution
    (log_softmax of the step logits — before temperature/filters, the
    OpenAI-logprobs convention).

    constraint: None, or (fsm0 [B] i32, cmask [S, V] bool, ctrans [S, V]
    i32) — grammar-constrained decoding (constrain/): each step masks the
    logits with cmask[fsm] and advances fsm = ctrans[fsm, token], both
    gathers inside the compiled loop (zero host work per token). The fsm
    carry exists ONLY in constrained traces, so unconstrained programs
    compile to byte-identical HLO.
    """
    B = first_token.shape[0]
    # clamp: limit > max_steps would walk dynamic_update_slice off the end
    # of `out` (the start index clamps, corrupting the last column) and
    # inflate n_gen past the buffer
    limit = jnp.minimum(limit, jnp.int32(max_steps))
    pad = jnp.int32(cfg.pad_token_id)
    out0 = jnp.full((B, max_steps), pad, jnp.int32)
    finished0 = stop_mask(cfg, first_token)
    # presence [B, V]: repetition-penalty state (prompt + emitted so far,
    # first_token marked by the caller); None = penalty off, carried as a
    # dummy so the loop structure stays static
    use_presence = presence is not None
    pres0 = presence if use_presence else jnp.zeros((B, 1), jnp.bool_)
    # counts [B, V] int32: OpenAI frequency/presence-penalty state over
    # GENERATED tokens only (first_token counted by the caller); None =
    # penalties off, carried as a dummy so the loop structure stays static
    use_counts = counts is not None
    cnt0 = counts if use_counts else jnp.zeros((B, 1), jnp.int32)

    lp0 = jnp.zeros((B, max_steps if with_logprobs else 1), jnp.float32)
    # constraint carry only exists in constrained traces (see docstring)
    use_fsm = constraint is not None
    if use_fsm:
        fsm0, cmask, ctrans = constraint

    def cond(c):
        step, _, _, _, _, finished, _, _, _, _, _ = c[:11]
        return (step < limit) & ~jnp.all(finished)

    def body(c):
        step, token, pos, cache, key, finished, out, n_gen, pres, cnt, lps = c[:11]
        fsm = c[11] if use_fsm else None
        logits, cache = _forward_step(
            cfg, params, token[:, None], cache, pos, valid_start
        )
        key, sub = jax.random.split(key)
        nxt = sample_token(
            sub, logits, *sampling, presence=pres if use_presence else None,
            counts=cnt if use_counts else None, bias=bias,
            allowed=fsm_allowed(cmask, fsm) if use_fsm else None,
        )
        if use_presence:
            pres = presence_update(pres, nxt)
        is_eos = stop_mask(cfg, nxt)
        newly_finished = finished | is_eos
        if use_counts:
            cnt = count_update(cnt, nxt, ~newly_finished)
        emit = jnp.where(newly_finished, pad, nxt)
        out = jax.lax.dynamic_update_slice(out, emit[:, None], (jnp.int32(0), step))
        if with_logprobs:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)
            lps = jax.lax.dynamic_update_slice(lps, tok_lp, (jnp.int32(0), step))
        n_gen = n_gen + (~newly_finished).astype(jnp.int32)
        token = jnp.where(newly_finished, pad, nxt)
        nc = (
            step + 1, token, pos + 1, cache, key, newly_finished, out, n_gen,
            pres, cnt, lps,
        )
        if use_fsm:
            nc = nc + (fsm_advance(ctrans, fsm, nxt, ~newly_finished),)
        return nc

    init = (
        jnp.int32(0),
        jnp.where(finished0, pad, first_token),
        start_pos,
        cache,
        key,
        finished0,
        out0,
        jnp.zeros((B,), jnp.int32),
        pres0,
        cnt0,
        lp0,
    )
    if use_fsm:
        init = init + (fsm0,)
    final = jax.lax.while_loop(cond, body, init)
    (_, _, _, cache, _, _, out, n_gen, _, _, lps) = final[:11]
    if with_logprobs:
        return out, n_gen, cache, lps
    return out, n_gen, cache


# -- continuous batching (slot decode) ---------------------------------------
#
# JetStream-style in-flight batching: a fixed fleet of B cache slots decodes
# in lock-step, and new requests join a FREE slot mid-flight (prefilled on a
# scratch cache, spliced in) instead of waiting for the whole batch to
# finish. Each slot row sits at its own sequence position, so the forward
# runs with a per-row `pos` vector (models/llama.forward_layers slots mode).
# The reference serves strictly one request at a time
# (/root/reference/orchestration.py:98,144); dispatch-time coalescing
# (serving/queue.py) batches a burst but still drains it to completion —
# this removes that head-of-line blocking.


class SlotParams(NamedTuple):
    """Per-slot sampling knobs, all [B]-shaped (broadcast row-wise through
    sample_token, so slots with different temperatures/top-k/top-p/greedy/
    min-p/repetition-penalty decode together in one program)."""

    temperature: jnp.ndarray  # f32 [B]
    top_k: jnp.ndarray  # i32 [B]
    top_p: jnp.ndarray  # f32 [B]
    greedy: jnp.ndarray  # bool [B]
    min_p: jnp.ndarray  # f32 [B]
    rep_penalty: jnp.ndarray  # f32 [B]
    freq_penalty: jnp.ndarray  # f32 [B] (OpenAI frequency_penalty)
    pres_penalty: jnp.ndarray  # f32 [B] (OpenAI presence_penalty)


class SlotState(NamedTuple):
    """Device-side per-slot decode state.

    token: last emitted token (its K/V not yet written); pad when inactive.
    pos: cache position where `token`'s K/V lands on the next forward —
         exactly plain decode's start_pos contract.
    active: slot is mid-generation.
    remaining: tokens this slot may still emit (admission sets
         max_tokens - 1: the prefill token was #0, like decode's limit).
    presence: [B, V] seen-token set per slot (repetition-penalty state:
         prompt + emitted; armed by insert_slot, updated every step).
    counts: [B, V] generated-token counts per slot (OpenAI frequency/
         presence-penalty state: emitted only, prompt excluded; armed by
         insert_slot with the first token, updated every step).
    """

    token: jnp.ndarray  # i32 [B]
    pos: jnp.ndarray  # i32 [B]
    active: jnp.ndarray  # bool [B]
    remaining: jnp.ndarray  # i32 [B]
    presence: jnp.ndarray  # bool [B, V]
    counts: jnp.ndarray  # i32 [B, V]


def init_slots(n_slots: int, vocab_size: int) -> tuple[SlotState, SlotParams]:
    z = jnp.zeros((n_slots,), jnp.int32)
    return (
        SlotState(
            z, z, jnp.zeros((n_slots,), bool), z,
            jnp.zeros((n_slots, vocab_size), bool),
            jnp.zeros((n_slots, vocab_size), jnp.int32),
        ),
        SlotParams(
            jnp.ones((n_slots,), jnp.float32),
            z,
            jnp.ones((n_slots,), jnp.float32),
            jnp.ones((n_slots,), bool),
            jnp.zeros((n_slots,), jnp.float32),
            jnp.ones((n_slots,), jnp.float32),
            jnp.zeros((n_slots,), jnp.float32),
            jnp.zeros((n_slots,), jnp.float32),
        ),
    )


# NOTE: only `cache` is donated in the slot programs. The host keeps live
# references into the returned SlotState across chunk launches (lag-1
# pipelining reads state.active from the PREVIOUS chunk after the next one
# has been launched) — donating state would invalidate those buffers. The
# state arrays are a few hundred bytes; the cache is the only buffer worth
# updating in place.
@functools.partial(
    jax.jit, static_argnames=("cfg", "num_steps"), donate_argnames=("cache",)
)
def decode_slots(
    cfg: ModelConfig,
    params,
    state: SlotState,
    cache,
    key,
    sparams: SlotParams,
    *,
    num_steps: int,
):
    """Advance every slot `num_steps` tokens (inactive slots ride along,
    masked). One compiled program per (n_slots, num_steps).

    Inactive rows still forward their pad token and write K/V at their
    (frozen) pos — garbage confined to their own cache row, overwritten
    before it can ever be attended (write-then-attend ordering inside the
    layer), exactly the padded-prefill argument. Gating them out would save
    nothing: the batch dimension is fixed.

    Returns (emitted [num_steps, B], emit_mask [num_steps, B] bool — True
    where a real token was emitted, the host's only token-vs-pad oracle —
    state, cache).
    """
    def body(carry, sub):
        state, cache = carry
        logits, cache = _forward_step(
            cfg, params, state.token[:, None], cache, state.pos
        )
        new, emit, can_emit = slot_step(cfg, state, sparams, logits, sub)
        return (new, cache), (emit, can_emit)

    subs = jax.random.split(key, num_steps)
    (state, cache), (emitted, emit_mask) = jax.lax.scan(
        body, (state, cache), subs
    )
    return emitted, emit_mask, state, cache


@functools.partial(
    jax.jit, static_argnames=("cfg", "num_steps"), donate_argnames=("cache",)
)
def decode_slots_constrained(
    cfg: ModelConfig,
    params,
    state: SlotState,
    cache,
    key,
    sparams: SlotParams,
    fsm,
    cmask,
    ctrans,
    *,
    num_steps: int,
):
    """decode_slots under the fleet constraint tables: identical chunk
    contract plus the fsm [B] carry chained device-side between chunks
    (admission/release set rows host-side; decode never syncs). The
    continuous engine launches this program only while >= 1 constrained
    slot is active — pure-unconstrained fleets dispatch the untouched
    decode_slots. Returns (emitted, emit_mask, state, cache, fsm)."""
    def body(carry, sub):
        state, cache, fsm = carry
        logits, cache = _forward_step(
            cfg, params, state.token[:, None], cache, state.pos
        )
        new, emit, can_emit, fsm = slot_step_constrained(
            cfg, state, sparams, logits, sub, fsm, cmask, ctrans
        )
        return (new, cache, fsm), (emit, can_emit)

    subs = jax.random.split(key, num_steps)
    (state, cache, fsm), (emitted, emit_mask) = jax.lax.scan(
        body, (state, cache, fsm), subs
    )
    return emitted, emit_mask, state, cache, fsm


def slot_step(cfg: ModelConfig, state: SlotState, sparams: SlotParams,
              logits, key, allowed=None):
    """ONE copy of the per-step slot sampling/bookkeeping — the single-chip
    decode_slots scan and the pipeline's shard_map slots program both call
    this, so the cross-backend token-parity guarantee can't drift.
    allowed [B, V]: optional grammar-constraint mask rows (the constrained
    slot programs gather them from the fleet table — slot_step_constrained).
    Returns (new_state, emit [B], can_emit [B])."""
    pad = jnp.int32(cfg.pad_token_id)
    nxt = sample_token(
        key,
        logits,
        sparams.temperature[:, None],
        sparams.top_k[:, None],
        sparams.top_p[:, None],
        # OR-ing idle rows into "greedy" keeps the all-greedy sampler
        # bypass live when a retired slot still carries a previous
        # sampled tenant's False flag — idle rows' tokens are masked
        # downstream, so their branch only matters for speed
        sparams.greedy | ~state.active,
        sparams.min_p[:, None],
        sparams.rep_penalty[:, None],
        sparams.freq_penalty[:, None],
        sparams.pres_penalty[:, None],
        presence=state.presence,
        counts=state.counts,
        allowed=allowed,
    )
    # break-before-append EOS semantics (orchestration.py:181-186)
    can_emit = state.active & ~stop_mask(cfg, nxt) & (state.remaining > 0)
    emit = jnp.where(can_emit, nxt, pad)
    new = SlotState(
        token=jnp.where(can_emit, nxt, pad),
        pos=state.pos + state.active.astype(jnp.int32),
        active=can_emit & (state.remaining > 1),
        remaining=state.remaining - can_emit.astype(jnp.int32),
        presence=presence_update(state.presence, nxt),
        counts=count_update(state.counts, nxt, can_emit),
    )
    return new, emit, can_emit


def slot_step_constrained(cfg: ModelConfig, state: SlotState,
                          sparams: SlotParams, logits, key, fsm, cmask,
                          ctrans):
    """slot_step under the FLEET constraint tables (constrain/fleet.py):
    fsm [B] indexes the combined table — row 0 is the free state, so
    unconstrained slots ride the same two gathers as a no-op. ONE copy for
    the single-chip and pp shard_map constrained slot programs.
    Returns (new_state, emit [B], can_emit [B], new_fsm [B])."""
    new, emit, can_emit = slot_step(
        cfg, state, sparams, logits, key, allowed=fsm_allowed(cmask, fsm)
    )
    # emit == the sampled token exactly where can_emit; frozen elsewhere
    return new, emit, can_emit, fsm_advance(ctrans, fsm, emit, can_emit)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def insert_slot(
    cfg: ModelConfig,
    cache,
    scratch,
    state: SlotState,
    sparams: SlotParams,
    slot,
    first_token,
    prompt_len,
    max_tokens,
    temperature,
    top_k,
    top_p,
    greedy,
    min_p,
    rep_penalty,
    freq_penalty,
    pres_penalty,
    presence_row,
):
    """Splice a freshly prefilled scratch cache (batch=1, same max_seq) into
    slot row `slot` and arm its state. The whole scratch row is copied —
    one compiled program for every prompt length; the copy is one
    HBM-contiguous row (~tens of MB, microseconds at HBM bandwidth) and
    stale high positions are never attended.

    The decode budget (max_tokens - 1: the prefill token is emitted token
    #0) and the EOS-on-first check are computed ON DEVICE, so admission
    never blocks on fetching the first token — the host batches those
    fetches across a whole admission wave (one round trip, not one per
    request; the tunnel RTT dominates the loop otherwise).
    """
    slot = jnp.int32(slot)

    def splice(big, small):
        start = (jnp.int32(0), slot) + (jnp.int32(0),) * (big.ndim - 2)
        return jax.lax.dynamic_update_slice(big, small, start)

    cache = jax.tree.map(splice, cache, scratch)
    state, sparams = arm_slot(
        cfg, state, sparams, slot, first_token, prompt_len, max_tokens,
        temperature, top_k, top_p, greedy, min_p, rep_penalty,
        freq_penalty, pres_penalty, presence_row,
    )
    return cache, state, sparams


def arm_slot(cfg, state, sparams, slot, first_token, prompt_len, max_tokens,
             temperature, top_k, top_p, greedy, min_p, rep_penalty,
             freq_penalty, pres_penalty, presence_row):
    """Arm slot row `slot`'s decode state + sampling knobs after its prompt
    K/V landed. ONE copy of the budget / EOS-on-first / presence arming —
    insert_slot (dense fleet) and engine/paged.insert_slot_paged (block
    pool) both call this, so the admission semantics can't drift."""
    budget = jnp.where(
        stop_mask(cfg, first_token), jnp.int32(0), jnp.maximum(max_tokens - 1, 0)
    )
    # presence_row [V]: the prompt's token-id set + the first token
    # (host-built) — the slot's repetition-penalty state
    presence_row = presence_row | (
        jnp.arange(state.presence.shape[-1], dtype=jnp.int32) == first_token
    )
    # counts_row [V]: the slot's OpenAI-penalty state starts at just the
    # first (generated) token — the prompt is excluded by OpenAI semantics
    counts_row = (
        jnp.arange(state.counts.shape[-1], dtype=jnp.int32) == first_token
    ).astype(jnp.int32)
    state = SlotState(
        token=state.token.at[slot].set(first_token),
        pos=state.pos.at[slot].set(prompt_len),
        active=state.active.at[slot].set(budget > 0),
        remaining=state.remaining.at[slot].set(budget),
        presence=state.presence.at[slot].set(presence_row),
        counts=state.counts.at[slot].set(counts_row),
    )
    sparams = SlotParams(
        temperature=sparams.temperature.at[slot].set(temperature),
        top_k=sparams.top_k.at[slot].set(top_k),
        top_p=sparams.top_p.at[slot].set(top_p),
        greedy=sparams.greedy.at[slot].set(greedy),
        min_p=sparams.min_p.at[slot].set(min_p),
        rep_penalty=sparams.rep_penalty.at[slot].set(rep_penalty),
        freq_penalty=sparams.freq_penalty.at[slot].set(freq_penalty),
        pres_penalty=sparams.pres_penalty.at[slot].set(pres_penalty),
    )
    return state, sparams


@jax.jit
def kill_slot(state: SlotState, slot):
    """Force-deactivate a slot (per-request deadline overrun)."""
    return state._replace(active=state.active.at[jnp.int32(slot)].set(False))


@jax.jit
def pack_chunk(emitted, emit_mask, active):
    """Pack one decode chunk's host-bound results into a single int32 array
    [2K+1, B] (emitted / mask / final active), so the per-chunk
    device->host cost is ONE transfer — on a tunneled backend each fetch
    pays the full RTT, which would otherwise triple the loop's overhead."""
    return jnp.concatenate(
        [
            emitted,
            emit_mask.astype(jnp.int32),
            active.astype(jnp.int32)[None, :],
        ],
        axis=0,
    )


def pick_bucket(buckets: tuple, n: int) -> int:
    """Smallest bucket >= n (compile-once-per-bucket shape discipline)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"length {n} exceeds largest bucket {buckets[-1]}")


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_steps", "draft_len"),
    donate_argnames=("cache",),
)
def decode_speculative(
    cfg: ModelConfig,
    params,
    first_token,
    cache,
    hist,
    hist_len,
    limit,
    *,
    max_steps: int,
    draft_len: int = 4,
):
    """Greedy decode with prompt-lookup (n-gram) self-speculation.

    Batch-1 decode is HBM-bound: a T=1+g forward streams the same weight
    bytes as T=1, so verifying g drafted tokens costs ~one normal step.
    Each iteration drafts the g tokens that followed the most recent
    earlier occurrence of the current 2-gram in the token history
    (prompt + generated so far), runs ONE forward over [current, draft],
    and accepts the longest prefix where the draft matches the model's
    own greedy argmax — plus the model's correction token. Every emitted
    token is the model's argmax given the accepted context: in fp32 this
    is BIT-IDENTICAL to plain greedy decode (equivalence-tested); in bf16
    the T=1+g verify matmuls can accumulate in a different order than
    T=1 steps, so numerical near-ties may resolve differently — same
    class of benign divergence as chunked vs tokenwise prefill. Useless
    drafts cost nothing but the already-paid forward; repetitive text
    (code, structured data, chat-with-quoting) accepts often and decodes
    several tokens per step (~2.2x measured on v5e for a fully-
    repetitive stream: 260 -> 574 tok/s, TinyLlama bf16).

    KV discipline: the forward writes K/V for [current, draft] at
    pos..pos+g. Accepted slots hold exactly the accepted tokens' K/V; the
    first rejected slot is overwritten by the NEXT iteration's forward
    (its input starts with the correction token at that position), and
    later stale slots sit beyond the query position until overwritten —
    the same never-attended argument as padded prefill. `hist` [1, H] is
    the token history buffer (prompt written in [0, hist_len)); H bounds
    prompt + generated + draft overshoot.

    Greedy only (B=1): speculation verifies argmax, not a sampled draw.
    Returns (out [1, max_steps], n_gen [1], cache).
    """

    def fwd(tokens_in, cache, pos):
        x = M.embed(cfg, params, tokens_in, pos)
        x, cache = M.forward_layers(cfg, params["layers"], x, cache, pos)
        return M.unembed(cfg, params, x), cache

    return spec_loop(
        cfg, fwd, first_token, cache, hist, hist_len, limit,
        max_steps=max_steps, draft_len=draft_len,
    )


def spec_loop(
    cfg: ModelConfig,
    fwd,
    first_token,
    cache,
    hist,
    hist_len,
    limit,
    *,
    max_steps: int,
    draft_len: int = 4,
):
    """Backend-agnostic prompt-lookup speculation loop (the whole
    algorithm behind `decode_speculative`). `fwd(tokens [1, 1+G], cache,
    pos) -> (logits [1, 1+G, V], cache)` abstracts the verify forward:
    single-device embed/layers/unembed, or the pipeline's ring microsteps
    inside a shard_map body (parallel/pipeline.PipelineBackend) — one
    implementation, so pp speculation is consistent with the single chip
    by construction. On a pipeline, one verify forward costs the same S
    microsteps as a single token, so g accepted tokens amortize the
    batch-1 ring bubble g-fold.
    """
    G = draft_len
    H = hist.shape[1]
    pad = jnp.int32(cfg.pad_token_id)
    # out gets G+1 extra columns of scratch: each iteration writes its full
    # (1+G)-token window at the emit offset; rejected tails are overwritten
    # by later iterations and the scratch margin is sliced off at the end
    out0 = jnp.full((1, max_steps + G + 1), pad, jnp.int32)
    limit = jnp.minimum(limit, jnp.int32(max_steps))
    finished0 = stop_mask(cfg, first_token[0]) | (limit <= 0)

    def hist_at(h, i):
        return jax.lax.dynamic_slice(
            h, (jnp.int32(0), jnp.maximum(i, 0)), (1, 1)
        )[0, 0]

    # Loop invariant: `cur` is the LAST EMITTED token (counted already; its
    # K/V not yet written), `pos` its sequence position, `hlen` = pos + 1 =
    # tokens of canonical history in `hist` — exactly plain decode's
    # contract, where first_token's K/V lands at start_pos on its first
    # forward.
    def cond(c):
        _, _, _, _, _, _, n_gen, finished = c
        return (n_gen < limit) & ~finished

    def body(c):
        cur, pos, hlen, hist, cache, out, n_gen, finished = c
        # --- draft: the G tokens that followed the most recent earlier
        # occurrence of the current 2-gram in the history
        c0 = hist_at(hist, hlen - 2)
        c1 = hist_at(hist, hlen - 1)
        w0 = hist[0, : H - 1]
        w1 = hist[0, 1:]
        idx = jnp.arange(H - 1, dtype=jnp.int32)
        # the match must be strictly earlier than the current bigram
        is_match = (w0 == c0) & (w1 == c1) & (idx + 2 < hlen)
        any_match = jnp.any(is_match)
        last_match = jnp.max(jnp.where(is_match, idx, -1))
        dstart = jnp.where(any_match, last_match + 2, jnp.int32(0))
        # junk drafts (no match / overrunning hlen) are harmless: a token
        # is only accepted when it EQUALS the model's argmax
        draft = jax.lax.dynamic_slice(hist, (jnp.int32(0), dstart), (1, G))[0]

        # --- one forward over [current, draft] at pos
        tokens_in = jnp.concatenate([cur[None], draft])[None, :]  # [1, 1+G]
        logits, cache = fwd(tokens_in, cache, pos)  # [1, 1+G, V]
        window = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)  # [1+G]

        # --- accept the matched draft prefix + the correction token
        match = draft == window[:G]
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
        j = jnp.arange(G + 1, dtype=jnp.int32)
        valid = j <= n_acc
        cum_eos = jnp.cumsum(stop_mask(cfg, window).astype(jnp.int32)) > 0
        emit_ok = valid & ~cum_eos  # break BEFORE appending EOS
        room = limit - n_gen
        n_emit = jnp.minimum(jnp.sum(emit_ok.astype(jnp.int32)), room)
        emit_ok = emit_ok & (j < n_emit)
        saw_eos = jnp.any(valid & cum_eos)

        out = jax.lax.dynamic_update_slice(
            out, jnp.where(emit_ok, window, pad)[None, :], (jnp.int32(0), n_gen)
        )
        # window[j] is the token at sequence position pos+1+j = hlen+j
        hist = jax.lax.dynamic_update_slice(
            hist, window[None, :], (jnp.int32(0), hlen)
        )
        cur2 = window[jnp.maximum(n_emit - 1, 0)]  # new last-emitted token
        finished2 = saw_eos | (n_emit <= 0)
        return (
            cur2,
            pos + n_emit,
            hlen + n_emit,
            hist,
            cache,
            out,
            n_gen + n_emit,
            finished2,
        )

    hist = jax.lax.dynamic_update_slice(
        hist, first_token[None, :], (jnp.int32(0), hist_len)
    )
    init = (
        first_token[0],
        hist_len,  # first_token's position == start_pos
        hist_len + 1,
        hist,
        cache,
        out0,
        jnp.int32(0),
        finished0,
    )
    _, _, _, _, cache, out, n_gen, _ = jax.lax.while_loop(cond, body, init)
    return out[:, :max_steps], n_gen[None], cache


# plain Python float, NOT jnp.float32(...): materializing a device scalar
# at module scope would force backend init on IMPORT (hangs `--help` when
# the TPU tunnel is wedged; observed live)
NEG_INF_F32 = -1e9


@functools.partial(
    jax.jit, static_argnames=("cfg", "top_n"), donate_argnames=("cache",)
)
def score_chunk(cfg: ModelConfig, params, tokens, pos, cache, *,
                top_n: int = 0):
    """Teacher-forced scoring of one chunk at offset `pos`: the
    log-probability of every within-chunk token given its prefix (the
    lm-eval / OpenAI echo+logprobs loglikelihood pattern — the reference
    can only sample, orchestration.py:168). The engine chains chunks
    through the KV cache, so sequences up to max_seq_len score with
    compile-once bucket shapes, exactly like chunked prefill.

    tokens [B, T_chunk] (right-padded only in the FINAL chunk). Returns
    (within_lp [B, T-1] — entry t is log p(tokens[t+1] | prefix),
     top_v [B, T-1, top_n], top_i int32 — per-position top-N of the same
     distributions (empty when top_n == 0),
     last_lp [B, V] — the LAST position's full distribution, which scores
     the next chunk's first token across the boundary,
     cache)."""
    logits, cache = M.forward(cfg, params, tokens, cache, pos)
    return score_post(logits, tokens, top_n) + (cache,)


def score_post(logits, tokens, top_n: int):
    """Shared scoring tail: [B, T, V] teacher-forced logits -> (within_lp,
    top_v, top_i, last_lp). One implementation for the single-device and
    pipeline backends (the pipeline computes the same replicated logits
    from vocab shards — parallel/vocab.unembed_sharded)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    within_lp = jnp.take_along_axis(
        lp[:, :-1, :], tgt[..., None], axis=-1
    )[..., 0]
    if top_n > 0:
        top_v, top_i = jax.lax.top_k(lp[:, :-1, :], top_n)
    else:
        B, Tm1 = within_lp.shape
        top_v = jnp.zeros((B, Tm1, 0), jnp.float32)
        top_i = jnp.zeros((B, Tm1, 0), jnp.int32)
    return within_lp, top_v, top_i, lp[:, -1, :]


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_steps", "num_beams", "early_stopping"),
    donate_argnames=("cache",),
)
def decode_beam(
    cfg: ModelConfig,
    params,
    logits0,
    cache,
    start_pos,
    limit,
    length_penalty,
    *,
    max_steps: int,
    num_beams: int,
    early_stopping: bool = False,
):
    """Deterministic beam search after a BATCHED prefill (HF
    `generate(num_beams=N, do_sample=False)` semantics — the reference
    only samples, /root/reference/orchestration.py:168; this is
    beyond-parity HF-generate completeness).

    logits0: [num_beams, V] prefill logits (identical rows — the engine
    tiles the prompt); cache: [L, num_beams, ...] prefilled (identical
    rows). The first expansion takes the top num_beams DISTINCT tokens of
    row 0; each later step expands every alive beam by the full vocab,
    keeps the top num_beams alive continuations (EOS candidates retire
    into a finished set scored sum_logprobs / len**length_penalty, HF
    BeamSearchScorer), and reorders the KV cache by parent beam with a
    batched gather. early_stopping=True stops once num_beams hypotheses
    finished; False keeps going while an alive beam could still beat the
    worst finished score (HF's is_done bound with best_sum_logprobs /
    cur_len**length_penalty).

    Returns (tokens [num_beams, max_steps] — the FINAL beams, best
    first, pad-masked after EOS (EOS excluded), n_gen [num_beams],
    scores [num_beams], cache).
    """
    return beam_loop(
        cfg,
        lambda last, cache, pos: _forward_step(cfg, params, last, cache, pos),
        logits0, cache, start_pos, limit, length_penalty,
        max_steps=max_steps, num_beams=num_beams, early_stopping=early_stopping,
    )


def beam_loop(
    cfg: ModelConfig,
    fwd,
    logits0,
    cache,
    start_pos,
    limit,
    length_penalty,
    *,
    max_steps: int,
    num_beams: int,
    early_stopping: bool = False,
):
    """Backend-agnostic beam-search loop (the whole algorithm behind
    `decode_beam`). `fwd(last [nb, 1], cache, pos) -> (logits [nb, V],
    cache)` abstracts the forward step: single-device `_forward_step`, or
    the pipeline ring microstep inside a shard_map body
    (parallel/pipeline.PipelineBackend._build_beam) — ONE implementation,
    so pp meshes are bit-consistent with the single chip by construction.
    """
    nb = num_beams
    V = logits0.shape[-1]
    pad = jnp.int32(cfg.pad_token_id)
    limit = jnp.minimum(limit, jnp.int32(max_steps))

    lp0 = jax.nn.log_softmax(logits0[0].astype(jnp.float32))  # [V]
    # mask stop tokens at the seed step like HF (a 1-token hypothesis from
    # the prompt's immediate EOS): still allow it as a finished candidate
    seed_scores, seed_tokens = jax.lax.top_k(lp0, nb)

    out0 = jnp.full((nb, max_steps), pad, jnp.int32)
    alive_out = out0.at[:, 0].set(seed_tokens)
    alive_scores = seed_scores  # sum of logprobs per alive beam
    alive_len = jnp.full((nb,), 1, jnp.int32)

    fin_out = out0
    fin_scores = jnp.full((nb,), NEG_INF_F32)
    fin_len = jnp.zeros((nb,), jnp.int32)

    # seed beams that ARE stop tokens retire immediately
    seed_stop = stop_mask(cfg, seed_tokens)
    pen1 = jnp.float32(1.0) ** length_penalty
    fin_scores = jnp.where(seed_stop, seed_scores / pen1, fin_scores)
    # finished hypotheses exclude the EOS token itself (reference
    # break-before-append, orchestration.py:181-186): length 0 text
    alive_scores = jnp.where(seed_stop, NEG_INF_F32, alive_scores)
    order = jnp.argsort(-fin_scores)
    fin_scores = fin_scores[order]
    fin_out = fin_out[order]
    fin_len = fin_len[order]

    def cond(c):
        (step, _, alive_scores, _, _, fin_scores, _, _, _) = c
        if early_stopping:
            more = jnp.any(fin_scores <= NEG_INF_F32 / 2)
        else:
            # an alive beam could still beat the worst finished hypothesis
            # (HF is_done: best alive sum_logprobs / cur_len**penalty)
            best_alive = jnp.max(alive_scores) / (
                jnp.maximum(step.astype(jnp.float32), 1.0) ** length_penalty
            )
            more = jnp.min(fin_scores) < best_alive
        return (step < limit) & more & jnp.any(alive_scores > NEG_INF_F32 / 2)

    def body(c):
        (step, alive_out, alive_scores, alive_len, cache, fin_scores,
         fin_out, fin_len, pos) = c
        last = jnp.take_along_axis(alive_out, (alive_len - 1)[:, None], axis=1)
        logits, cache = fwd(last, cache, pos)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))  # [nb, V]
        cand = alive_scores[:, None] + lp  # [nb, V]

        flat = cand.reshape(nb * V)
        # 2*nb candidates guarantee nb non-stop continuations survive
        top_scores, top_idx = jax.lax.top_k(flat, 2 * nb)
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)
        is_stop = stop_mask(cfg, token)

        # candidate sequences: parent's prefix + token (token NOT written
        # for finished hypotheses — EOS excluded from the text)
        cand_out = alive_out[parent]
        cand_len = alive_len[parent]
        write_col = jnp.clip(cand_len, 0, max_steps - 1)
        ext_out = jax.vmap(
            lambda row, col, t: row.at[col].set(t)
        )(cand_out, write_col, token)

        # finished pool: existing nb + new stop candidates, keep best nb
        new_fin_scores = jnp.where(
            is_stop,
            top_scores / (cand_len.astype(jnp.float32) ** length_penalty),
            NEG_INF_F32,
        )
        pool_scores = jnp.concatenate([fin_scores, new_fin_scores])
        pool_out = jnp.concatenate([fin_out, cand_out])
        pool_len = jnp.concatenate([fin_len, cand_len])
        keep = jnp.argsort(-pool_scores)[:nb]
        fin_scores, fin_out, fin_len = (
            pool_scores[keep], pool_out[keep], pool_len[keep]
        )

        # alive pool: best nb non-stop candidates
        alive_rank_score = jnp.where(is_stop, NEG_INF_F32, top_scores)
        keep_a = jnp.argsort(-alive_rank_score)[:nb]
        alive_scores = alive_rank_score[keep_a]
        alive_out = ext_out[keep_a]
        alive_len = cand_len[keep_a] + 1
        parents = parent[keep_a]
        # reorder every KV leaf by parent beam (batch axis 1)
        cache = jax.tree.map(
            lambda x: jnp.take(x, parents, axis=1), cache
        )
        return (step + 1, alive_out, alive_scores, alive_len, cache,
                fin_scores, fin_out, fin_len, pos + 1)

    init = (jnp.int32(1), alive_out, alive_scores, alive_len, cache,
            fin_scores, fin_out, fin_len, start_pos)
    (step, alive_out, alive_scores, alive_len, cache, fin_scores, fin_out,
     fin_len, _) = jax.lax.while_loop(cond, body, init)

    # merge: unfinished alive beams count as length-`alive_len` hypotheses
    # (budget exhausted, HF's final add of running beams)
    alive_final = alive_scores / (
        jnp.maximum(alive_len.astype(jnp.float32), 1.0) ** length_penalty
    )
    all_scores = jnp.concatenate([fin_scores, alive_final])
    all_out = jnp.concatenate([fin_out, alive_out])
    all_len = jnp.concatenate([fin_len, alive_len])
    best = jnp.argsort(-all_scores)[:nb]
    out = all_out[best]
    n_gen = all_len[best]
    # pad-mask beyond each hypothesis' length
    col = jnp.arange(max_steps, dtype=jnp.int32)[None, :]
    out = jnp.where(col < n_gen[:, None], out, pad)
    return out, n_gen, all_scores[best], cache


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "max_steps", "draft_len"),
    donate_argnames=("cache", "dcache"),
)
def decode_draft_speculative(
    cfg: ModelConfig,
    params,
    dcfg: ModelConfig,
    dparams,
    first_token,
    cache,
    dcache,
    start_pos,
    limit,
    *,
    max_steps: int,
    draft_len: int = 4,
):
    """Greedy decode verified against a separate (smaller) DRAFT model.

    Classic two-model speculative decoding, greedy-acceptance flavor:
    each iteration the draft model autoregressively proposes `draft_len`
    tokens (cheap — small model), the target runs ONE forward over
    [current, draft] (costing ~one normal HBM-bound step, same argument
    as `decode_speculative`), and the longest draft prefix matching the
    target's own argmax is emitted plus the target's correction token.
    Every emitted token is the target's argmax given the accepted
    context — exact vs plain greedy in fp32; bf16 near-ties may resolve
    differently (chunked-vs-tokenwise class of divergence). Unlike
    prompt-lookup (which only wins on self-repeating text), a competent
    draft model accelerates ARBITRARY text at the cost of holding its
    weights in HBM.

    KV discipline (both caches hold history < the last emitted token's
    position on loop entry — the prompt must be prefilled into BOTH):
      * draft: the proposal scan runs draft_len+1 steps from `cur`,
        writing draft K/V at pos..pos+G — one step more than it proposes,
        so a full-accept-plus-bonus iteration leaves no unwritten hole at
        pos+G for the next iteration to attend through.
      * target: the verify forward writes K/V for [cur, draft] at
        pos..pos+G. Rejected-slot staleness is overwritten before it is
        ever attended (same argument as decode_speculative).

    Greedy only, B=1. Returns (out [1, max_steps], n_gen [1], cache,
    dcache).
    """

    def fwd(tokens_in, cache, pos):
        x = M.embed(cfg, params, tokens_in, pos)
        x, cache = M.forward_layers(cfg, params["layers"], x, cache, pos)
        return M.unembed(cfg, params, x), cache

    def dfwd(tok_11, dc, p):
        x = M.embed(dcfg, dparams, tok_11, p)
        x, dc = M.forward_layers(dcfg, dparams["layers"], x, dc, p)
        return M.unembed(dcfg, dparams, x), dc

    return draft_spec_loop(
        cfg, fwd, dfwd, first_token, cache, dcache, start_pos, limit,
        max_steps=max_steps, draft_len=draft_len,
    )


def draft_spec_loop(
    cfg: ModelConfig,
    fwd,
    dfwd,
    first_token,
    cache,
    dcache,
    start_pos,
    limit,
    *,
    max_steps: int,
    draft_len: int = 4,
):
    """Backend-agnostic two-model speculation loop (the algorithm behind
    `decode_draft_speculative`). `fwd(tokens [1, 1+G], cache, pos)` is the
    TARGET verify forward; `dfwd(tok [1, 1], dcache, pos)` one DRAFT
    step. The pipeline backend supplies a ring-microstep target forward
    and a replicated draft (every device runs the small draft redundantly
    — cheaper than scattering it), so pp meshes serve draft speculation
    with the same acceptance semantics as the single chip."""
    G = draft_len
    pad = jnp.int32(cfg.pad_token_id)
    out0 = jnp.full((1, max_steps + G + 1), pad, jnp.int32)
    limit = jnp.minimum(limit, jnp.int32(max_steps))
    finished0 = stop_mask(cfg, first_token[0]) | (limit <= 0)

    def cond(c):
        _, _, _, _, _, n_gen, finished = c
        return (n_gen < limit) & ~finished

    def body(c):
        cur, pos, cache, dcache, out, n_gen, finished = c

        # --- draft chain: G+1 greedy steps from `cur` (the +1 writes
        # d_{G-1}'s K/V so a full accept leaves no cache hole; its
        # proposal is discarded)
        def dstep(carry, _):
            tok, p, dc = carry
            lg, dc = dfwd(tok[None, None], dc, p)
            nxt = jnp.argmax(lg[0, 0]).astype(jnp.int32)
            return (nxt, p + 1, dc), nxt

        (_, _, dcache), proposals = jax.lax.scan(
            dstep, (cur, pos, dcache), None, length=G + 1
        )
        draft = proposals[:G]

        # --- one target forward over [current, draft] at pos
        tokens_in = jnp.concatenate([cur[None], draft])[None, :]  # [1, 1+G]
        logits, cache = fwd(tokens_in, cache, pos)
        window = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)  # [1+G]

        # --- accept matched prefix + correction (identical emit logic to
        # decode_speculative)
        match = draft == window[:G]
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
        j = jnp.arange(G + 1, dtype=jnp.int32)
        valid = j <= n_acc
        cum_eos = jnp.cumsum(stop_mask(cfg, window).astype(jnp.int32)) > 0
        emit_ok = valid & ~cum_eos
        room = limit - n_gen
        n_emit = jnp.minimum(jnp.sum(emit_ok.astype(jnp.int32)), room)
        emit_ok = emit_ok & (j < n_emit)
        saw_eos = jnp.any(valid & cum_eos)

        out = jax.lax.dynamic_update_slice(
            out, jnp.where(emit_ok, window, pad)[None, :], (jnp.int32(0), n_gen)
        )
        cur2 = window[jnp.maximum(n_emit - 1, 0)]
        finished2 = saw_eos | (n_emit <= 0)
        return (cur2, pos + n_emit, cache, dcache, out, n_gen + n_emit,
                finished2)

    init = (first_token[0], start_pos, cache, dcache, out0, jnp.int32(0),
            finished0)
    _, _, cache, dcache, out, n_gen, _ = jax.lax.while_loop(cond, body, init)
    return out[:, :max_steps], n_gen[None], cache, dcache
