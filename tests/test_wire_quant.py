"""Quantized inter-stage transfers (ops/wire_quant.py +
EngineConfig.pp_wire_quant).

Four layers of coverage:

  * WireQuant primitive units — round-trip contracts, per-row scale
    isolation (an outlier token cannot poison its neighbors), and the
    shared-implementation guarantee with the KV cache's quantize_chunk;
  * collective semantics WITHOUT a mesh — `jax.vmap(axis_name=...)`
    carries ppermute/psum, so the off-path bit-identity contract
    (`wire_ppermute(quant=False)` IS `lax.ppermute`, `masked_psum`
    IS the masked-psum idiom) and the on-path round-trip numerics are
    asserted bitwise even on jax builds with no shard_map;
  * the CPU proxy (proxy_stage_generate/_match) — the pp ring's wire
    numerics replayed on one device: quant-off bit-identity with the
    single-device greedy path, and the greedy token-match-rate GATE
    (teacher-forced, per-decision — asserted, not eyeballed);
  * real-mesh tests (shard_map-gated like all pp tests): quant-off
    bit-identity with today's outputs on pp / 1F1B / sp / sp x pp,
    quant-on equality with the proxy's numerics twin, sp's
    wire==kv-quant prefill equivalence, and the chaos leg (crash + warm
    recovery mid-decode with the wire on stays bit-identical — the
    tolerance envelope's floor).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.ops import kv_quant as KQ
from distributed_llm_inference_tpu.ops import wire_quant as WQ

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)

# Greedy token-match-rate gate for the int8 wire on the tiny proxy
# config (4 layers, dim 64, RANDOM weights — near-flat logits, far
# harsher than any real checkpoint): teacher-forced per-decision
# agreement, calibrated on this config (observed S=2 mean 0.995 / min
# 0.958, S=4 mean 0.969 / min 0.875 over 8 prompts).
WIRE_MATCH_MEAN = 0.90
WIRE_MATCH_MIN = 0.80
_N_TOKENS = 20


# -- WireQuant primitive units ------------------------------------------------

def test_roundtrip_shape_dtype_contract():
    for dt in (jnp.float32, jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 16), dt)
        w = WQ.wire_encode(x)
        assert w.q.shape == x.shape and w.q.dtype == jnp.int8
        assert w.s.shape == x.shape[:-1] and w.s.dtype == jnp.float32
        back = WQ.wire_decode(w, x.dtype)
        assert back.shape == x.shape and back.dtype == dt
        # symmetric int8: quantization error bounded by half a step/row
        # (measured pre-cast — the bf16 restore adds its own rounding)
        err = jnp.abs(
            WQ.wire_decode(w, jnp.float32) - x.astype(jnp.float32)
        )
        assert float(jnp.max(err - 0.5 * w.s[..., None])) <= 1e-6


def test_outlier_token_keeps_own_scale():
    """Per-row scales: blowing up one token's row must not change any
    OTHER row's reconstruction by a single bit."""
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 32))
    spiked = x.at[0, 2].multiply(1e4)
    base = WQ.wire_roundtrip(x)
    spk = WQ.wire_roundtrip(spiked)
    for t in (0, 1, 3):
        np.testing.assert_array_equal(
            np.asarray(base[0, t]), np.asarray(spk[0, t])
        )
    # and the outlier row still reconstructs to its own magnitude
    assert float(jnp.max(jnp.abs(spk[0, 2]))) > 1e3


def test_zero_rows_stay_zero():
    x = jnp.zeros((2, 3, 8))
    w = WQ.wire_encode(x)
    assert float(jnp.max(jnp.abs(WQ.wire_decode(w, x.dtype)))) == 0.0


def test_kv_quant_shares_wire_impl():
    """quantize_chunk IS quantize_rows — cache and wire quantization
    cannot drift (the one-implementation satellite)."""
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 4, 16))
    q1, s1 = WQ.quantize_rows(x)
    q2, s2 = KQ.quantize_chunk(x)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))


def test_wirequant_is_pytree():
    w = WQ.wire_encode(jnp.ones((2, 4)))
    leaves = jax.tree.leaves(w)
    assert len(leaves) == 2
    w2 = jax.tree.map(lambda a: a, w)
    assert isinstance(w2, WQ.WireQuant)


def test_wire_bytes_formula():
    # f32 [1, 1, 64]: 256 bytes raw vs 64 int8 + 4 scale = 3.76x
    off = WQ.wire_bytes((1, 1, 64), 4, 1, quant=False)
    on = WQ.wire_bytes((1, 1, 64), 4, 1, quant=True)
    assert off == 256 and on == 68
    assert off / on >= 2.0
    assert WQ.wire_bytes((2, 3, 64), 4, 5, quant=False) == 2 * 3 * 64 * 4 * 5


# -- collective semantics under vmap (no shard_map needed) --------------------

_PERM4 = [(0, 1), (1, 2), (2, 3), (3, 0)]


def _ring(fn, x):
    return jax.vmap(fn, axis_name="r")(x)


def test_wire_ppermute_off_is_lax_ppermute():
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 2, 8))
    a = _ring(lambda y: WQ.wire_ppermute(y, "r", _PERM4, quant=False), x)
    b = _ring(lambda y: jax.lax.ppermute(y, "r", _PERM4), x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_wire_ppermute_on_is_roundtrip_then_permute():
    """The receiving stage sees exactly wire_roundtrip(sender's buffer)
    — the property the CPU proxy (and the mesh-equals-proxy test)
    stand on."""
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 2, 8))
    a = _ring(lambda y: WQ.wire_ppermute(y, "r", _PERM4, quant=True), x)
    b = _ring(
        lambda y: jax.lax.ppermute(WQ.wire_roundtrip(y), "r", _PERM4), x
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_masked_psum_off_is_masked_psum():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 1, 8))

    def off(y):
        sel = jax.lax.axis_index("r") == 0
        return WQ.masked_psum(y, sel, "r", quant=False)

    def ref(y):
        sel = jax.lax.axis_index("r") == 0
        return jax.lax.psum(jnp.where(sel, y, jnp.zeros((), y.dtype)), "r")

    np.testing.assert_array_equal(
        np.asarray(_ring(off, x)), np.asarray(_ring(ref, x))
    )


def test_masked_psum_on_broadcasts_owner_roundtrip():
    """Quantized masked broadcast: every participant lands exactly the
    owner's wire_roundtrip — one nonzero int8 contribution, no
    overflow, no cross-talk."""
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 1, 8))

    def on(y):
        sel = jax.lax.axis_index("r") == 0
        return WQ.masked_psum(y, sel, "r", quant=True)

    got = _ring(on, x)
    want = WQ.wire_roundtrip(x[0])
    for r in range(4):
        np.testing.assert_array_equal(np.asarray(got[r]), np.asarray(want))


# -- config validation + metrics ---------------------------------------------

def test_engine_config_validates_pp_wire_quant():
    with pytest.raises(ValueError, match="pp_wire_quant must be None or"):
        EngineConfig(pp_wire_quant="int4")
    with pytest.raises(ValueError, match="pp_wire_quant must be None or"):
        EngineConfig(pp_wire_quant="fp8")
    EngineConfig(pp_wire_quant="int8")
    EngineConfig(pp_wire_quant=None)


def test_error_shape_matches_kv_quant():
    """The satellite contract: unknown values reject with the same error
    shape as kv_quant's."""
    cfg = get_model_config("test-llama-tiny")
    with pytest.raises(ValueError, match="kv_quant must be None or 'int8'"):
        cfg.replace(kv_quant="int4")
    with pytest.raises(
        ValueError, match="pp_wire_quant must be None or 'int8'"
    ):
        EngineConfig(pp_wire_quant="int4")


def test_metrics_preregistered_and_gauge_off_on_single_device():
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine

    eng = InferenceEngine(
        get_model_config("test-llama-tiny"),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    assert eng.metrics.get("dli_pp_wire_bytes_total") is not None
    snap = eng.metrics.snapshot()
    series = snap["dli_pp_wire_quant"]["series"]
    assert len(series) == 1 and series[0]["value"] == 0.0


# -- the CPU proxy (runs everywhere) ------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = get_model_config("test-llama-tiny")
    return cfg, M.init_params(cfg, jax.random.PRNGKey(0))


def _proxy_prompt(seed, cfg, n=16):
    rng = np.random.default_rng(seed)
    return rng.integers(3, cfg.vocab_size, size=n).tolist()


def test_proxy_off_bit_identical_to_single_device(tiny):
    """quant=False stage-sliced proxy == the real single-device greedy
    path, token for token — so the proxy's quant-on delta isolates
    exactly the wire quantization."""
    cfg, params = tiny
    prompt = _proxy_prompt(0, cfg, 12)
    N = _N_TOKENS
    got = WQ.proxy_stage_generate(cfg, params, prompt, N, 4, quant=False)
    toks = jnp.asarray([prompt], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=64)
    sampling = G.default_sampling(greedy=True)
    first, _, cache = G.prefill(
        cfg, params, toks, jnp.int32(len(prompt)), cache,
        jax.random.PRNGKey(0), sampling,
    )
    out, _, _ = G.decode(
        cfg, params, first, cache, jnp.int32(len(prompt)), jnp.int32(N - 1),
        jax.random.PRNGKey(1), sampling, None, None, None, None, None,
        max_steps=N - 1,
    )
    ref = [int(first[0])] + [int(t) for t in np.asarray(out[0])[: N - 1]]
    assert got == ref


@pytest.mark.parametrize("stages", [2, 4])
def test_proxy_greedy_match_rate_gate(tiny, stages):
    """THE quality gate: teacher-forced greedy agreement of the
    wire-quantized forward, asserted against the documented tolerance
    (not eyeballed). Per-decision — one flip cannot cascade."""
    cfg, params = tiny
    rates = [
        WQ.proxy_stage_match(
            cfg, params, _proxy_prompt(seed, cfg), _N_TOKENS, stages
        )
        for seed in range(6)
    ]
    assert float(np.mean(rates)) >= WIRE_MATCH_MEAN, rates
    assert min(rates) >= WIRE_MATCH_MIN, rates


# -- real-mesh tests (shard_map-gated like all pp tests) ----------------------

def _pb(cfg, params, eight_devices, pp, **kw):
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    mesh = build_mesh(MeshConfig(dp=1, pp=pp, tp=1), eight_devices)
    return PipelineBackend(cfg, params, mesh, **kw)


def _greedy_seq(backend, prompt, n):
    toks = jnp.asarray([prompt], jnp.int32)
    sampling = G.default_sampling(greedy=True)
    cache = backend.init_cache(1, 64)
    first, _, cache = backend.prefill(
        toks, jnp.int32(len(prompt)), cache, jax.random.PRNGKey(0), sampling
    )
    out, _, _ = backend.decode(
        first, cache, jnp.int32(len(prompt)), jnp.int32(n - 1),
        jax.random.PRNGKey(1), sampling, max_steps=n - 1,
    )
    return [int(first[0])] + [int(t) for t in np.asarray(out[0])[: n - 1]]


@needs_shard_map
def test_pp_wire_off_bit_identical(tiny, eight_devices):
    """pp_wire_quant=None is bit-identical to today's outputs (and both
    are bit-identical to the single device — the pre-existing pp
    invariant catches an off-path that accidentally quantizes)."""
    cfg, params = tiny
    prompt = _proxy_prompt(0, cfg, 12)
    base = _greedy_seq(_pb(cfg, params, eight_devices, 2), prompt, 12)
    off = _greedy_seq(
        _pb(cfg, params, eight_devices, 2, wire_quant=None), prompt, 12
    )
    assert off == base
    solo = WQ.proxy_stage_generate(cfg, params, prompt, 12, 2, quant=False)
    assert base == solo


@needs_shard_map
def test_pp_wire_on_matches_proxy_numerics(tiny, eight_devices):
    """The numerics-twin contract: the pp=2 mesh with the int8 wire on
    emits EXACTLY the proxy's quantized sequence — every hand-off is one
    row-local wire_roundtrip, nothing else differs."""
    cfg, params = tiny
    pb = _pb(cfg, params, eight_devices, 2, wire_quant="int8")
    for seed in range(3):
        prompt = _proxy_prompt(seed, cfg, 12)
        mesh_seq = _greedy_seq(pb, prompt, 12)
        proxy_seq = WQ.proxy_stage_generate(
            cfg, params, prompt, 12, 2, quant=True
        )
        assert mesh_seq == proxy_seq, (seed, mesh_seq, proxy_seq)


@needs_shard_map
def test_pp_wire_on_match_rate_gate(tiny, eight_devices):
    """Per-decision gate on the real mesh: the FIRST sampled token of
    each prefill is one independent decision (no cascade) — agreement
    with the exact single-device first token must clear the documented
    floor."""
    cfg, params = tiny
    pb = _pb(cfg, params, eight_devices, 4, wire_quant="int8")
    sampling = G.default_sampling(greedy=True)
    hits = total = 0
    for seed in range(8):
        prompt = _proxy_prompt(seed, cfg, 12)
        toks = jnp.asarray([prompt], jnp.int32)
        cache = M.init_kv_cache(cfg, 1, max_seq=64)
        ref, _, _ = G.prefill(
            cfg, params, toks, jnp.int32(len(prompt)), cache,
            jax.random.PRNGKey(0), sampling,
        )
        cache_p = pb.init_cache(1, 64)
        got, _, _ = pb.prefill(
            toks, jnp.int32(len(prompt)), cache_p, jax.random.PRNGKey(0),
            sampling,
        )
        hits += int(int(got[0]) == int(ref[0]))
        total += 1
    assert hits / total >= WIRE_MATCH_MIN, (hits, total)


@needs_shard_map
@pytest.mark.slow
def test_1f1b_wire_off_and_on(tiny, eight_devices):
    """1F1B fleet decode: wire off is bit-identical to the default
    backend; wire on emits the proxy's quantized sequence per row (the
    1F1B schedule gives every token the same S hops + one broadcast as
    the plain ring)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.schedule import (
        MicrobatchPipelineBackend,
    )

    cfg, params = tiny
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), eight_devices)
    prompts = [_proxy_prompt(s, cfg, 12) for s in range(2)]
    toks = jnp.asarray(prompts, jnp.int32)
    sampling = G.default_sampling(greedy=True)

    def fleet_seq(backend, n=10):
        cache = backend.init_cache(2, 64)
        first, _, cache = backend.prefill(
            toks, jnp.int32(12), cache, jax.random.PRNGKey(0), sampling
        )
        out, _, _ = backend.decode(
            first, cache, jnp.int32(12), jnp.int32(n - 1),
            jax.random.PRNGKey(1), sampling, max_steps=n - 1,
        )
        return [
            [int(first[r])] + [int(t) for t in np.asarray(out[r])[: n - 1]]
            for r in range(2)
        ]

    base = fleet_seq(MicrobatchPipelineBackend(cfg, params, mesh))
    off = fleet_seq(
        MicrobatchPipelineBackend(cfg, params, mesh, wire_quant=None)
    )
    assert off == base
    on = fleet_seq(
        MicrobatchPipelineBackend(cfg, params, mesh, wire_quant="int8")
    )
    for r in range(2):
        proxy_seq = WQ.proxy_stage_generate(
            cfg, params, prompts[r], 10, 2, quant=True
        )
        assert on[r] == proxy_seq, (r, on[r], proxy_seq)


@needs_shard_map
def test_sp_wire_off_bit_identical_and_on_equals_kv_quant_prefill(
    tiny, eight_devices
):
    """sp ring: wire off == today's outputs; wire ON attends exactly the
    quantized chunk round-trip — which is the SAME attention math the
    int8 KV cache performs — so the wire-on prefill's sampled token
    equals the kv_quant="int8" prefill's, bit for bit."""
    from distributed_llm_inference_tpu.parallel.context import (
        ContextParallelBackend,
    )
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh

    cfg, params = tiny
    mesh = build_mesh(MeshConfig(dp=1, pp=1, sp=2, tp=1), eight_devices)
    prompt = _proxy_prompt(0, cfg, 16)  # bucket 16 % sp == 0
    toks = jnp.asarray([prompt], jnp.int32)
    sampling = G.default_sampling(greedy=True)

    def sp_first(backend):
        cache = backend.init_cache(1, 64)
        first, logits, _ = backend.prefill(
            toks, jnp.int32(16), cache, jax.random.PRNGKey(0), sampling
        )
        return int(first[0]), np.asarray(logits)

    base, logits_base = sp_first(ContextParallelBackend(cfg, params, mesh))
    off, logits_off = sp_first(
        ContextParallelBackend(cfg, params, mesh, wire_quant=None)
    )
    assert off == base
    np.testing.assert_array_equal(logits_off, logits_base)

    # isolate the chunk-hop recipe: the full wire ALSO quantizes the
    # final sampled-window broadcast, which kv_quant never does — with
    # that leg white-box disabled, the two attend byte-identical
    # quantized chunks and the prefill logits must match bit for bit
    pb_on = ContextParallelBackend(cfg, params, mesh, wire_quant="int8")
    pb_on._wire_bcast = False
    on, logits_on = sp_first(pb_on)
    kvq, logits_kvq = sp_first(
        ContextParallelBackend(cfg.replace(kv_quant="int8"), params, mesh)
    )
    assert on == kvq
    np.testing.assert_array_equal(logits_on, logits_kvq)

    # and the FULL wire (broadcast included) still samples a valid
    # token within a step of the kv-quant logits
    full, logits_full = sp_first(
        ContextParallelBackend(cfg, params, mesh, wire_quant="int8")
    )
    assert 0 <= full < cfg.vocab_size
    assert float(np.max(np.abs(logits_full - logits_kvq))) < 0.5


@needs_shard_map
@pytest.mark.slow
def test_sp_pp_composition_wire(tiny, eight_devices):
    """sp x pp: off is bit-identical to the default composed backend;
    on serves greedy decode end to end (composition smoke + the
    per-decision first-token gate)."""
    from distributed_llm_inference_tpu.parallel.context import (
        ContextParallelBackend,
    )
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh

    cfg, params = tiny
    mesh = build_mesh(MeshConfig(dp=1, pp=2, sp=2, tp=1), eight_devices)
    prompt = _proxy_prompt(0, cfg, 16)
    toks = jnp.asarray([prompt], jnp.int32)
    sampling = G.default_sampling(greedy=True)

    def run(backend, n=8):
        cache = backend.init_cache(1, 64)
        first, _, cache = backend.prefill(
            toks, jnp.int32(16), cache, jax.random.PRNGKey(0), sampling
        )
        out, n_gen, _ = backend.decode(
            first, cache, jnp.int32(16), jnp.int32(n - 1),
            jax.random.PRNGKey(1), sampling, max_steps=n - 1,
        )
        return [int(first[0])] + [int(t) for t in np.asarray(out[0])[: n - 1]]

    base = run(ContextParallelBackend(cfg, params, mesh))
    off = run(ContextParallelBackend(cfg, params, mesh, wire_quant=None))
    assert off == base
    on = run(ContextParallelBackend(cfg, params, mesh, wire_quant="int8"))
    assert len(on) == 8
    assert all(0 <= t < cfg.vocab_size for t in on)


@needs_shard_map
@pytest.mark.slow
def test_pp_wire_chaos_crash_recovers_within_envelope(tiny, eight_devices):
    """The chaos leg: a mid-decode crash on a pp=2 paged fleet WITH the
    int8 wire on recovers warm and re-emits the fault-free wire-on
    output bit-identically — the recovery re-prefill's wire crossings
    are row-local, so the restored run cannot leave the envelope."""
    from distributed_llm_inference_tpu.engine.continuous import (
        ContinuousEngine,
    )
    from distributed_llm_inference_tpu.runtime import create_engine
    from distributed_llm_inference_tpu.utils import faults

    eng = create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8,
            pp_wire_quant="int8",
        ),
    )
    assert eng.backend.wire_quant == "int8"
    prompt = "the quick brown fox jumps over the"
    ref = eng.generate(prompt, max_tokens=10, greedy=True, chat=False)
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, restart_backoff_s=0.01,
        kv_pool_blocks=48, kv_block_size=8,
    )
    try:
        r0 = cont.submit(prompt, max_tokens=10, greedy=True, chat=False)
        assert r0["response"] == ref["response"]
        assert cont._shadow is not None and cont._shadow.flush(10.0)
        faults.arm([
            faults.FaultRule("decode_launch", "transient", on_call=4)
        ])
        r1 = cont.submit(prompt, max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        assert r1["status"] == "success", r1
        assert r1["response"] == ref["response"]
    finally:
        faults.disarm()
        cont.close()


@needs_shard_map
def test_pp_wire_bytes_counter_accounts(tiny, eight_devices):
    """dli_pp_wire_bytes_total: attached through the engine seam, the
    backend counts static per-launch bytes on the microstep +
    broadcast families, and the quantized backend counts ~4x less."""
    from distributed_llm_inference_tpu.utils.metrics import MetricsRegistry

    cfg, params = tiny

    def bytes_for(wire):
        pb = _pb(cfg, params, eight_devices, 2, wire_quant=wire)
        reg = MetricsRegistry()
        reg.counter(
            "dli_pp_wire_bytes_total", "", ("path",)
        )
        pb.attach_wire_metrics(reg)
        _greedy_seq(pb, _proxy_prompt(0, cfg, 12), 8)
        snap = reg.snapshot()
        series = snap["dli_pp_wire_bytes_total"]["series"]
        return {
            tuple(s["labels"].items()): s["value"] for s in series
        }

    off = bytes_for(None)
    on = bytes_for("int8")
    assert any("microstep" in str(k) for k in off)
    assert any("broadcast" in str(k) for k in off)
    total_off = sum(off.values())
    total_on = sum(on.values())
    assert total_off / total_on >= 2.0
