"""Logits parity: our JAX Gemma-3 (text) vs a tiny-random HF
Gemma3TextForCausalLM.

Gemma-3 text = gemma-2 bones (unit-offset norms, GeGLU, sqrt(dim) embed
scale, sandwich norms, query_pre_attn_scalar) MINUS the logit softcaps,
PLUS unit-offset per-head qk-norm, an explicit 5-sliding:1-full layer
pattern (cfg.attn_window_layer_types), and DUAL RoPE — sliding layers
rotate with rope_local_base_freq, full layers with rope_theta (+ linear
scaling on the big checkpoints).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
pytest.importorskip("transformers.models.gemma3")

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, get_model_config
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.models.convert import params_from_hf_model

# fast-tier exclusion: HF-parity family file; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow


def _tiny_hf_gemma3(rope_scaling=None, n_layers=6):
    cfg = transformers.Gemma3TextConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=24,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1000000.0, rope_local_base_freq=10000.0,
        sliding_window=16, query_pre_attn_scalar=24,
        rope_scaling=rope_scaling,
        pad_token_id=0, eos_token_id=1, bos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(31)
    model = transformers.Gemma3ForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize(
    "rope_scaling", [None, {"rope_type": "linear", "factor": 8.0}],
    ids=["plain", "linear-scaled"],
)
def test_gemma3_logits_match_hf(rope_scaling):
    hf = _tiny_hf_gemma3(rope_scaling)
    cfg, params = params_from_hf_model(hf, dtype="float32")
    assert cfg.use_qk_norm and cfg.norm_unit_offset and cfg.post_norms
    assert cfg.rope_local_theta == 10000.0
    assert cfg.attn_window == 16
    # HF default layer_types: every 6th layer full (idx 5)
    assert cfg.attn_window_layer_types == (1, 1, 1, 1, 1, 0)
    assert (cfg.rope_scaling == "linear") == (rope_scaling is not None)
    assert cfg.attn_softcap is None and cfg.final_softcap is None
    assert "window_flag" in params["layers"]

    rng = np.random.default_rng(0)
    # long enough that sliding layers actually clip history (window 16)
    tokens = rng.integers(0, cfg.vocab_size, size=(2, 33), dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    cache = llama.init_kv_cache(cfg, batch=2, max_seq=64)
    logits, _ = llama.forward(
        cfg, params, jnp.asarray(tokens, jnp.int32), cache, jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits,
                               rtol=3e-4, atol=3e-4)


def test_gemma3_decode_matches_hf_generate():
    """Step-by-step KV-cache correctness: the per-layer dual-rope and
    window selection must hold across decode positions, not just one
    prefill forward."""
    from distributed_llm_inference_tpu.engine import generate as G

    hf = _tiny_hf_gemma3()
    cfg, params = params_from_hf_model(hf, dtype="float32")
    rng = np.random.default_rng(4)
    prompt_ids = rng.integers(3, cfg.vocab_size, size=21, dtype=np.int64)
    steps = 10
    with torch.no_grad():
        hf_out = hf.generate(
            torch.from_numpy(prompt_ids[None]), max_new_tokens=steps,
            do_sample=False, pad_token_id=0,
        )[0, len(prompt_ids):].numpy().tolist()
    if cfg.eos_token_id in hf_out:
        hf_out = hf_out[: hf_out.index(cfg.eos_token_id)]

    bucket = 32
    tokens = jnp.asarray(
        [prompt_ids.tolist() + [cfg.pad_token_id] * (bucket - len(prompt_ids))],
        jnp.int32,
    )
    plen = jnp.int32(len(prompt_ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(0))
    cache = llama.init_kv_cache(cfg, 1, max_seq=64)
    first, _, cache = G.prefill(cfg, params, tokens, plen, cache, kp, sampling)
    out, n, _ = G.decode(
        cfg, params, first, cache, plen, jnp.int32(steps - 1), kd, sampling,
        max_steps=steps,
    )
    ours = [int(first[0])] + [int(t) for t in np.asarray(out[0][: int(n[0])])]
    if cfg.eos_token_id in ours:
        ours = ours[: ours.index(cfg.eos_token_id)]
    assert ours == hf_out


def test_gemma3_pipeline_matches_single_device(eight_devices):
    """The stacked window_flag + dual-rope selection must survive pipeline
    slicing: a pp=3 mesh (uneven 6-layer split intact) decodes bit-exactly
    what one device decodes."""
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-gemma3-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    ids = [5, 9, 13, 21, 8, 17, 3]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, params, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, params, f_s, cache_s, plen, jnp.int32(steps), kd, sampling,
        max_steps=steps,
    )

    mesh = build_mesh(MeshConfig(dp=1, pp=3, tp=1), eight_devices)
    pb = PipelineBackend(cfg, params, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


def test_gemma3_engine_smoke_and_preset():
    cfg = get_model_config("gemma3-1b")
    assert cfg.use_qk_norm and cfg.rope_local_theta == 10000.0
    assert sum(1 for t in cfg.attn_window_layer_types if t == 0) == 4

    eng = InferenceEngine(
        get_model_config("test-gemma3-tiny"),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("hello gemma3", max_tokens=5, greedy=True)
    assert r["status"] == "success", r
