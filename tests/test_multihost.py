"""Multi-host bring-up plumbing (parallel/mesh.multihost_initialize).

Round-1 review: the DCN bring-up was an untested one-line passthrough.
jax.distributed cannot actually run multi-process in CI, so these tests
pin the ARGUMENT PLUMBING and validation — the part that used to be able
to rot silently — with the initialize call stubbed out.
"""

import pytest
import jax

from distributed_llm_inference_tpu.parallel.mesh import multihost_initialize


@pytest.fixture
def captured(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    return calls


def test_explicit_coordination_plumbs_through(captured):
    multihost_initialize(
        coordinator_address="10.0.0.1:1234", num_processes=4, process_id=2
    )
    assert captured == [
        {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }
    ]


def test_auto_detection_passes_nothing(captured):
    multihost_initialize()
    assert captured == [{}]


def test_extra_kwargs_forwarded(captured):
    multihost_initialize(
        coordinator_address="h:1", num_processes=2, process_id=0,
        local_device_ids=[0, 1],
    )
    assert captured[0]["local_device_ids"] == [0, 1]


def test_partial_coordination_rejected(captured):
    with pytest.raises(ValueError, match="together"):
        multihost_initialize(coordinator_address="h:1")
    with pytest.raises(ValueError, match="together"):
        multihost_initialize(num_processes=2, process_id=0)
    assert captured == []  # rejected before touching jax.distributed


def test_process_id_range_checked(captured):
    with pytest.raises(ValueError, match="out of range"):
        multihost_initialize(
            coordinator_address="h:1", num_processes=2, process_id=2
        )
    assert captured == []


def test_server_cli_wires_coordination(monkeypatch):
    """--coordinator/--num-processes/--process-id reach multihost_initialize
    before the engine is built."""
    from distributed_llm_inference_tpu.parallel import mesh as mesh_mod
    from distributed_llm_inference_tpu.serving import server as server_mod

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )

    class _Stop(Exception):
        pass

    def bail(*a, **kw):
        raise _Stop

    monkeypatch.setattr(server_mod, "create_engine", bail, raising=False)
    # create_engine is imported inside main(); patch at its source instead
    import distributed_llm_inference_tpu.runtime as runtime_mod

    monkeypatch.setattr(runtime_mod, "create_engine", bail)
    with pytest.raises(_Stop):
        server_mod.main(
            [
                "--model", "test-llama-tiny",
                "--coordinator", "c:9999",
                "--num-processes", "2",
                "--process-id", "1",
            ]
        )
    assert calls == [
        {"coordinator_address": "c:9999", "num_processes": 2, "process_id": 1}
    ]
