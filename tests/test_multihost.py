"""Multi-host bring-up plumbing (parallel/mesh.multihost_initialize).

Round-1 review: the DCN bring-up was an untested one-line passthrough.
jax.distributed cannot actually run multi-process in CI, so these tests
pin the ARGUMENT PLUMBING and validation — the part that used to be able
to rot silently — with the initialize call stubbed out.
"""

import json
import os

import pytest
import jax

from distributed_llm_inference_tpu.parallel.mesh import multihost_initialize


@pytest.fixture
def captured(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    return calls


def test_explicit_coordination_plumbs_through(captured):
    multihost_initialize(
        coordinator_address="10.0.0.1:1234", num_processes=4, process_id=2
    )
    assert captured == [
        {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }
    ]


def test_auto_detection_passes_nothing(captured):
    multihost_initialize()
    assert captured == [{}]


def test_extra_kwargs_forwarded(captured):
    multihost_initialize(
        coordinator_address="h:1", num_processes=2, process_id=0,
        local_device_ids=[0, 1],
    )
    assert captured[0]["local_device_ids"] == [0, 1]


def test_partial_coordination_rejected(captured):
    with pytest.raises(ValueError, match="together"):
        multihost_initialize(coordinator_address="h:1")
    with pytest.raises(ValueError, match="together"):
        multihost_initialize(num_processes=2, process_id=0)
    assert captured == []  # rejected before touching jax.distributed


def test_process_id_range_checked(captured):
    with pytest.raises(ValueError, match="out of range"):
        multihost_initialize(
            coordinator_address="h:1", num_processes=2, process_id=2
        )
    assert captured == []


def test_server_cli_wires_coordination(monkeypatch):
    """--coordinator/--num-processes/--process-id reach multihost_initialize
    before the engine is built."""
    from distributed_llm_inference_tpu.parallel import mesh as mesh_mod
    from distributed_llm_inference_tpu.serving import server as server_mod

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )

    class _Stop(Exception):
        pass

    def bail(*a, **kw):
        raise _Stop

    monkeypatch.setattr(server_mod, "create_engine", bail, raising=False)
    # create_engine is imported inside main(); patch at its source instead
    import distributed_llm_inference_tpu.runtime as runtime_mod

    monkeypatch.setattr(runtime_mod, "create_engine", bail)
    with pytest.raises(_Stop):
        server_mod.main(
            [
                "--model", "test-llama-tiny",
                "--coordinator", "c:9999",
                "--num-processes", "2",
                "--process-id", "1",
            ]
        )
    assert calls == [
        {"coordinator_address": "c:9999", "num_processes": 2, "process_id": 1}
    ]


@pytest.mark.slow
def test_two_process_pipelined_generate(tmp_path):
    """Round-2 review #9: a REAL 2-process jax.distributed bring-up (gloo
    CPU collectives), one 2-device pp mesh spanning both processes, one
    pipelined greedy generate — replacing mock-only multihost coverage.
    Each process mmap-loads only its stage via load_params_sharded."""
    import socket
    import subprocess
    import sys as _sys

    from distributed_llm_inference_tpu import create_engine
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models import checkpoint as ckpt
    from distributed_llm_inference_tpu.models.registry import get_model_config

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(31))
    store = str(tmp_path / "mh_store")
    ckpt.save_params(store, cfg, params)
    expected = create_engine(cfg, params=params).generate(
        "multi host hello", max_tokens=5, temperature=0.0, seed=0
    )

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [_sys.executable, worker, str(i), str(port), store],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for i in range(2)
    ]
    results = {}
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT:")]
        assert line, out[-2000:]
        results[i] = json.loads(line[-1][len("RESULT:"):])

    for i in (0, 1):
        assert results[i]["status"] == "success", results[i]
        assert results[i]["n_devices"] == 2
    # both controllers computed the identical pipelined generation, and it
    # matches the single-process reference bit-for-bit
    assert results[0]["response"] == results[1]["response"]
    assert results[0]["response"] == expected["response"]
    assert results[0]["tokens"] == expected["tokens_generated"]


@pytest.mark.slow
def test_two_process_server_cli(tmp_path):
    """Round-3 review #8: the ACTUAL server CLI on a 2-process mesh — the
    reference's N-serving-machines shape (/root/reference/Worker1.py:
    248-266). Process 0 serves HTTP and broadcasts each request
    (serving/multihost.MirroredEngine); process 1 runs the follower loop.
    Drives /generate + /workers through client.py and checks the response
    matches a single-process engine on the same checkpoint bit-for-bit."""
    import socket
    import subprocess
    import sys as _sys
    import time
    import urllib.request

    from distributed_llm_inference_tpu import create_engine
    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.models import checkpoint as ckpt
    from distributed_llm_inference_tpu.models.registry import get_model_config

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(41))
    store = str(tmp_path / "mh_srv_store")
    ckpt.save_params(store, cfg, params)
    expected = create_engine(cfg, params=params).generate(
        "serve me twice", max_tokens=5, temperature=0.0, seed=0, chat=False
    )
    assert expected["status"] == "success"

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    coord, http_port = free_port(), free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env.update(
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        JAX_PLATFORMS="cpu",
        JAX_CPU_COLLECTIVES_IMPLEMENTATION="gloo",
        JAX_DEFAULT_MATMUL_PRECISION="highest",
    )
    procs = [
        subprocess.Popen(
            [
                _sys.executable, "-m",
                "distributed_llm_inference_tpu.serving.server",
                "--checkpoint", store, "--pp", "2",
                "--coordinator", f"127.0.0.1:{coord}",
                "--num-processes", "2", "--process-id", str(i),
                "--host", "127.0.0.1", "--port", str(http_port),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    try:
        deadline = time.time() + 240
        up = False
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                break
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/health", timeout=2
                ) as r:
                    if json.loads(r.read())["status"] in ("healthy", "degraded"):
                        up = True
                        break
            except Exception:
                time.sleep(2)
        if not up:
            outs = []
            for p in procs:
                p.kill()
                out, _ = p.communicate(timeout=30)
                outs.append(out[-2000:])
            raise AssertionError(f"server never came up:\n{outs}")

        # /generate through client.py (the reference Test.py flow; the
        # client's own defaults — sampled, chat template — so this leg
        # checks the flow, the deterministic parity check is below)
        client = subprocess.run(
            [
                _sys.executable, "-m", "distributed_llm_inference_tpu.client",
                "--url", f"http://127.0.0.1:{http_port}",
                "--prompt", "serve me twice", "--max-tokens", "5",
            ],
            capture_output=True, text=True, timeout=300,
            env={k: v for k, v in os.environ.items()},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert client.returncode == 0, client.stdout + client.stderr
        assert "Response:" in client.stdout, client.stdout + client.stderr

        # /workers: 2 stages; each reports its OWN process's device online
        # and the other "remote"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}/workers", timeout=120
        ) as r:
            workers = json.loads(r.read())
        stages = workers["detail"]
        assert len(stages) == 2
        statuses = [s["status"] for s in stages]
        assert "online" in statuses and "remote" in statuses, statuses

        # a second request exercises the broadcast path again (no wedge)
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/generate",
            data=json.dumps({
                "prompt": "serve me twice", "max_tokens": 5,
                "temperature": 0.0, "seed": 0, "chat": False,
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            second = json.loads(r.read())
        assert second["status"] == "success"
        assert second["response"] == expected["response"]
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                pass


def test_multiprocess_rejects_timing_dependent_layers(monkeypatch):
    """--continuous/--queue cannot mirror deterministically across
    processes: multi-process serving exits loudly instead of serving
    diverging collectives."""
    from distributed_llm_inference_tpu.serving import server as server_mod

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with pytest.raises(SystemExit, match="ARRIVAL TIMING"):
        server_mod.main(
            ["--model", "test-llama-tiny", "--continuous", "2", "--port", "0"]
        )


def test_shutdown_followers_bounded_when_follower_dead(monkeypatch):
    """A follower that already died can never answer the shutdown
    collective; the leader's exit must be bounded, not wedged — the
    broadcast runs on an abandoned daemon thread past timeout_s (same
    discipline as engine._with_deadline)."""
    import threading
    import time

    from distributed_llm_inference_tpu.serving import multihost as mh

    m = mh.MirroredEngine(object())
    hung = threading.Event()

    def _hang(obj, is_source):
        hung.set()
        time.sleep(30)  # the dead-follower collective never completes

    monkeypatch.setattr(mh, "_broadcast_obj", _hang)
    t0 = time.time()
    assert m.shutdown_followers(timeout_s=0.2) is False
    assert time.time() - t0 < 5
    assert hung.wait(5)  # the broadcast really was attempted


def test_shutdown_followers_returns_true_on_fast_broadcast(monkeypatch):
    from distributed_llm_inference_tpu.serving import multihost as mh

    m = mh.MirroredEngine(object())
    seen = []
    monkeypatch.setattr(
        mh, "_broadcast_obj", lambda obj, is_source: seen.append(obj)
    )
    assert m.shutdown_followers(timeout_s=5.0) is True
    assert seen == [mh._SHUTDOWN]


def test_shutdown_followers_bounded_when_issue_lock_held(monkeypatch):
    """A wedged mirrored call holds the issue lock; shutdown must not
    wait on it forever either — the lock acquisition lives on the same
    abandoned thread as the broadcast."""
    from distributed_llm_inference_tpu.serving import multihost as mh

    m = mh.MirroredEngine(object())
    monkeypatch.setattr(
        mh, "_broadcast_obj", lambda obj, is_source: None
    )
    with m._issue_lock:  # a stuck mirrored call, in spirit
        assert m.shutdown_followers(timeout_s=0.2) is False
    # lock released: the abandoned thread's broadcast now completes
    # harmlessly in the background
