"""Continuous (in-flight) batching tests: staggered admission equivalence,
EOS/limit semantics, backpressure, mixed per-slot sampling.

The bar: a request served while OTHER requests come and go mid-flight must
produce exactly the tokens it would get served solo (greedy, fp32 — slot
rows are mathematically independent through the whole stack).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import (
    InferenceEngine,
    SingleDeviceBackend,
)
from distributed_llm_inference_tpu.models import llama

# fast-tier exclusion: many fleet-program compiles; run the full suite (plain
# `pytest`) to include it
pytestmark = pytest.mark.slow

PROMPTS = [
    "the quick brown fox",
    "jumps over",
    "a lazy dog while the band plays on",
    "hello",
    "one two three four five six seven",
]


@pytest.fixture(scope="module")
def solo_engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))


def _zero_params(cfg):
    p = llama.init_params(cfg, jax.random.PRNGKey(0))
    return jax.tree.map(jnp.zeros_like, p)


def test_decode_slots_matches_plain_decode(solo_engine):
    """Device-level check: one occupied slot in a 4-slot fleet decodes the
    exact token stream plain decode produces from the same prefill."""
    eng = solo_engine
    cfg = eng.cfg
    backend = eng.backend
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)
    tokens = jnp.asarray([[cfg.bos_token_id, 11, 12, 13, 14, 15, 16, 17]], jnp.int32)
    tokens = jnp.pad(tokens, ((0, 0), (0, 24)), constant_values=cfg.pad_token_id)
    plen = jnp.int32(8)

    # plain: prefill + decode 12 steps
    cache_a = backend.init_cache(1, cfg.max_seq_len)
    first_a, _, cache_a = backend.prefill(tokens, plen, cache_a, key, sampling)
    out_a, n_a, _ = backend.decode(
        first_a, cache_a, plen, jnp.int32(12), key, sampling, max_steps=16
    )

    # slots: same prefill spliced into slot 2 of a 4-slot fleet
    cache_b = backend.init_cache(4, cfg.max_seq_len)
    state, sparams = G.init_slots(4, cfg.vocab_size)
    scratch = backend.init_cache(1, cfg.max_seq_len)
    first_b, _, scratch = backend.prefill(tokens, plen, scratch, key, sampling)
    cache_b, state, sparams = G.insert_slot(
        cfg, cache_b, scratch, state, sparams, 2, first_b[0], plen,
        jnp.int32(13),
        jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0), jnp.bool_(True),
        jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((cfg.vocab_size,), bool),
    )
    emitted, mask, state, cache_b = G.decode_slots(
        cfg, backend.params, state, cache_b, key, sparams, num_steps=14
    )
    emitted, mask = np.asarray(emitted), np.asarray(mask)
    slot_tokens = [int(t) for t in emitted[mask[:, 2], 2]]

    ref = [int(t) for t in np.asarray(out_a[0])[: int(n_a[0])]]
    assert int(first_b[0]) == int(first_a[0])
    assert slot_tokens == ref
    # other slots stayed silent
    assert not mask[:, [0, 1, 3]].any()


def test_staggered_admission_matches_solo(solo_engine):
    """Concurrent requests admitted at different times (more requests than
    slots, so slots recycle mid-flight) each match their solo greedy run."""
    solo = {
        p: solo_engine.generate(p, max_tokens=10, greedy=True, chat=False)
        for p in PROMPTS
    }
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4, max_queue=16)
    try:
        results = {}
        lock = threading.Lock()

        def run(p, delay):
            time.sleep(delay)
            r = cont.submit(p, max_tokens=10, greedy=True, chat=False)
            with lock:
                results[p] = r

        threads = [
            threading.Thread(target=run, args=(p, 0.05 * i))
            for i, p in enumerate(PROMPTS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == len(PROMPTS)
        for p in PROMPTS:
            r = results[p]
            assert r["status"] == "success", r
            assert r["continuous"] is True
            assert r["response"] == solo[p]["response"], p
            assert r["tokens_generated"] == solo[p]["tokens_generated"], p
        s = cont.stats()
        assert s["completed"] == len(PROMPTS)
        assert s["occupied"] == 0
        assert s["peak_occupancy"] >= 2  # slots actually shared the fleet
    finally:
        cont.close()


def test_eos_immediate_and_max_tokens_exact():
    """Zero params + eos=0: every request finishes with 0 tokens. Then with
    eos unreachable, exactly max_tokens tokens come back."""
    cfg = get_model_config("test-llama-tiny").replace(eos_token_id=0, pad_token_id=3)
    eng = InferenceEngine(
        cfg,
        backend=SingleDeviceBackend(cfg, _zero_params(cfg)),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        r = cont.submit("hi", max_tokens=8, greedy=True, chat=False)
        assert r["status"] == "success"
        assert r["tokens_generated"] == 0 and r["response"] == ""
    finally:
        cont.close()

    cfg2 = get_model_config("test-llama-tiny").replace(eos_token_id=5, pad_token_id=3)
    eng2 = InferenceEngine(
        cfg2,
        backend=SingleDeviceBackend(cfg2, _zero_params(cfg2)),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    cont2 = ContinuousEngine(eng2, n_slots=2, chunk_steps=4)
    try:
        r = cont2.submit("hi", max_tokens=6, greedy=True, chat=False)
        assert r["status"] == "success"
        assert r["tokens_generated"] == 6
    finally:
        cont2.close()


def test_mixed_sampling_params_share_fleet(solo_engine):
    """A greedy slot and a sampled slot decode together; the greedy one
    still matches its solo run exactly."""
    p_greedy, p_sampled = PROMPTS[0], PROMPTS[1]
    solo = solo_engine.generate(p_greedy, max_tokens=8, greedy=True, chat=False)
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4)
    try:
        out = {}

        def run(p, **kw):
            out[p] = cont.submit(p, max_tokens=8, chat=False, **kw)

        t1 = threading.Thread(target=run, args=(p_greedy,), kwargs={"greedy": True})
        t2 = threading.Thread(
            target=run, args=(p_sampled,),
            kwargs={"temperature": 0.9, "top_k": 5, "top_p": 0.9},
        )
        t1.start(); t2.start()
        t1.join(timeout=120); t2.join(timeout=120)
        assert out[p_greedy]["status"] == "success"
        assert out[p_sampled]["status"] == "success"
        assert out[p_greedy]["response"] == solo["response"]
    finally:
        cont.close()


def test_queue_full_sheds_429(solo_engine):
    cont = ContinuousEngine(solo_engine, n_slots=1, chunk_steps=4, max_queue=1)
    try:
        outs = []
        lock = threading.Lock()

        def run():
            r = cont.submit(PROMPTS[2], max_tokens=32, greedy=True, chat=False)
            with lock:
                outs.append(r)

        threads = [threading.Thread(target=run) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        shed = [r for r in outs if r.get("error_type") == "overloaded"]
        ok = [r for r in outs if r.get("status") == "success"]
        assert len(outs) == 6
        assert shed, "bounded queue never shed load"
        assert ok, "no request served at all"
    finally:
        cont.close()


def test_seeded_request_falls_back_solo(solo_engine):
    """A seeded request keeps its determinism contract by running solo."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4)
    try:
        a = cont.submit("seeded prompt", max_tokens=6, seed=123, chat=False)
        b = cont.submit("seeded prompt", max_tokens=6, seed=123, chat=False)
        assert a["status"] == b["status"] == "success"
        assert a["response"] == b["response"]
        assert "continuous" not in a  # served by the solo engine
    finally:
        cont.close()


def test_rejects_unsupported_configs(solo_engine):
    eng0 = object.__new__(InferenceEngine)
    eng0.cfg = solo_engine.cfg.replace(arch="t5")  # unsupported arch
    with pytest.raises(ValueError, match="families"):
        ContinuousEngine(eng0)

    class NoSlots:
        name = "fake"
        supports_slots = False

    eng2 = object.__new__(InferenceEngine)
    eng2.cfg = solo_engine.cfg
    eng2.backend = NoSlots()
    with pytest.raises(ValueError, match="slot"):
        ContinuousEngine(eng2)


def test_gpt2_continuous_matches_solo():
    """GPT-2 CAN slot-batch (unlike ragged left-padding: every slot starts
    at position 0, so learned absolute positions stay exact): staggered
    concurrent requests match solo greedy runs."""
    cfg = get_model_config("test-gpt2-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))
    solo = {
        p: eng.generate(p, max_tokens=8, greedy=True, chat=False)
        for p in PROMPTS[:3]
    }
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        results = {}
        lock = threading.Lock()

        def run(p, delay):
            time.sleep(delay)
            r = cont.submit(p, max_tokens=8, greedy=True, chat=False)
            with lock:
                results[p] = r

        threads = [
            threading.Thread(target=run, args=(p, 0.05 * i))
            for i, p in enumerate(PROMPTS[:3])
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for p in PROMPTS[:3]:
            assert results[p]["status"] == "success", results[p]
            assert results[p]["response"] == solo[p]["response"], p
    finally:
        cont.close()


def test_deadline_expired_in_queue_does_not_kill_engine(solo_engine):
    """A request that ages past the deadline WHILE QUEUED gets a timeout
    envelope — and the worker loop survives to serve later requests
    (regression: the expired admission once poisoned the fetch wave)."""
    cfg = solo_engine.cfg
    eng = InferenceEngine(
        cfg,
        backend=solo_engine.backend,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), request_deadline_s=0.25
        ),
    )
    cont = ContinuousEngine(eng, n_slots=1, chunk_steps=2, max_queue=16)
    try:
        # deterministic: a request already aged past the deadline when the
        # admission loop reaches it (backdated enqueue time — no timing
        # races against warm-cache generation speed)
        from distributed_llm_inference_tpu.engine.continuous import _Request

        req = _Request("victim", dict(max_tokens=4, greedy=True, chat=False))
        req.enqueued = req.t_start = time.time() - 10
        assert cont._enqueue(req) is None
        assert req.done.wait(60)
        assert req.result["error_type"] == "timeout", req.result
        # the engine must still be alive: a fresh request succeeds
        r = cont.submit("still alive?", max_tokens=3, greedy=True, chat=False)
        assert r["status"] == "success", r
    finally:
        cont.close()


def test_stream_deltas_reassemble_full_response(solo_engine):
    """stream() yields incremental text deltas whose concatenation equals
    the solo response, with the standard envelope as the final event."""
    p = PROMPTS[2]
    solo = solo_engine.generate(p, max_tokens=16, greedy=True, chat=False)
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4)
    try:
        events = list(cont.stream(p, max_tokens=16, greedy=True, chat=False))
        final = events[-1]
        deltas = [e["delta"] for e in events[:-1]]
        assert final.get("done") is True
        assert final["status"] == "success", final
        assert final["response"] == solo["response"]
        assert "".join(deltas) == solo["response"]
        # chunk_steps=4 over 16 tokens: streaming must actually be
        # incremental, not one blob at the end
        assert len(deltas) >= 3, deltas
    finally:
        cont.close()


def test_stream_concurrent_with_submit(solo_engine):
    """A streaming request and blocking requests share the fleet."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4)
    try:
        out = {}

        def run_blocking():
            out["b"] = cont.submit(PROMPTS[0], max_tokens=12, greedy=True, chat=False)

        t = threading.Thread(target=run_blocking)
        t.start()
        events = list(
            cont.stream(PROMPTS[1], max_tokens=12, greedy=True, chat=False)
        )
        t.join(timeout=120)
        assert events[-1]["status"] == "success"
        assert out["b"]["status"] == "success"
        solo = solo_engine.generate(PROMPTS[1], max_tokens=12, greedy=True, chat=False)
        assert events[-1]["response"] == solo["response"]
    finally:
        cont.close()


def test_stream_seeded_falls_back_single_event(solo_engine):
    cont = ContinuousEngine(solo_engine, n_slots=1, chunk_steps=4)
    try:
        events = list(cont.stream("seeded", max_tokens=5, seed=3, chat=False))
        assert len(events) == 1
        assert events[0]["status"] == "success" and events[0]["done"] is True
    finally:
        cont.close()


def test_pipeline_continuous_matches_solo(solo_engine, eight_devices):
    """In-flight batching over a pp=2 pipeline mesh: staggered concurrent
    requests through the shard_map slot fleet match their solo
    single-device greedy runs exactly."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = solo_engine.cfg
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), jax.devices())
    pb = PipelineBackend(cfg, solo_engine.backend.params, mesh)
    assert pb.supports_slots
    eng = InferenceEngine(
        cfg, backend=pb, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )
    solo = {
        p: solo_engine.generate(p, max_tokens=8, greedy=True, chat=False)
        for p in PROMPTS[:3]
    }
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        results = {}
        lock = threading.Lock()

        def run(p, delay):
            time.sleep(delay)
            r = cont.submit(p, max_tokens=8, greedy=True, chat=False)
            with lock:
                results[p] = r

        threads = [
            threading.Thread(target=run, args=(p, 0.1 * i))
            for i, p in enumerate(PROMPTS[:3])
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for p in PROMPTS[:3]:
            assert results[p]["status"] == "success", results[p]
            assert results[p]["response"] == solo[p]["response"], p
    finally:
        cont.close()


def test_pipeline_continuous_rejects_dp(solo_engine, eight_devices):
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = solo_engine.cfg
    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=1), jax.devices())
    pb = PipelineBackend(cfg, solo_engine.backend.params, mesh)
    assert not pb.supports_slots
    eng = InferenceEngine(
        cfg, backend=pb, engine_cfg=EngineConfig(prefill_buckets=(32,))
    )
    with pytest.raises(ValueError, match="slot"):
        ContinuousEngine(eng)


def test_continuous_prefix_cache_reuse(solo_engine):
    """A re-served shared prompt hits the continuous engine's own prefix
    cache (prefill only the tail) and still emits exactly the solo tokens."""
    cfg = solo_engine.cfg
    eng = InferenceEngine(
        cfg,
        backend=solo_engine.backend,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=4, prefix_chunk=16
        ),
    )
    prompt = "shared prefix prompt with plenty of tokens to cross a chunk"
    solo = solo_engine.generate(prompt, max_tokens=8, greedy=True, chat=False)
    cont = ContinuousEngine(eng, n_slots=2, chunk_steps=4)
    try:
        r1 = cont.submit(prompt, max_tokens=8, greedy=True, chat=False)
        assert r1["status"] == "success"
        assert r1["response"] == solo["response"]
        r2 = cont.submit(prompt, max_tokens=8, greedy=True, chat=False)
        assert r2["status"] == "success"
        assert r2["response"] == solo["response"]
        assert r2.get("prefix_cached_tokens", 0) >= 16  # tail-only prefill
        s = cont.stats()
        assert s["prefix_cache"]["hits"] >= 1
    finally:
        cont.close()


def test_stream_abandon_cancels_slot(solo_engine):
    """Closing a streaming generator mid-flight cancels the request: its
    slot frees early and the fleet keeps serving."""
    cont = ContinuousEngine(solo_engine, n_slots=1, chunk_steps=2, max_queue=8)
    try:
        gen = cont.stream(PROMPTS[2], max_tokens=64, greedy=True, chat=False)
        first_ev = next(gen)
        assert "delta" in first_ev
        gen.close()  # abandon: engine must cancel, not decode 64 tokens
        deadline = time.time() + 30
        while time.time() < deadline:
            if cont.stats()["occupied"] == 0:
                break
            time.sleep(0.2)
        assert cont.stats()["occupied"] == 0, "cancelled slot never freed"
        # fleet still serves
        r = cont.submit("after cancel", max_tokens=3, greedy=True, chat=False)
        assert r["status"] == "success"
    finally:
        cont.close()


def test_cancel_while_queued(solo_engine):
    """cancel() on a still-queued request dequeues it immediately with a
    cancelled envelope."""
    cont = ContinuousEngine(solo_engine, n_slots=1, chunk_steps=2, max_queue=8)
    try:
        # occupy the single slot
        blocker = threading.Thread(
            target=lambda: cont.submit(
                PROMPTS[0], max_tokens=32, greedy=True, chat=False
            )
        )
        blocker.start()
        time.sleep(0.3)
        from distributed_llm_inference_tpu.engine.continuous import _Request

        req = _Request("queued victim", dict(max_tokens=4, greedy=True, chat=False))
        err = cont._enqueue(req)
        assert err is None
        cont.cancel(req)
        assert req.done.is_set()
        assert req.result["error_type"] == "cancelled"
        blocker.join(timeout=120)
    finally:
        cont.close()


def test_over_long_prompt_invalid_request(solo_engine):
    cont = ContinuousEngine(solo_engine, n_slots=1, chunk_steps=4)
    try:
        r = cont.submit("w " * (solo_engine.cfg.max_seq_len * 2),
                        max_tokens=4, chat=False)
        assert r["status"] == "failed"
        assert r["error_type"] == "invalid_request"
    finally:
        cont.close()


def test_slot_max_seq_bounds_fleet_cache(solo_engine):
    """Round-2 review weak #7: fleet KV is a function of the configured
    per-slot budget, not n_slots x model max_seq_len."""
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=64)
    try:
        # cache [L, B, KV, S, Dh]: the S axis equals the slot budget
        assert cont.cache["k"].shape[3] == 64
        assert cont.cache["k"].shape[1] == 2
        assert cont._scratch["k"].shape[3] == 64
        r = cont.submit("short prompt", max_tokens=5, greedy=True, chat=False)
        assert r["status"] == "success"
    finally:
        cont.close()


def test_slot_max_seq_rejects_oversized_prompt(solo_engine):
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=40)
    try:
        # the only fitting prefill bucket is 32; a prompt over 38 tokens
        # cannot fit the 40-slot class even though the model window could
        long_prompt = "x " * 50
        r = cont.submit(long_prompt, max_tokens=5, greedy=True, chat=False)
        assert r["status"] == "failed"
        assert "slot capacity" in r["error"]
        # and a fitting request still serves
        ok = cont.submit("fits fine", max_tokens=4, greedy=True, chat=False)
        assert ok["status"] == "success"
    finally:
        cont.close()


def test_slot_max_seq_clamps_decode_budget(solo_engine):
    cont = ContinuousEngine(solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=48)
    try:
        # budget clamps to slot_max_seq - prompt_len - 1 (decode writes at
        # prompt_len.., re-using the padded prefill bucket's junk slots),
        # far below the requested 400
        r = cont.submit("a b c", max_tokens=400, greedy=True, chat=False)
        assert r["status"] == "success"
        assert r["tokens_generated"] <= 48 - r["prompt_tokens"] - 1
    finally:
        cont.close()


def test_invalid_kwarg_after_block_grant_releases_pool(solo_engine):
    """Regression (lock-discipline/lifecycle audit, PR 12): a ValueError
    raised AFTER the paged admission's block grant — e.g. a malformed
    sampling kwarg whose float() only runs at arming time — must release
    the granted blocks (and any constraint row) before the
    invalid_request envelope is delivered. Pre-fix, the except handler
    in _admit/_start_jobs pushed the envelope without touching
    req.block_ids, bleeding the pool on every malformed embedded
    request — the PR-4 _BLOCKED leak shape on the error path."""
    eng = solo_engine
    cont = ContinuousEngine(
        InferenceEngine(
            eng.cfg, params=eng.backend.params,
            engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        ),
        n_slots=2, chunk_steps=4, slot_max_seq=192,
        kv_pool_blocks=40, kv_block_size=16,
    )
    try:
        total = cont._alloc.free_blocks
        for _ in range(3):  # a leak compounds; hygiene must not
            out = cont.submit(
                "hello there", max_tokens=4, chat=False,
                repetition_penalty="bogus",
            )
            assert out["error_type"] == "invalid_request"
            assert "failed" == out["status"]
            assert cont._alloc.free_blocks == total, "pool leaked blocks"
        # the fleet still serves clean requests afterwards
        ok = cont.submit("hello there", max_tokens=4, greedy=True,
                         chat=False)
        assert ok["status"] == "success"
    finally:
        cont.close()
