"""Prompt-lookup speculative decoding (engine/generate.decode_speculative).

Correctness bar: BIT-IDENTICAL output to plain greedy decode in this
suite's fp32/highest-precision CPU environment — every emitted token is
the model's argmax given the accepted context; speculation only changes
how many land per forward. (In bf16 on TPU the chunked verify matmuls
may resolve numerical near-ties differently — same benign class as
chunked-vs-tokenwise prefill.) The reference has no analogue (0.12-0.2
tok/s with no KV cache at all); this is a beyond-parity TPU feature.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config


def _setup(cfg, ids, bucket=32, max_seq=256):
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        [ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32
    )
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(1))
    return params, tokens, sampling, kp, kd


def _plain(cfg, params, tokens, plen, steps, kp, kd, sampling, max_seq=256):
    cache = M.init_kv_cache(cfg, 1, max_seq=max_seq)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, kp, sampling
    )
    out, n, _ = G.decode(
        cfg, params, first, cache, jnp.int32(plen), jnp.int32(steps),
        kd, sampling, max_steps=steps,
    )
    return first, out, n


def _spec(cfg, params, tokens, ids, plen, steps, kp, sampling, draft_len=4,
          max_seq=256):
    cache = M.init_kv_cache(cfg, 1, max_seq=max_seq)
    first, _, cache = G.prefill(
        cfg, params, tokens, jnp.int32(plen), cache, kp, sampling
    )
    hist = jnp.zeros((1, max_seq + draft_len + 2), jnp.int32)
    hist = hist.at[0, :plen].set(jnp.asarray(ids, jnp.int32))
    out, n, _ = G.decode_speculative(
        cfg, params, first, cache, hist, jnp.int32(plen), jnp.int32(steps),
        max_steps=steps, draft_len=draft_len,
    )
    return first, out, n


@pytest.mark.parametrize("draft_len", [2, 4, 7])
@pytest.mark.parametrize(
    "ids",
    [
        ([7, 11, 13, 17] * 6)[:20],  # repetitive: speculation lands
        [5, 9, 13, 21, 8, 3, 30, 12, 25, 6],  # no repeats: all rejected
    ],
    ids=["repetitive", "random"],
)
@pytest.mark.slow
def test_speculative_bit_identical_to_greedy(ids, draft_len):
    cfg = get_model_config("test-llama-tiny", eos_token_id=-1, max_seq_len=256)
    params, tokens, sampling, kp, kd = _setup(cfg, ids)
    steps = 24
    f_r, out_r, n_r = _plain(cfg, params, tokens, len(ids), steps, kp, kd, sampling)
    f_s, out_s, n_s = _spec(
        cfg, params, tokens, ids, len(ids), steps, kp, sampling, draft_len
    )
    assert int(f_r[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_r), np.asarray(out_s))
    assert int(n_r[0]) == int(n_s[0])


@pytest.mark.slow
def test_speculative_eos_truncation_matches():
    cfg0 = get_model_config("test-llama-tiny", eos_token_id=-1, max_seq_len=256)
    ids = ([7, 11, 13, 17] * 6)[:20]
    params, tokens, sampling, kp, kd = _setup(cfg0, ids)
    steps = 24
    _, out_free, _ = _plain(cfg0, params, tokens, len(ids), steps, kp, kd, sampling)
    eos = int(np.asarray(out_free)[0, 6])  # token greedy emits mid-stream

    cfg = cfg0.replace(eos_token_id=eos)
    f_r, out_r, n_r = _plain(cfg, params, tokens, len(ids), steps, kp, kd, sampling)
    f_s, out_s, n_s = _spec(cfg, params, tokens, ids, len(ids), steps, kp, sampling)
    assert int(np.asarray(n_r)[0]) < steps  # EOS actually truncated
    np.testing.assert_array_equal(np.asarray(out_r), np.asarray(out_s))
    assert int(n_r[0]) == int(n_s[0])


@pytest.mark.slow
def test_speculative_limit_exact():
    """The traced limit cuts emission mid-window without overshoot."""
    cfg = get_model_config("test-llama-tiny", eos_token_id=-1, max_seq_len=256)
    ids = ([7, 11, 13, 17] * 6)[:20]
    params, tokens, sampling, kp, kd = _setup(cfg, ids)
    for steps in (1, 3, 5):
        f_r, out_r, n_r = _plain(cfg, params, tokens, len(ids), steps, kp, kd, sampling)
        f_s, out_s, n_s = _spec(cfg, params, tokens, ids, len(ids), steps, kp, sampling)
        np.testing.assert_array_equal(np.asarray(out_r), np.asarray(out_s))
        assert int(n_s[0]) == int(n_r[0]) <= steps


def test_engine_speculative_flag():
    engine = create_engine(
        get_model_config("test-llama-tiny", max_seq_len=256),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64), max_seq_len=256),
    )
    p = "repeat repeat repeat repeat repeat"
    r_plain = engine.generate(p, max_tokens=8, greedy=True, chat=False)
    r_spec = engine.generate(p, max_tokens=8, greedy=True, chat=False,
                             speculative=True)
    assert r_spec["status"] == "success", r_spec
    assert r_spec.get("speculative") is True
    assert r_spec["response"] == r_plain["response"]
    # non-greedy ignores the flag
    r_sampled = engine.generate(p, max_tokens=4, chat=False, speculative=True,
                                seed=3)
    assert r_sampled["status"] == "success"
    assert "speculative" not in r_sampled
