"""Warm-state recovery chaos suite (engine/shadow.py + the continuous
supervisor's restore path).

The bar, on top of tests/test_faults.py's cold-recovery guarantees:
  * chaos MATRIX — a crash at every fault point (admission / prefill /
    decode_launch / fetch / shadow_copy) × {warm, cold}: greedy output
    stays bit-identical to a fault-free run in EVERY cell, and the warm
    cells re-prefill only the partial tail block
    (dli_recovery_tokens_recomputed_total < block_size per request)
    while the cold cells recompute the whole sequence;
  * crash DURING restore (double fault): the supervisor contains the
    second crash, retries the restore, and the output is still
    bit-identical;
  * graceful drain persists the shadow to --restore-dir and a fresh
    engine restores it — the respawn serves the old prompt set with a
    warm block-prefix cache (the router's rolling-restart handoff);
  * the shadow store itself: content-keyed chains, LRU cascade
    eviction, bounded copier backpressure (drops, never blocks), and a
    crash-consistent (atomic-rename) on-disk format;
  * wedge-driven ejection: /ready flips 503 (reason "wedged") while an
    abandoned deadline-overrun call exceeds --wedge-unready, and
    recovers when the call drains — dli_engine_wedged tracks it.

Deterministic like the rest of the chaos tier: counter triggers, no wall
clock (marker `chaos`, never `slow`).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.engine.shadow import ShadowStore
from distributed_llm_inference_tpu.serving.server import InferenceServer
from distributed_llm_inference_tpu.utils import faults

pytestmark = pytest.mark.chaos

BS = 8  # kv_block_size for every fleet here
POOL = 48
PROMPT = "the quick brown fox jumps over the"  # 27 ids, NOT a BS multiple


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8
        ),
    )


@pytest.fixture(scope="module")
def solo(engine):
    return engine.generate(PROMPT, max_tokens=10, greedy=True, chat=False)


def _cont(engine, warm=True, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("kv_pool_blocks", POOL)
    kw.setdefault("kv_block_size", BS)
    return ContinuousEngine(engine, kv_shadow=warm, **kw)


def _ctr(engine, name):
    snap = engine.metrics.snapshot()
    return sum(
        s["value"] for s in snap.get(name, {}).get("series", [])
    )


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=15) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# -- the chaos matrix ---------------------------------------------------------

# per-point trigger: late enough that the request is mid-flight with its
# prompt blocks already shadowed (decode_launch fires on the 4th launch
# so at least one healthy fetch lands first; the single-firing default
# keeps the recovery path itself fault-free)
_MATRIX_RULES = {
    "admission": dict(on_call=1),
    "prefill": dict(on_call=1),
    "decode_launch": dict(on_call=4),
    "fetch": dict(on_call=2),
    "shadow_copy": dict(on_call=1),
}


@pytest.mark.parametrize("warm", [True, False], ids=["warm", "cold"])
@pytest.mark.parametrize("point", sorted(_MATRIX_RULES))
def test_crash_matrix_warm_vs_cold(engine, solo, point, warm):
    """Crash at each fault point, warm (shadow on) vs cold (shadow off):
    output bit-identical in every cell; warm recomputes only the partial
    tail block, cold recomputes the whole sequence. The first (clean)
    serve populates the shadow, so even admission-time crashes — whose
    own blocks never filled — restore their prompt's chains."""
    cont = _cont(engine, warm=warm)
    try:
        r0 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r0["response"] == solo["response"], r0
        if warm:
            assert cont._shadow.flush(10.0)
        base = _ctr(engine, "dli_recovery_tokens_recomputed_total")
        faults.arm([
            faults.FaultRule(point, "transient", **_MATRIX_RULES[point])
        ])
        r1 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        if point == "shadow_copy" and not warm:
            # no shadow store => the point is never reached: the cell
            # degenerates to a fault-free serve (still bit-identical)
            assert cont.restarts_total == 0
            assert r1["response"] == solo["response"]
            return
        assert r1["status"] == "success", r1
        assert r1["response"] == solo["response"]
        assert r1["tokens_generated"] == solo["tokens_generated"]
        assert cont.restarts_total == 1
        assert cont.stats()["supervisor"]["ready"] is True
        recomputed = _ctr(
            engine, "dli_recovery_tokens_recomputed_total"
        ) - base
        if warm:
            # only the partial tail block (plus any salvage past the
            # last shadowed boundary) re-prefills
            assert 0 < recomputed < BS, recomputed
            assert cont.shadow_restored_total > 0
        else:
            # cold recovery recomputes the whole prompt(+salvage)
            assert recomputed > 2 * BS, recomputed
        # pool hygiene across the crash: everything not cached by the
        # prefix index is back on the free list
        st = cont.stats()["paged"]
        assert st["free_blocks"] + st["cached_blocks"] == POOL - 1
    finally:
        faults.disarm()
        cont.close()


def test_double_fault_crash_during_restore(engine, solo):
    """A SECOND crash inside the restore itself (shadow_copy at the
    'restore' tag) is contained like any scheduler crash: resources
    released, fleet rebuilt again, restore retried — greedy output still
    bit-identical, two restarts on the books."""
    cont = _cont(engine, warm=True)
    try:
        r0 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r0["response"] == solo["response"]
        assert cont._shadow.flush(10.0)
        faults.arm([
            faults.FaultRule("decode_launch", "transient", on_call=4),
            faults.FaultRule(
                "shadow_copy", "transient", match="restore", on_call=1
            ),
        ])
        r1 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        assert r1["status"] == "success", r1
        assert r1["response"] == solo["response"]
        assert cont.restarts_total == 2
        assert cont.shadow_restored_total > 0  # the retried restore
        assert cont.stats()["supervisor"]["ready"] is True
    finally:
        faults.disarm()
        cont.close()


def test_warm_beats_cold_on_recompute(engine, solo):
    """The acceptance inequality in one place: same crash, warm
    recomputes strictly fewer tokens than cold."""
    costs = {}
    for warm in (True, False):
        cont = _cont(engine, warm=warm)
        try:
            cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
            if warm:
                cont._shadow.flush(10.0)
            base = _ctr(engine, "dli_recovery_tokens_recomputed_total")
            faults.arm([
                faults.FaultRule("decode_launch", "transient", on_call=4)
            ])
            r = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
            faults.disarm()
            assert r["response"] == solo["response"]
            costs[warm] = _ctr(
                engine, "dli_recovery_tokens_recomputed_total"
            ) - base
        finally:
            faults.disarm()
            cont.close()
    assert costs[True] < costs[False], costs


def test_warm_recovery_int8_pool():
    """The shadow rides the pool's pytree structure, so an int8 pool's
    KVQuant leaves (int8 blocks + float scales, different ranks) gather,
    persist, and restore through the same code — warm recovery stays
    bit-exact with KV quantization on."""
    cfg = get_model_config("test-llama-tiny", kv_quant="int8")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8
        ),
    )
    cont = _cont(eng, warm=True)
    try:
        r0 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r0["status"] == "success"
        assert cont._shadow.flush(10.0)
        base = _ctr(eng, "dli_recovery_tokens_recomputed_total")
        faults.arm([
            faults.FaultRule("decode_launch", "transient", on_call=4)
        ])
        r1 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        assert r1["status"] == "success", r1
        assert r1["response"] == r0["response"]
        assert cont.restarts_total == 1
        assert cont.shadow_restored_total > 0
        rec = _ctr(eng, "dli_recovery_tokens_recomputed_total") - base
        assert 0 < rec < BS, rec
    finally:
        faults.disarm()
        cont.close()


# -- drain persist / --restore-dir warm start --------------------------------

def test_drain_persists_and_restore_dir_warms_successor(engine, solo,
                                                        tmp_path):
    """The rolling-restart handoff: drain serializes the shadow (blocks
    + chain metadata) to --restore-dir; a successor engine restores it
    into its fresh pool before serving, so the old prompt set hits the
    block-prefix cache immediately — and greedy output is bit-identical
    across the drain->respawn boundary."""
    d = str(tmp_path / "restore")
    cont1 = _cont(engine, warm=True, restore_dir=d)
    try:
        r0 = cont1.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r0["response"] == solo["response"]
        assert cont1._shadow.flush(10.0)
        assert cont1.drain(deadline_s=30.0) is True
    finally:
        cont1.close()
    cont2 = _cont(engine, warm=True, restore_dir=d)
    try:
        # the worker thread restores before serving; poll briefly
        t0 = time.time()
        while cont2.shadow_restored_total == 0 and time.time() - t0 < 10:
            time.sleep(0.02)
        assert cont2.shadow_restored_total > 0
        r1 = cont2.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r1["status"] == "success"
        assert r1["response"] == solo["response"]
        # warm prefix cache: the mapped head covers every full prompt
        # block the predecessor shadowed
        assert r1.get("prefix_cached_tokens", 0) >= 2 * BS
        assert cont2.stats()["shadow"]["restored_blocks"] > 0
    finally:
        cont2.close()


def test_restore_dir_missing_or_invalid_starts_cold(engine, tmp_path):
    """A missing or corrupt persisted shadow is a cold start, never an
    error (warmth is an optimization)."""
    d = str(tmp_path / "nothing-here")
    cont = _cont(engine, warm=True, restore_dir=d)
    try:
        r = cont.submit(PROMPT, max_tokens=4, greedy=True, chat=False)
        assert r["status"] == "success"
        assert cont.shadow_restored_total == 0
    finally:
        cont.close()
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "shadow.npz").write_bytes(b"not an npz at all")
    cont = _cont(engine, warm=True, restore_dir=str(bad))
    try:
        r = cont.submit(PROMPT, max_tokens=4, greedy=True, chat=False)
        assert r["status"] == "success"
        assert cont.shadow_restored_total == 0
    finally:
        cont.close()


# -- shadow store units -------------------------------------------------------

def _mk_leaves(n, tag=0.0):
    """One fake stacked gather batch: two leaves of n blocks each."""
    return [
        np.full((n, 2, 3), tag, np.float32),
        np.full((n, 2), tag, np.float32),
    ]


def _put_sync(store, keys, tag=0.0, seq=0):
    assert store.put_async(keys, _mk_leaves(len(keys), tag), seq)
    assert store.flush(5.0)


def test_shadow_store_chains_and_select():
    s = ShadowStore(2, max_blocks=16)
    try:
        k1 = (1, 2)
        k2 = (1, 2, 3, 4)
        k3 = (9, 9)
        _put_sync(s, [k1, k2, k3], tag=1.0)
        assert s.has(k1) and s.has(k2) and s.has(k3)
        assert not s.has((5, 5))
        entries, leaves = s.select(10)
        assert [k for k, _ in entries] == sorted(
            [k1, k3, k2], key=len
        ) or len(entries) == 3
        assert set(leaves) == {k2, k3}
        # budget too small for the deep chain: the shorter chain still fits
        entries, leaves = s.select(1)
        assert len(entries) == 1
    finally:
        s.close()


def test_shadow_store_lru_cascade_eviction():
    s = ShadowStore(2, max_blocks=2)
    try:
        _put_sync(s, [(1, 2)])
        _put_sync(s, [(1, 2, 3, 4)])
        # inserting a new root evicts the LRU root (1,2) — and its child
        # cascades with it (a chain with a hole can never restore)
        _put_sync(s, [(7, 8)])
        assert s.has((7, 8))
        assert not s.has((1, 2)) and not s.has((1, 2, 3, 4))
        assert s.stats()["evicted"] >= 2
    finally:
        s.close()


def test_shadow_store_backpressure_drops_never_blocks():
    class _Slow:
        def __init__(self, arr):
            self._a = arr

        def __array__(self, dtype=None):
            time.sleep(0.3)
            return np.asarray(self._a, dtype=dtype)

    s = ShadowStore(2, max_blocks=16, max_pending=1)
    try:
        slow = [_Slow(leaf) for leaf in _mk_leaves(1)]
        assert s.put_async([(1, 1)], slow, 0)  # copier busy for 0.3s+
        t0 = time.time()
        while s._q and time.time() - t0 < 5:  # wait for the copier to
            time.sleep(0.005)  # pop the slow batch (now mid-transfer)
        t0 = time.time()
        s.put_async([(2, 2)], _mk_leaves(1), 0)  # queued (len 1)
        ok3 = s.put_async([(3, 3)], _mk_leaves(1), 0)  # full -> dropped
        assert time.time() - t0 < 0.25  # never blocked on the copier
        assert ok3 is False
        assert s.flush(10.0)
        assert s.stats()["dropped"] >= 1
        assert s.has((1, 1)) and s.has((2, 2)) and not s.has((3, 3))
    finally:
        s.close()


def test_shadow_store_save_load_round_trip(tmp_path):
    s = ShadowStore(2, max_blocks=16)
    try:
        _put_sync(s, [(1, 2), (1, 2, 3, 4), (9, 9)], tag=7.0, seq=42)
        assert s.save(str(tmp_path)) == 3
    finally:
        s.close()
    t = ShadowStore(2, max_blocks=16)
    try:
        assert t.load(str(tmp_path)) == 3
        assert t.has((1, 2, 3, 4)) and t.has((9, 9))
        entries, _ = t.select(10)
        data = dict(entries)
        np.testing.assert_array_equal(
            data[(1, 2)].leaves[0], np.full((2, 3), 7.0, np.float32)
        )
        assert data[(1, 2)].seq == 42
    finally:
        t.close()
    # wrong block size: refused, cold start
    u = ShadowStore(4, max_blocks=16)
    try:
        assert u.load(str(tmp_path)) == 0
    finally:
        u.close()


# -- wedge-driven readiness (satellite: router ejection signal) --------------

def test_wedge_flips_ready_503_until_the_call_drains():
    """An abandoned deadline-overrun device call past --wedge-unready
    flips /ready to 503 (reason 'wedged') while /health stays 200 — the
    router's probes eject the replica, and readmit it once the wedged
    call drains. dli_engine_wedged tracks the abandoned-call count."""
    import dataclasses

    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    # compile BEFORE the deadline arms, or the warmup itself would
    # overrun it and leave its own abandoned-call entry
    eng.generate("warm", max_tokens=2, greedy=True, chat=False)
    eng.engine_cfg = dataclasses.replace(
        eng.engine_cfg, request_deadline_s=0.3
    )
    server = InferenceServer(
        eng, host="127.0.0.1", port=0, wedge_unready_s=0.2
    )
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        faults.arm([
            faults.FaultRule("solo", "transient", wedge_s=2.5, times=1)
        ])
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps(
                {"prompt": "wedge me", "max_tokens": 4, "chat": False}
            ).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=15) as r:
                body = json.loads(r.read())
                code = r.status
        except urllib.error.HTTPError as e:
            code, body = e.code, json.loads(e.read())
        assert code == 503 and body["error_type"] == "timeout", body
        assert eng.max_wedged_age() is not None
        time.sleep(0.25)  # age past the 0.2s wedge-unready threshold
        code, body, hdrs = _get(base, "/ready")
        assert code == 503 and body["reason"] == "wedged", body
        assert hdrs.get("Retry-After")
        code, body, _ = _get(base, "/health")
        assert code == 200 and body["ready"] is False
        assert body["ready_reason"] == "wedged"
        assert _ctr(eng, "dli_engine_wedged") == 1
        # the wedge drains (the sleep ends, the daemon thread exits):
        # readiness recovers without a restart
        t0 = time.time()
        while eng.max_wedged_age() is not None and time.time() - t0 < 10:
            time.sleep(0.05)
        code, body, _ = _get(base, "/ready")
        assert code == 200 and body["ready"] is True
        assert _ctr(eng, "dli_engine_wedged") == 0
    finally:
        faults.disarm()
        server.shutdown()


def test_wedge_unready_zero_disables():
    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(
        cfg,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), request_deadline_s=0.2
        ),
    )
    server = InferenceServer(
        eng, host="127.0.0.1", port=0, wedge_unready_s=0.0
    )
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with eng._wedged_lock:
            eng._wedged[object()] = {"what": "t", "since": time.monotonic()}
        time.sleep(0.05)
        code, body, _ = _get(base, "/ready")
        assert code == 200 and body["ready"] is True
    finally:
        with eng._wedged_lock:
            eng._wedged.clear()
        server.shutdown()


# -- pp warm-recovery seam (the shard_map shadow twins) -----------------------

needs_shard_map = pytest.mark.skipif(
    not hasattr(__import__("jax"), "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)


@needs_shard_map
@pytest.mark.slow
def test_pp_shadow_gather_restore_roundtrip(eight_devices):
    """The pipeline backend's layer-local shadow twins: restoring known
    block content into a pp=2-sharded pool and gathering it back is the
    identity — the seam that lets pp fleets recover WARM (the old
    follow-up: pp pools recovered cold)."""
    import jax.numpy as jnp

    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    eng = create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=4
        ),
    )
    be = eng.backend
    pool = be.init_paged_pool(9, BS)
    ids = jnp.asarray([3, 6, 2], jnp.int32)
    blocks = {
        k: jnp.asarray(
            np.random.RandomState(i).standard_normal(
                (3, v.shape[0]) + v.shape[2:]
            ),
            v.dtype,
        )
        for i, (k, v) in enumerate(pool.items())
    }
    pool = be.restore_shadow_blocks(pool, blocks, ids)
    back = be.gather_shadow_blocks(pool, ids)
    for k in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(back[k]), np.asarray(blocks[k])
        )


@needs_shard_map
@pytest.mark.slow
def test_pp_fleet_recovers_warm(eight_devices):
    """End to end on the pp=2 mesh: the continuous fleet's shadow is
    ENABLED (the backend now carries the twins), and a mid-decode crash
    recovers warm — only the partial tail block re-prefills, greedy
    output bit-identical."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    eng = create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=8
        ),
    )
    solo_pp = eng.generate(PROMPT, max_tokens=10, greedy=True, chat=False)
    cont = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, restart_backoff_s=0.01,
        kv_pool_blocks=POOL, kv_block_size=BS,
    )
    try:
        assert cont._shadow is not None  # the seam: pp shadows now
        r0 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        assert r0["response"] == solo_pp["response"]
        assert cont._shadow.flush(10.0)
        base = _ctr(eng, "dli_recovery_tokens_recomputed_total")
        faults.arm([
            faults.FaultRule("decode_launch", "transient", on_call=4)
        ])
        r1 = cont.submit(PROMPT, max_tokens=10, greedy=True, chat=False)
        faults.disarm()
        assert r1["status"] == "success", r1
        assert r1["response"] == solo_pp["response"]
        recomputed = _ctr(
            eng, "dli_recovery_tokens_recomputed_total"
        ) - base
        assert 0 < recomputed < BS, recomputed
        assert cont.shadow_restored_total > 0
    finally:
        faults.disarm()
        cont.close()
