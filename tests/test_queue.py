"""Bounded request queue + coalescing (serving/queue.py).

Round-1 review stretch goal: concurrent singles must coalesce into ragged
batched fleets instead of serializing on the engine lock, and a full queue
must shed load with a 429 instead of piling up threads.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest
import jax

from distributed_llm_inference_tpu import EngineConfig, create_engine
from distributed_llm_inference_tpu.engine.engine import (
    InferenceEngine, SingleDeviceBackend,
)
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.serving.queue import BatchingQueue


def _engine(**eng_kw):
    return create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(64,), **eng_kw),
    )


def _fire(queue, prompts, **kwargs):
    """Submit prompts concurrently; returns results in prompt order."""
    results = [None] * len(prompts)

    def run(i):
        results[i] = queue.submit(prompts[i], **kwargs)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    return results


@pytest.mark.slow
def test_concurrent_singles_coalesce():
    engine = _engine()
    queue = BatchingQueue(engine, max_queue=16, max_batch=8, max_wait_ms=100)
    try:
        prompts = [f"prompt number {i}" for i in range(4)]
        results = _fire(
            queue, prompts, max_tokens=4, greedy=True, chat=False
        )
        for i, r in enumerate(results):
            assert r["status"] == "success", r
            assert r["prompt"] == prompts[i]  # rows mapped back in order
        # at least one actual fleet formed out of the burst
        assert queue.coalesced_batches >= 1
        batched = [r for r in results if "batched_with" in r]
        assert len(batched) >= 2
    finally:
        queue.close()


@pytest.mark.slow
def test_coalesced_rows_match_solo_generation():
    """A coalesced row's text must equal the same prompt served alone
    (ragged batching is invisible — the engine equivalence bar)."""
    engine = _engine()
    queue = BatchingQueue(engine, max_queue=16, max_batch=4, max_wait_ms=100)
    try:
        prompts = ["alpha beta", "gamma delta epsilon zeta"]
        results = _fire(queue, prompts, max_tokens=5, greedy=True, chat=False)
        for p, r in zip(prompts, results):
            solo = engine.generate(p, max_tokens=5, greedy=True, chat=False)
            assert r["status"] == solo["status"] == "success"
            assert r["response"] == solo["response"], p
    finally:
        queue.close()


def test_full_queue_sheds_load():
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    class SlowBackend(SingleDeviceBackend):
        def prefill(self, *a, **kw):
            time.sleep(0.5)
            return super().prefill(*a, **kw)

    engine = InferenceEngine(
        cfg, backend=SlowBackend(cfg, params),
        engine_cfg=EngineConfig(prefill_buckets=(64,)),
    )
    queue = BatchingQueue(engine, max_queue=1, max_batch=1, max_wait_ms=0)
    try:
        results = _fire(
            queue, [f"p{i}" for i in range(6)], max_tokens=2, greedy=True,
            chat=False,
        )
        shed = [r for r in results if r.get("error_type") == "overloaded"]
        served = [r for r in results if r.get("status") == "success"]
        assert shed, "expected at least one overloaded envelope"
        assert served, "expected at least one served request"
        for r in shed:
            assert "queue full" in r["error"]
            # regression: overload sheds must carry the queue-depth-derived
            # Retry-After hint (only the drain path used to send one), so
            # client/router backoff is server-directed on overload too
            assert r["retry_after_s"] >= 1
    finally:
        queue.close()


@pytest.mark.slow
def test_seeded_requests_do_not_coalesce():
    engine = _engine()
    queue = BatchingQueue(engine, max_queue=16, max_batch=8, max_wait_ms=100)
    try:
        results = _fire(
            queue, ["one", "two", "three"], max_tokens=3, greedy=True,
            chat=False, seed=7,
        )
        assert all(r["status"] == "success" for r in results)
        assert queue.coalesced_batches == 0
        assert all("batched_with" not in r for r in results)
    finally:
        queue.close()


@pytest.mark.slow
def test_fleet_failure_falls_back_to_solo():
    """One bad request must not fail the innocents it coalesced with: on a
    whole-fleet failure every member retries solo (where e.g. chunked
    prefill can still serve an over-long prompt)."""
    engine = create_engine(
        "test-llama-tiny",
        engine_cfg=EngineConfig(prefill_buckets=(32,), max_seq_len=2048),
    )
    queue = BatchingQueue(engine, max_queue=16, max_batch=4, max_wait_ms=150)
    try:
        # ~90 tokens under the byte tokenizer: over the 32 bucket, so the
        # FLEET fails (_plan rejects), but solo chunked prefill serves it
        long_prompt = "words " * 15
        results = _fire(
            queue, [long_prompt, "short one"], max_tokens=3, greedy=True,
            chat=False,
        )
        assert all(r["status"] == "success" for r in results), results
    finally:
        queue.close()


@pytest.mark.slow
def test_client_batch_flows_through_queue():
    engine = _engine()
    queue = BatchingQueue(engine, max_queue=4, max_batch=4, max_wait_ms=0)
    try:
        r = queue.submit_batch(["a", "bb"], max_tokens=3, greedy=True, chat=False)
        assert r["status"] == "success" and r["batch_size"] == 2
    finally:
        queue.close()


@pytest.mark.slow
def test_queue_wait_counts_against_deadline():
    """--deadline bounds the WHOLE request wall clock: a request whose
    queue wait already blew the deadline gets a timeout envelope at
    dequeue instead of running minutes late."""
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    class SlowBackend(SingleDeviceBackend):
        def prefill(self, *a, **kw):
            time.sleep(1.0)
            return super().prefill(*a, **kw)

    engine = InferenceEngine(
        cfg, backend=SlowBackend(cfg, params),
        engine_cfg=EngineConfig(prefill_buckets=(64,), request_deadline_s=0.5),
    )
    queue = BatchingQueue(engine, max_queue=8, max_batch=1, max_wait_ms=0)
    try:
        results = _fire(
            queue, [f"p{i}" for i in range(4)], max_tokens=2, greedy=True,
            chat=False,
        )
        timeouts = [r for r in results if r.get("error_type") == "timeout"]
        assert timeouts, results
        queued_out = [r for r in timeouts if "while queued" in r["error"]]
        assert queued_out, timeouts  # at least one expired IN the queue
    finally:
        queue.close()


def test_max_batch_clamped_to_engine_limit():
    from distributed_llm_inference_tpu.engine.engine import BATCH_BUCKETS

    engine = _engine()
    queue = BatchingQueue(engine, max_queue=4, max_batch=999, max_wait_ms=0)
    try:
        assert queue.max_batch == BATCH_BUCKETS[-1]
    finally:
        queue.close()


@pytest.mark.slow
def test_queue_over_http_429():
    from distributed_llm_inference_tpu.serving.server import InferenceServer

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))

    class SlowBackend(SingleDeviceBackend):
        def prefill(self, *a, **kw):
            time.sleep(0.5)
            return super().prefill(*a, **kw)

    engine = InferenceEngine(
        cfg, backend=SlowBackend(cfg, params),
        engine_cfg=EngineConfig(prefill_buckets=(64,)),
    )
    queue = BatchingQueue(engine, max_queue=1, max_batch=1, max_wait_ms=0)
    server = InferenceServer(engine, host="127.0.0.1", port=0, queue=queue)
    server.start()
    try:
        codes = []
        retry_afters = []

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/generate",
                data=json.dumps({"prompt": "x", "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    codes.append(resp.status)
            except urllib.error.HTTPError as e:
                codes.append(e.code)
                if e.code == 429:
                    retry_afters.append(e.headers.get("Retry-After"))

        threads = [threading.Thread(target=post) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert 429 in codes, codes
        assert 200 in codes, codes
        # regression: the 429 must arrive with the queue-depth-derived
        # Retry-After header, not just the drain path's 503
        assert retry_afters and all(
            ra is not None and float(ra) >= 1 for ra in retry_afters
        ), retry_afters
    finally:
        server.shutdown()


@pytest.mark.slow
def test_coalesced_fleet_tolerates_server_kwargs():
    """Regression: the server sets logprobs/speculative/debug on every
    request; a coalesced fleet must drop the non-batch kwargs instead of
    crashing generate_batch with a TypeError — and logprobs=True requests
    must never coalesce (a fleet has no per-token logprob buffer)."""
    import threading

    from distributed_llm_inference_tpu import EngineConfig, get_model_config
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.serving.queue import BatchingQueue, _Pending

    cfg = get_model_config("test-llama-tiny")
    eng = InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))
    q = BatchingQueue(eng, max_queue=8, max_batch=4, max_wait_ms=60.0)
    try:
        kwargs = dict(
            max_tokens=5, temperature=0.7, top_k=50, top_p=0.9,
            greedy=True, chat=False, seed=None, min_p=0.0,
            repetition_penalty=1.0, debug=False, speculative=False,
            logprobs=False,
        )
        outs = []
        lock = threading.Lock()

        def run(p):
            r = q.submit(p, **dict(kwargs))
            with lock:
                outs.append(r)

        threads = [
            threading.Thread(target=run, args=(f"fleet {i}",)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert len(outs) == 3
        for r in outs:
            assert r["status"] == "success", r
        # logprobs=True never coalesces
        p = _Pending("x", dict(kwargs, logprobs=True))
        assert p.coalesce_key() is None
    finally:
        q.close()


def test_different_penalties_do_not_coalesce():
    """Requests with different frequency/presence penalties must land in
    different fleets — the knobs are fleet-shared scalars."""
    from distributed_llm_inference_tpu.serving.queue import _Pending

    a = _Pending("x", {"greedy": True, "frequency_penalty": 1.0})
    b = _Pending("y", {"greedy": True, "frequency_penalty": 0.5})
    c = _Pending("z", {"greedy": True, "frequency_penalty": 1.0})
    assert a.coalesce_key() != b.coalesce_key()
    assert a.coalesce_key() == c.coalesce_key()
