"""Block-paged KV cache (engine/paged.py) tests.

The bar: paged mode is a MEMORY strategy, not a semantics change — every
token stream must be bit-identical to the dense fleet's (greedy, fp32),
while fleet HBM becomes a function of the pool and admission backpressures
on pool exhaustion instead of over-allocating.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.engine import paged as P
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine

PROMPTS = [
    "the quick brown fox",
    "jumps over",
    "a lazy dog while the band plays on",
    "hello",
]


@pytest.fixture(
    scope="module", params=["test-llama-tiny", "test-gpt2-tiny"]
)
def solo_engine(request):
    # BOTH families: the paged pool rides the shared attn_hook seam
    # (gpt2's block routes through llama.default_attn_hook since round
    # 5), so every fleet-level test here runs against each
    cfg = get_model_config(request.param)
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )


def _submit_all(cont, prompts, **kw):
    out = [None] * len(prompts)

    def run(i):
        out[i] = cont.submit(prompts[i], greedy=True, chat=False, **kw)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def test_allocator():
    a = P.BlockAllocator(8)  # 7 usable (block 0 is trash)
    assert a.free_blocks == 7
    ids = a.alloc(5)
    assert len(ids) == 5 and 0 not in ids
    assert a.alloc(3) is None  # only 2 left
    more = a.alloc(2)
    assert a.free_blocks == 0
    a.free(ids)
    assert a.free_blocks == 5
    assert sorted(a.alloc(5)) == sorted(ids)
    a.free(more)
    with pytest.raises(ValueError):
        P.BlockAllocator(1)


def test_blocks_needed():
    assert P.blocks_needed(8, 8, 16) == 1
    assert P.blocks_needed(9, 8, 16) == 2
    assert P.blocks_needed(16, 16, 16) == 2
    assert P.blocks_needed(1, 1, 16) == 1


@pytest.mark.slow
def test_decode_slots_paged_matches_dense(solo_engine):
    """Device-level: one occupied slot decoding over the block pool emits
    the exact stream the dense fleet emits from the same prefill."""
    eng = solo_engine
    cfg = eng.cfg
    backend = eng.backend
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)
    tokens = jnp.asarray(
        [[cfg.bos_token_id, 11, 12, 13, 14, 15, 16, 17]], jnp.int32
    )
    tokens = jnp.pad(tokens, ((0, 0), (0, 24)), constant_values=cfg.pad_token_id)
    plen, n_slots, steps = jnp.int32(8), 4, 12
    bs = 8
    MB = 4  # logical window 32
    knobs = (
        jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0), True,
        jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((cfg.vocab_size,), bool),
    )

    # dense fleet
    scratch = backend.init_cache(1, MB * bs)
    first, _, scratch = backend.prefill(tokens, plen, scratch, key, sampling)
    state, sparams = G.init_slots(n_slots, cfg.vocab_size)
    cache = backend.init_cache(n_slots, MB * bs)
    cache, state, sparams = G.insert_slot(
        cfg, cache, scratch, state, sparams, 1, first[0], plen,
        jnp.int32(steps + 1), *knobs,
    )
    em_d, mask_d, state_d, _ = G.decode_slots(
        cfg, backend.params, state, cache, jax.random.PRNGKey(3), sparams,
        num_steps=steps,
    )

    # paged pool: same scratch content, scattered into blocks
    scratch2 = backend.init_cache(1, MB * bs)
    first2, _, scratch2 = backend.prefill(tokens, plen, scratch2, key, sampling)
    pool = backend.init_paged_pool(2 * MB + 1, bs)
    # non-trivial physical placement: out-of-order block ids
    table = np.zeros((n_slots, MB), np.int32)
    row = np.asarray([5, 2, 7, 3], np.int32)
    table[1] = row
    state2, sparams2 = G.init_slots(n_slots, cfg.vocab_size)
    pool, state2, sparams2 = backend.insert_slot_paged(
        pool, scratch2, state2, sparams2, 1, jnp.asarray(row),
        first2[0], plen, jnp.int32(steps + 1), *knobs,
    )
    em_p, mask_p, state_p, _ = backend.decode_slots_paged(
        state2, pool, jnp.asarray(table), jax.random.PRNGKey(3), sparams2,
        num_steps=steps,
    )

    assert int(first[0]) == int(first2[0])
    np.testing.assert_array_equal(np.asarray(mask_d), np.asarray(mask_p))
    np.testing.assert_array_equal(
        np.asarray(em_d)[np.asarray(mask_d)], np.asarray(em_p)[np.asarray(mask_p)]
    )


@pytest.mark.slow
def test_paged_engine_matches_dense_engine(solo_engine):
    """End-to-end: the same request mix through a paged fleet and a dense
    fleet produces identical greedy text."""
    dense = ContinuousEngine(
        solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=96
    )
    try:
        want = [
            dense.submit(p, greedy=True, chat=False, max_tokens=12)
            for p in PROMPTS
        ]
    finally:
        dense.close()
    paged = ContinuousEngine(
        solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=96,
        kv_pool_blocks=16, kv_block_size=16,
    )
    try:
        got = _submit_all(paged, PROMPTS, max_tokens=12)
        stats = paged.stats()
    finally:
        paged.close()
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]
        assert g["tokens_generated"] == w["tokens_generated"]
    assert stats["paged"]["pool_blocks"] == 16
    # all blocks returned after completion
    assert stats["paged"]["free_blocks"] == 15


@pytest.mark.slow
def test_pool_backpressure_and_reuse(solo_engine):
    """A pool too small for all requests at once still serves every one:
    admission waits for released blocks (no failure, no deadlock), and
    freed blocks are reused across tenants with correct output."""
    # slot class 96 tokens -> 6 blocks/slot max; pool of 8 usable blocks
    # cannot hold two worst-case tenants at once
    cont = ContinuousEngine(
        solo_engine, n_slots=4, chunk_steps=4, slot_max_seq=96,
        kv_pool_blocks=9, kv_block_size=16,
    )
    try:
        solo = [
            solo_engine.generate(p, greedy=True, chat=False, max_tokens=40)
            for p in PROMPTS
        ]
        got = _submit_all(cont, PROMPTS, max_tokens=40)
        stats = cont.stats()
    finally:
        cont.close()
    for w, g in zip(solo, got):
        assert g["status"] == "success"
        assert g["response"] == w["response"]
    assert stats["paged"]["free_blocks"] == 8


@pytest.mark.slow
def test_request_exceeding_slot_class_rejected(solo_engine):
    cont = ContinuousEngine(
        solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=64,
        kv_pool_blocks=16, kv_block_size=16,
    )
    try:
        out = cont.submit(
            " ".join(f"w{i}" for i in range(80)), greedy=True, chat=False,
            max_tokens=8,
        )
    finally:
        cont.close()
    assert out["status"] == "failed"
    assert out["error_type"] == "invalid_request"


@pytest.mark.slow
def test_paged_requires_capable_backend(solo_engine):
    with pytest.raises(ValueError, match="full slot-class"):
        ContinuousEngine(
            solo_engine, n_slots=2, chunk_steps=4, slot_max_seq=96,
            kv_pool_blocks=4, kv_block_size=16,  # < 6 blocks + trash
        )


# ---------------------------------------------------------------------------
# Pallas paged-attention kernel (ops/paged_attention.py)


def _gather_attend(q, pool_k, pool_v, table, pos, window=None):
    """The hook's XLA gather path, stand-alone: the kernel's reference."""
    from distributed_llm_inference_tpu.ops.attention import (
        attend, slot_causal_mask,
    )

    B, _, H, Dh = q.shape
    KV, bs = pool_k.shape[1], pool_k.shape[2]
    MB = table.shape[1]
    gk = pool_k[table].transpose(0, 2, 1, 3, 4).reshape(B, KV, MB * bs, Dh)
    gv = pool_v[table].transpose(0, 2, 1, 3, 4).reshape(B, KV, MB * bs, Dh)
    mask = slot_causal_mask(pos, 1, MB * bs, window)
    return attend(q, gk, gv, mask)


@pytest.mark.parametrize("window", [None, 21])
@pytest.mark.slow
def test_paged_kernel_matches_gather(window):
    """Kernel-level: paged_flash_attend == gather+attend on a scattered
    out-of-order table, per-row positions, GQA grouping."""
    from distributed_llm_inference_tpu.ops.paged_attention import (
        paged_flash_attend,
    )

    B, H, KV, Dh, bs, MB, N = 3, 8, 2, 16, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, 1, H, Dh), jnp.float32)
    pool_k = jax.random.normal(ks[1], (N, KV, bs, Dh), jnp.float32)
    pool_v = jax.random.normal(ks[2], (N, KV, bs, Dh), jnp.float32)
    # out-of-order physical placement, trash-block tails (block 0)
    table = jnp.asarray(
        [[5, 2, 7, 0], [1, 9, 0, 0], [11, 4, 6, 3]], jnp.int32
    )
    # rows mid-block, at a block edge, and at the last logical position
    pos = jnp.asarray([11, 7, MB * bs - 1], jnp.int32)
    got = paged_flash_attend(
        q, pool_k, pool_v, table, pos, window=window, interpret=True
    )
    want = _gather_attend(q, pool_k, pool_v, table, pos, window=window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_paged_kernel_token_parity(solo_engine):
    """Engine-level: a paged decode with attn_impl='pallas' emits the
    exact token stream the XLA gather path emits (greedy, same params)."""
    eng_x = solo_engine
    cfg_p = eng_x.cfg.replace(attn_impl="pallas")
    eng_p = InferenceEngine(
        cfg_p, params=eng_x.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)
    tokens = jnp.asarray(
        [[eng_x.cfg.bos_token_id, 21, 22, 23, 24, 25]], jnp.int32
    )
    tokens = jnp.pad(tokens, ((0, 0), (0, 26)),
                     constant_values=eng_x.cfg.pad_token_id)
    plen, n_slots, steps, bs, MB = jnp.int32(6), 2, 10, 8, 4
    knobs = (
        jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0), True,
        jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((eng_x.cfg.vocab_size,), bool),
    )
    table = np.zeros((n_slots, MB), np.int32)
    table[1] = np.asarray([3, 6, 2, 5], np.int32)
    streams = []
    for eng in (eng_x, eng_p):
        be = eng.backend
        scratch = be.init_cache(1, MB * bs)
        first, _, scratch = be.prefill(tokens, plen, scratch, key, sampling)
        state, sparams = G.init_slots(n_slots, eng.cfg.vocab_size)
        pool = be.init_paged_pool(2 * MB + 1, bs)
        pool, state, sparams = be.insert_slot_paged(
            pool, scratch, state, sparams, 1, jnp.asarray(table[1]),
            first[0], plen, jnp.int32(steps + 1), *knobs,
        )
        em, mask, _, _ = be.decode_slots_paged(
            state, pool, jnp.asarray(table), jax.random.PRNGKey(3),
            sparams, num_steps=steps,
        )
        streams.append(np.asarray(em)[np.asarray(mask)])
    np.testing.assert_array_equal(streams[0], streams[1])


@pytest.mark.parametrize("window", [None, 13])
def test_slots_kernel_matches_attend(window):
    """flash_attend_slots == attend over the dense fleet cache with
    per-row positions (slot_causal_mask semantics), ragged final tile."""
    from distributed_llm_inference_tpu.ops.attention import (
        attend, slot_causal_mask,
    )
    from distributed_llm_inference_tpu.ops.paged_attention import (
        flash_attend_slots,
    )

    B, H, KV, Dh, S = 3, 8, 2, 16, 44  # S deliberately not a tile multiple
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, 1, H, Dh), jnp.float32)
    ck = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    cv = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    pos = jnp.asarray([0, 17, S - 1], jnp.int32)
    got = flash_attend_slots(
        q, ck, cv, pos, block_k=16, window=window, interpret=True
    )
    want = attend(q, ck, cv, slot_causal_mask(pos, 1, S, window))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_slots_kernel_fleet_token_parity(solo_engine):
    """Engine-level: the dense continuous fleet under attn_impl='pallas'
    serves the exact greedy text the XLA fleet serves."""
    eng_x = solo_engine
    want = []
    cont = ContinuousEngine(eng_x, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        want = [
            cont.submit(p, greedy=True, chat=False, max_tokens=10)
            for p in PROMPTS
        ]
    finally:
        cont.close()
    eng_p = InferenceEngine(
        eng_x.cfg.replace(attn_impl="pallas"), params=eng_x.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    cont_p = ContinuousEngine(eng_p, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        got = _submit_all(cont_p, PROMPTS, max_tokens=10)
    finally:
        cont_p.close()
    for w, g in zip(want, got):
        assert g["status"] == "success"
        assert g["response"] == w["response"]


# ---------------------------------------------------------------------------
# Paged KV on the pp mesh (round-3 review #2): the flagship memory feature
# on the reference's flagship topology.


@pytest.mark.slow
def test_pp_decode_slots_paged_matches_dense(eight_devices):
    """Device-level on pp=2: a slot decoding over the layer-sharded block
    pool emits the exact stream the pp dense fleet emits from the same
    prefill — gated ring writes redirect ungated scatters to the trash
    block without corrupting any live block."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_backend

    cfg, backend = create_backend(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2)
    )
    sampling = G.default_sampling(greedy=True)
    key = jax.random.PRNGKey(7)
    tokens = jnp.asarray(
        [[cfg.bos_token_id, 11, 12, 13, 14, 15, 16, 17]], jnp.int32
    )
    tokens = jnp.pad(tokens, ((0, 0), (0, 24)), constant_values=cfg.pad_token_id)
    plen, n_slots, steps = jnp.int32(8), 4, 12
    bs, MB = 8, 4
    knobs = (
        jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0), True,
        jnp.float32(0.0), jnp.float32(1.0),
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((cfg.vocab_size,), bool),
    )

    assert backend.supports_paged

    # dense pp fleet
    scratch = backend.init_cache(1, MB * bs)
    first, _, scratch = backend.prefill(tokens, plen, scratch, key, sampling)
    state, sparams = G.init_slots(n_slots, cfg.vocab_size)
    cache = backend.init_cache(n_slots, MB * bs)
    cache, state, sparams = G.insert_slot(
        cfg, cache, scratch, state, sparams, 1, first[0], plen,
        jnp.int32(steps + 1), *knobs,
    )
    em_d, mask_d, _, _ = backend.decode_slots(
        state, cache, jax.random.PRNGKey(3), sparams, num_steps=steps
    )

    # paged pp pool: same scratch content scattered into out-of-order blocks
    scratch2 = backend.init_cache(1, MB * bs)
    first2, _, scratch2 = backend.prefill(tokens, plen, scratch2, key, sampling)
    pool = backend.init_paged_pool(2 * MB + 1, bs)
    table = np.zeros((n_slots, MB), np.int32)
    row = np.asarray([5, 2, 7, 3], np.int32)
    table[1] = row
    state2, sparams2 = G.init_slots(n_slots, cfg.vocab_size)
    pool, state2, sparams2 = backend.insert_slot_paged(
        pool, scratch2, state2, sparams2, 1, jnp.asarray(row),
        first2[0], plen, jnp.int32(steps + 1), *knobs,
    )
    em_p, mask_p, _, _ = backend.decode_slots_paged(
        state2, pool, jnp.asarray(table), jax.random.PRNGKey(3), sparams2,
        num_steps=steps,
    )

    assert int(first[0]) == int(first2[0])
    np.testing.assert_array_equal(np.asarray(mask_d), np.asarray(mask_p))
    np.testing.assert_array_equal(
        np.asarray(em_d)[np.asarray(mask_d)],
        np.asarray(em_p)[np.asarray(mask_p)],
    )


@pytest.mark.slow
def test_pp_paged_engine_matches_dense_engine(eight_devices):
    """End-to-end on pp=2: the same request mix through a paged continuous
    fleet and a dense one on the pipeline mesh produces identical greedy
    text, and the pool returns every block afterwards."""
    from distributed_llm_inference_tpu import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    eng = create_engine(
        "test-llama-tiny", mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    dense = ContinuousEngine(eng, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        want = [
            dense.submit(p, greedy=True, chat=False, max_tokens=12)
            for p in PROMPTS
        ]
    finally:
        dense.close()
    paged = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=96,
        kv_pool_blocks=16, kv_block_size=16,
    )
    try:
        got = _submit_all(paged, PROMPTS, max_tokens=12)
        stats = paged.stats()
    finally:
        paged.close()
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]
    assert stats["paged"]["free_blocks"] == 15


@pytest.mark.slow
def test_pp_paged_uneven_layer_split(eight_devices):
    """pp=3 over 4 layers (uneven: padded layer slots) with an int8 pool:
    paged + kv_quant + pp + layer padding all compose — identical greedy
    text to the dense int8 pp fleet."""
    from distributed_llm_inference_tpu import MeshConfig, get_model_config
    from distributed_llm_inference_tpu.runtime import create_engine

    cfg = get_model_config("test-llama-tiny", kv_quant="int8")
    eng = create_engine(
        cfg, mesh_cfg=MeshConfig(pp=3),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    dense = ContinuousEngine(eng, n_slots=2, chunk_steps=4, slot_max_seq=64)
    try:
        want = [
            dense.submit(p, greedy=True, chat=False, max_tokens=8)
            for p in PROMPTS[:2]
        ]
    finally:
        dense.close()
    paged = ContinuousEngine(
        eng, n_slots=2, chunk_steps=4, slot_max_seq=64,
        kv_pool_blocks=12, kv_block_size=16,
    )
    try:
        got = _submit_all(paged, PROMPTS[:2], max_tokens=8)
    finally:
        paged.close()
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]


@pytest.mark.parametrize("window", [None, 21])
@pytest.mark.slow
def test_paged_kernel_dequantizes_int8_pool(window):
    """Kernel-level: paged_flash_attend over KVQuant pool leaves == the
    gather path over the dequantized pool — the table walk streams int8
    and dequantizes per block in the prologue."""
    from distributed_llm_inference_tpu.ops.kv_quant import (
        KVQuant, dequantize, quantize_chunk,
    )
    from distributed_llm_inference_tpu.ops.paged_attention import (
        paged_flash_attend,
    )

    B, H, KV, Dh, bs, MB, N = 3, 8, 2, 16, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 4)
    q = jax.random.normal(ks[0], (B, 1, H, Dh), jnp.float32)
    raw_k = jax.random.normal(ks[1], (N, KV, bs, Dh), jnp.float32)
    raw_v = jax.random.normal(ks[2], (N, KV, bs, Dh), jnp.float32)
    # quantize_chunk scales over the trailing Dh axis given [..., T, KV, Dh];
    # pool layout is [N, KV, bs, Dh] -> per-(block, head, slot) scales
    qk, sk = quantize_chunk(raw_k.transpose(0, 2, 1, 3))
    qv, sv = quantize_chunk(raw_v.transpose(0, 2, 1, 3))
    pk = KVQuant(qk.transpose(0, 2, 1, 3), sk.transpose(0, 2, 1))
    pv = KVQuant(qv.transpose(0, 2, 1, 3), sv.transpose(0, 2, 1))
    table = jnp.asarray(
        [[5, 2, 7, 0], [1, 9, 0, 0], [11, 4, 6, 3]], jnp.int32
    )
    pos = jnp.asarray([11, 7, MB * bs - 1], jnp.int32)
    got = paged_flash_attend(
        q, pk, pv, table, pos, window=window, interpret=True
    )
    want = _gather_attend(
        q, dequantize(pk), dequantize(pv), table, pos, window=window
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_paged_int8_pallas_token_parity(solo_engine):
    """Engine-level: an int8 paged fleet under attn_impl='pallas' (the
    dequantizing table-walk kernel) emits the exact token stream the int8
    gather path emits."""
    base = solo_engine.cfg.replace(kv_quant="int8")
    streams = []
    for impl in ("xla", "pallas"):
        eng = InferenceEngine(
            base.replace(attn_impl=impl), params=solo_engine.backend.params,
            engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        )
        cont = ContinuousEngine(
            eng, n_slots=2, chunk_steps=4, slot_max_seq=96,
            kv_pool_blocks=16, kv_block_size=16,
        )
        try:
            streams.append([
                cont.submit(p, greedy=True, chat=False, max_tokens=10)["response"]
                for p in PROMPTS
            ])
        finally:
            cont.close()
    assert streams[0] == streams[1]


@pytest.mark.slow
def test_paged_kernel_softcap_scale_window_dyn():
    """Round-5: the paged kernel covers score-scale overrides, Gemma-2
    softcapping, and a traced per-layer window (window_dyn) — each must
    match the gather + attend reference, and the dynamic-window spelling
    must match the static one."""
    from distributed_llm_inference_tpu.ops.attention import (
        attend, slot_causal_mask,
    )
    from distributed_llm_inference_tpu.ops.paged_attention import (
        paged_flash_attend,
    )

    B, H, KV, Dh, bs, MB, N = 3, 8, 2, 16, 8, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    q = jax.random.normal(ks[0], (B, 1, H, Dh), jnp.float32)
    pool_k = jax.random.normal(ks[1], (N, KV, bs, Dh), jnp.float32)
    pool_v = jax.random.normal(ks[2], (N, KV, bs, Dh), jnp.float32)
    table = jnp.asarray(
        [[5, 2, 7, 0], [1, 9, 0, 0], [11, 4, 6, 3]], jnp.int32
    )
    pos = jnp.asarray([11, 7, MB * bs - 1], jnp.int32)

    def gather_ref(window, scale, softcap):
        gk = pool_k[table].transpose(0, 2, 1, 3, 4).reshape(B, KV, MB * bs, Dh)
        gv = pool_v[table].transpose(0, 2, 1, 3, 4).reshape(B, KV, MB * bs, Dh)
        mask = slot_causal_mask(pos, 1, MB * bs, window)
        return attend(q, gk, gv, mask, scale=scale, softcap=softcap)

    for W, sc, cap in [(13, 0.3, None), (None, 0.25, 5.0), (13, None, 9.0)]:
        want = np.asarray(gather_ref(W, sc, cap))
        got = np.asarray(paged_flash_attend(
            q, pool_k, pool_v, table, pos, window=W, scale=sc, softcap=cap,
            interpret=True,
        ))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5,
                                   err_msg=str((W, sc, cap)))
        got_dyn = np.asarray(paged_flash_attend(
            q, pool_k, pool_v, table, pos, jnp.int32(W if W else -1),
            scale=sc, softcap=cap, interpret=True,
        ))
        np.testing.assert_allclose(got_dyn, want, rtol=2e-5, atol=2e-5,
                                   err_msg=str((W, sc, cap)))


@pytest.mark.slow
def test_paged_pallas_gemma2_fleet_parity():
    """Engine-level: a gemma-2-style model (softcap + query scaling +
    per-layer 'even' windows) through a paged fleet under
    attn_impl='pallas' emits exactly the XLA gather fleet's greedy text —
    the per-layer width rides the kernel's window_dyn operand."""
    cfg_x = get_model_config("test-gemma2-tiny", eos_token_id=-1).replace(
        attn_window=8
    )
    params = InferenceEngine(
        cfg_x, engine_cfg=EngineConfig(prefill_buckets=(32,))
    ).backend.params

    def run(cfg):
        eng = InferenceEngine(
            cfg, params=params, engine_cfg=EngineConfig(prefill_buckets=(32,))
        )
        cont = ContinuousEngine(
            eng, n_slots=2, chunk_steps=4, slot_max_seq=96,
            kv_pool_blocks=16, kv_block_size=16,
        )
        try:
            return [
                cont.submit(p, greedy=True, chat=False, max_tokens=10)
                for p in PROMPTS[:2]
            ]
        finally:
            cont.close()

    want = run(cfg_x)
    got = run(cfg_x.replace(attn_impl="pallas"))
    for w, g in zip(want, got):
        assert w["status"] == g["status"] == "success", (w, g)
        assert g["response"] == w["response"]
