"""The comms contract (analysis/comms.py + the four comms-* rules):
per-rule positive/negative/suppressed fixtures, symbolic-bytes units
against the known test-llama-tiny dims, the derived-table-vs-measured-
counter agreement on a real pp mesh, the derived-graph-vs-HLO round
trip, and the `--comms` CLI exit contract with a seeded raw-collective
fixture.

Selectable standalone: `pytest -m analysis`.
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import pytest

from distributed_llm_inference_tpu.analysis import comms, hlo
from distributed_llm_inference_tpu.analysis.callgraph import build_index
from distributed_llm_inference_tpu.analysis.lint import run_lint

pytestmark = pytest.mark.analysis

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "distributed_llm_inference_tpu",
)

needs_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax build has no jax.shard_map (pp backends unavailable)",
)


def make_pkg(tmp_path, files: dict) -> str:
    root = tmp_path / "fixture_pkg"
    for rel, body in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return str(root)


def lint(tmp_path, files, rules=None):
    return run_lint(make_pkg(tmp_path, files), rules=rules)


def rules_hit(diagnostics):
    return sorted({d.rule for d in diagnostics})


# -- comms-axis: axis names must resolve to declared mesh axes ---------------

def _axis_pkg(axis_expr):
    return {
        "parallel/mesh.py": """
            AXIS_PP = "pp"
            AXIS_SP = "sp"
        """,
        "parallel/handoff.py": f"""
            from jax import lax

            def hop(x, perm):
                return lax.ppermute(x, {axis_expr}, perm)
        """,
    }


def test_comms_axis_negative_literal(tmp_path):
    diags, _ = lint(tmp_path, _axis_pkg('"pp"'), rules=["comms-axis"])
    assert diags == []


def test_comms_axis_positive_typo(tmp_path):
    diags, _ = lint(tmp_path, _axis_pkg('"ppp"'), rules=["comms-axis"])
    assert len(diags) == 1
    d = diags[0]
    assert d.rule == "comms-axis"
    assert d.path.endswith("parallel/handoff.py")
    assert "'ppp'" in d.message and "pp" in d.message


def test_comms_axis_resolves_imported_constant(tmp_path):
    files = {
        "parallel/mesh.py": """
            AXIS_PP = "pp"
        """,
        "parallel/handoff.py": """
            from jax import lax
            from .mesh import AXIS_PP

            def hop(x, perm):
                return lax.ppermute(x, AXIS_PP, perm)
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-axis"])
    assert diags == []


def test_comms_axis_inert_without_declarations(tmp_path):
    # a bare fixture tree declares no AXIS_*: nothing to validate against
    files = {
        "parallel/handoff.py": """
            from jax import lax

            def hop(x, perm):
                return lax.ppermute(x, "anything", perm)
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-axis"])
    assert diags == []


def test_comms_axis_suppressed(tmp_path):
    files = _axis_pkg('"ppp"')
    files["parallel/handoff.py"] = """
        from jax import lax

        def hop(x, perm):
            # jaxlint: disable=comms-axis -- fixture: deliberate off-mesh axis
            return lax.ppermute(x, "ppp", perm)
    """
    diags, suppressed = lint(tmp_path, files, rules=["comms-axis"])
    assert diags == []
    assert suppressed == 1


# -- comms-wire-coverage: parallel/ transfers use the wrappers ---------------

RAW_HOP = {
    "parallel/handoff.py": """
        from jax import lax

        def hop(x, perm):
            return lax.ppermute(x, "pp", perm)
    """,
}


def test_wire_coverage_positive_raw_ppermute(tmp_path):
    diags, _ = lint(tmp_path, RAW_HOP, rules=["comms-wire-coverage"])
    assert len(diags) == 1
    d = diags[0]
    assert d.rule == "comms-wire-coverage"
    assert d.path.endswith("parallel/handoff.py")
    assert "wire_ppermute" in d.message


def test_wire_coverage_negative_wrapped(tmp_path):
    files = {
        "parallel/handoff.py": """
            from ..ops.wire_quant import wire_ppermute

            def hop(x, perm):
                return wire_ppermute(x, "pp", perm)
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-wire-coverage"])
    assert diags == []


def test_wire_coverage_negative_outside_parallel(tmp_path):
    # the contract governs the parallel/ transfer plane only
    files = {"engine/mod.py": RAW_HOP["parallel/handoff.py"]}
    diags, _ = lint(tmp_path, files, rules=["comms-wire-coverage"])
    assert diags == []


def test_wire_coverage_exempts_axis_size_and_merge(tmp_path):
    files = {
        "parallel/probe.py": """
            from jax import lax

            def probe(x):
                n = lax.psum(1, "pp")
                m = lax.pmax(x, "pp")
                return n, m
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-wire-coverage"])
    assert diags == []


def test_wire_coverage_suppressed(tmp_path):
    files = {
        "parallel/handoff.py": """
            from jax import lax

            def hop(x, perm):
                # jaxlint: disable=comms-wire-coverage -- fixture: control payload
                return lax.ppermute(x, "pp", perm)
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["comms-wire-coverage"])
    assert diags == []
    assert suppressed == 1


# -- comms-masked-psum: quantized psum operands carry the one-hot mask -------

def test_masked_psum_positive_bare_quantized(tmp_path):
    files = {
        "parallel/bc.py": """
            from jax import lax
            from ..ops.wire_quant import quantize_rows

            def bcast(x):
                q, s = quantize_rows(x)
                return lax.psum(q, "pp"), lax.psum(s, "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-masked-psum"])
    assert len(diags) == 2
    assert all(d.rule == "comms-masked-psum" for d in diags)
    assert "overflow" in diags[0].message


def test_masked_psum_positive_through_alias(tmp_path):
    files = {
        "parallel/bc.py": """
            from jax import lax
            from ..ops.wire_quant import quantize_rows

            def bcast(x):
                q, s = quantize_rows(x)
                w = q
                return lax.psum(w, "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-masked-psum"])
    assert len(diags) == 1


def test_masked_psum_negative_where_masked(tmp_path):
    files = {
        "parallel/bc.py": """
            import jax.numpy as jnp
            from jax import lax
            from ..ops.wire_quant import quantize_rows

            def bcast(x, sel):
                q, s = quantize_rows(x)
                return lax.psum(jnp.where(sel, q, jnp.zeros_like(q)), "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-masked-psum"])
    assert diags == []


def test_masked_psum_negative_unquantized(tmp_path):
    files = {
        "parallel/bc.py": """
            from jax import lax

            def bcast(x):
                return lax.psum(x, "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-masked-psum"])
    assert diags == []


def test_masked_psum_suppressed(tmp_path):
    files = {
        "parallel/bc.py": """
            from jax import lax
            from ..ops.wire_quant import quantize_rows

            def bcast(x):
                q, s = quantize_rows(x)
                # jaxlint: disable=comms-masked-psum -- fixture: single-owner by construction
                return lax.psum(q, "pp")
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["comms-masked-psum"])
    assert diags == []
    assert suppressed == 1


# -- comms-fat-collective: wide gathers are inventoried ----------------------

def test_fat_collective_positive_uninventoried_gather(tmp_path):
    files = {
        "parallel/gatherer.py": """
            from jax import lax

            def collect(x):
                return lax.all_gather(x, "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-fat-collective"])
    assert len(diags) == 1
    assert "FAT_INVENTORY" in diags[0].message


def test_fat_collective_negative_inventoried_site(tmp_path):
    # mirrors the real parallel/vocab.unembed_sharded site (module, func,
    # primitive, AND the `lg` operand all match the inventory entry)
    files = {
        "parallel/vocab.py": """
            from jax import lax

            def unembed_sharded(lg):
                return lax.all_gather(lg, "pp")
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-fat-collective"])
    assert diags == []


def test_fat_collective_stale_entry(tmp_path):
    # the inventory names parallel.vocab.unembed_sharded: a tree where
    # that module exists but the gather is gone must flag the stale entry
    files = {
        "parallel/vocab.py": """
            def unembed_sharded(lg):
                return lg
        """,
    }
    diags, _ = lint(tmp_path, files, rules=["comms-fat-collective"])
    assert len(diags) == 1
    assert "stale" in diags[0].message


def test_fat_collective_suppressed(tmp_path):
    files = {
        "parallel/gatherer.py": """
            from jax import lax

            def collect(x):
                # jaxlint: disable=comms-fat-collective -- fixture: int32 control vector
                return lax.all_gather(x, "pp")
        """,
    }
    diags, suppressed = lint(tmp_path, files, rules=["comms-fat-collective"])
    assert diags == []
    assert suppressed == 1


# -- symbolic bytes: units at the known test-llama-tiny dims -----------------

def test_wire_link_bytes_formula():
    # raw: every element at itemsize; quant: int8 data + one fp32 scale
    # per leading row — times hops
    assert comms.wire_link_bytes((2, 1, 64), 4, 8, quant=False) \
        == 2 * 64 * 4 * 8
    assert comms.wire_link_bytes((2, 1, 64), 4, 8, quant=True) \
        == (2 * 64 + 4 * 2) * 8


def test_wire_bytes_delegates_to_comms():
    from distributed_llm_inference_tpu.ops.wire_quant import wire_bytes

    for shape in [(1, 1, 64), (2, 24, 64), (2, 16, 2, 16)]:
        for quant in (False, True):
            assert wire_bytes(shape, 4, 3, quant=quant) \
                == comms.wire_link_bytes(shape, 4, 3, quant=quant)


def test_link_bytes_at_tiny_dims():
    from distributed_llm_inference_tpu import get_model_config

    cfg = get_model_config("test-llama-tiny")
    p = comms.params_from_config(
        cfg, dp=1, pp=2, sp=2, mb=2, rows=2, t=32, t_chunk=16,
        steps=4, draft=3, bh=1, b_m=1,
    )
    assert p["dim"] == 64 and p["vocab_size"] == 256
    assert p["n_layers"] == 4 and p["n_kv_heads"] == 2
    # decode ring: (2, 1, 64) x steps*pp = 8 hops
    assert comms.link_bytes(
        "pp-microstep-decode", p, itemsize=4, quant=False
    ) == 2 * 64 * 4 * 8
    assert comms.link_bytes(
        "pp-microstep-decode", p, itemsize=4, quant=True
    ) == (2 * 64 + 4 * 2) * 8
    # prefill: (2, 32, 64) x pp = 2 hops
    assert comms.link_bytes(
        "pp-microstep-prefill", p, itemsize=4, quant=False
    ) == 2 * 32 * 64 * 4 * 2
    # sp kv ring: (2, 16, 2, 16) x 2*n_layers*(sp-1) = 8 hops
    assert comms.link_bytes(
        "sp-kv-ring", p, itemsize=4, quant=False
    ) == 2 * 16 * 2 * 16 * 4 * 8
    # spec verify window: (2, 1+3, 64) x steps*pp = 8 hops
    assert comms.link_bytes(
        "pp-microstep-spec", p, itemsize=4, quant=False
    ) == 2 * 4 * 64 * 4 * 8


def test_fat_inventory_vocab_bytes_at_tiny_dims():
    from distributed_llm_inference_tpu import get_model_config

    cfg = get_model_config("test-llama-tiny")
    p = comms.params_from_config(cfg, pp=2, sp=2, rows=1, t=32, t_chunk=16)
    entry = next(
        e for e in comms.FAT_INVENTORY if e.module == "parallel.vocab"
    )
    # V=256 divides pp=2: 4 bytes * 1 row * 32 tok * 128 local cols * 1 hop
    assert entry.bytes_fn(p) == 4 * 1 * 32 * 128 * 1
    assert entry.bytes_fn(comms.REFERENCE_PARAMS) > comms.FAT_THRESHOLD


# -- the real package: census, table provenance, declared axes ---------------

@pytest.fixture(scope="module")
def repo_index():
    return build_index(PKG_ROOT)


def test_declared_axes_real_package(repo_index):
    assert {"dp", "pp", "sp", "tp", "ep"} <= set(
        comms.declared_axes(repo_index)
    )


def test_vocab_logits_gather_in_census(repo_index):
    sites = comms.collect_sites(repo_index)
    gathers = [
        s for s in sites
        if s.primitive == "all_gather" and s.module == "parallel.vocab"
    ]
    assert len(gathers) == 1
    g = gathers[0]
    assert g.axes == ("pp",)
    assert g.role == "raw"
    assert comms.fat_entry_for(g) is not None


def test_wrapper_sites_classified_not_raw(repo_index):
    sites = comms.collect_sites(repo_index)
    wq = [s for s in sites if s.module == "ops.wire_quant"]
    assert wq and all(s.role == "wrapper-internal" for s in wq)


def test_repo_report_clean_and_fully_routed(repo_index):
    report = comms.build_report(index=repo_index)
    assert report["problems"] == []
    for row in report["links"]:
        assert row["accounted_at"], (
            f"link {row['name']} has no _account_link provenance"
        )
    fat = {r["module"]: r for r in report["fat_inventory"]}
    v = fat["parallel.vocab"]
    assert v["sites"] and "parallel/vocab.py" in v["sites"][0]
    assert v["reference_bytes"] > comms.FAT_THRESHOLD


def test_repo_lint_clean_all_comms_rules():
    diags, _ = run_lint(PKG_ROOT, rules=[
        "comms-axis", "comms-wire-coverage", "comms-masked-psum",
        "comms-fat-collective",
    ])
    assert diags == [], "\n".join(d.format() for d in diags)


# -- derived bytes vs measured counters on a real pp mesh --------------------

@needs_shard_map
@pytest.mark.parametrize("wq", [None, "int8"])
def test_derived_bytes_match_measured_counters(wq):
    import jax.numpy as jnp
    import numpy as np

    from distributed_llm_inference_tpu import MeshConfig, get_model_config
    from distributed_llm_inference_tpu.engine import generate as G
    from distributed_llm_inference_tpu.runtime import create_backend
    from distributed_llm_inference_tpu.utils.metrics import MetricsRegistry

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for a pp mesh")
    cfg = get_model_config(
        "test-llama-tiny", dtype="float32", eos_token_id=-1
    )
    cfg, be = create_backend(cfg, mesh_cfg=MeshConfig(pp=2), wire_quant=wq)
    reg = MetricsRegistry()
    be.attach_wire_metrics(reg)
    B, PLEN, BUCKET, STEPS = 2, 12, 16, 4
    row = ([cfg.bos_token_id] + [7] * (PLEN - 1)
           + [cfg.pad_token_id] * (BUCKET - PLEN))
    tokens = jnp.asarray([row] * B, jnp.int32)
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(0))
    cache = be.init_cache(B, 64)
    first, _, cache = be.prefill(
        tokens, jnp.int32(PLEN), cache, kp, sampling
    )
    _, n_gen, cache = be.decode(
        first, cache, jnp.int32(PLEN), jnp.int32(STEPS), kd, sampling,
        max_steps=STEPS,
    )
    np.asarray(n_gen)
    fam = reg.get("dli_pp_wire_bytes_total")
    q = wq is not None
    p = comms.params_from_config(
        cfg, dp=1, pp=2, rows=B, t=BUCKET, steps=STEPS
    )
    assert int(fam.labels(path="microstep").value) == (
        comms.link_bytes("pp-microstep-prefill", p, itemsize=4, quant=q)
        + comms.link_bytes("pp-microstep-decode", p, itemsize=4, quant=q)
    )
    assert int(fam.labels(path="broadcast").value) == (
        comms.link_bytes("pp-broadcast-prefill", p, itemsize=4, quant=q)
        + comms.link_bytes("pp-broadcast-decode", p, itemsize=4, quant=q)
    )


# -- derived graph vs lowered HLO --------------------------------------------

def test_check_comms_graph_synthetic():
    # all three predicted pp edges present, nothing else: clean
    text = ('stablehlo.collective_permute stablehlo.all_reduce '
            '"stablehlo.all_gather"')
    assert hlo.check_comms_graph(text, "pp-decode") == []
    # an unpredicted collective kind must be flagged
    extra = hlo.check_comms_graph(
        text + " stablehlo.reduce_scatter", "pp-decode"
    )
    assert len(extra) == 1 and "unpredicted" in extra[0]
    # a missing predicted edge must be flagged
    missing = hlo.check_comms_graph("no collectives here", "pp-decode")
    assert len(missing) == 3 and all("stale" in m for m in missing)
    assert hlo.check_comms_graph("stablehlo.all_to_all", "sp-attend") == []


def test_collective_operand_parser():
    line = ('%3 = "stablehlo.all_to_all"(%2) <{split_count = 2}> : '
            '(tensor<1x4x2x16xi8>) -> tensor<1x8x1x16xi8>')
    ops = hlo._collective_operands(line, "all_to_all")
    assert len(ops) == 1
    rank, dtype, _ = ops[0]
    assert rank == 4 and dtype == "i8"
    # the attribute dict's replica_groups tensor has no paren wrapper and
    # must not parse as an operand
    attr_only = 'replica_groups = dense<[[0, 1]]> : tensor<1x2xi64>'
    assert hlo._collective_operands(attr_only, "tensor") == []


@needs_shard_map
def test_hlo_comms_graph_round_trip():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for a pp mesh")
    pp = hlo.lower_pp_decode()
    assert hlo.check_comms_graph(pp, "pp-decode") == []
    assert hlo.check_gather_dtype(pp) == []
    wired = hlo.lower_pp_decode(wire_quant="int8")
    assert hlo.check_comms_graph(wired, "pp-decode") == []
    assert hlo.check_gather_dtype(wired) == []


@needs_shard_map
def test_hlo_sp_attend_round_trip():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for an sp mesh")
    sp_off = hlo.lower_sp_attend(False)
    sp_on = hlo.lower_sp_attend(True)
    assert hlo.check_comms_graph(sp_off, "sp-attend") == []
    assert hlo.check_comms_graph(sp_on, "sp-attend") == []
    assert hlo.check_a2a_dtype(sp_off, wire=False) == []
    assert hlo.check_a2a_dtype(sp_on, wire=True) == []


# -- CLI exit contract -------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "distributed_llm_inference_tpu.analysis",
         *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(PKG_ROOT),
    )


def test_cli_comms_clean_repo_exits_zero():
    r = _run_cli("--comms")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "wire links" in r.stdout
    assert "fat-collective inventory" in r.stdout
    assert "accounted at" in r.stdout


def test_cli_comms_json_schema():
    r = _run_cli("--comms", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["problems"] == []
    assert data["diagnostics"] == []
    assert {l["name"] for l in data["links"]} == set(comms.WIRE_LINKS)
    assert all(l["accounted_at"] for l in data["links"])
    assert any(
        f["module"] == "parallel.vocab" for f in data["fat_inventory"]
    )


def test_cli_seeded_raw_collective_exits_nonzero(tmp_path):
    """The acceptance contract: a raw lax.ppermute seeded onto a
    parallel/ hand-off path fails the CLI with a file:line diagnostic
    naming comms-wire-coverage."""
    root = make_pkg(tmp_path, {
        "parallel/handoff.py": """
            from jax import lax

            def hop(x, perm):
                return lax.ppermute(x, "pp", perm)
        """,
    })
    r = _run_cli("--root", root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "comms-wire-coverage" in r.stdout
    assert "handoff.py:" in r.stdout


def test_cli_comms_flags_unrouted_link(tmp_path):
    """A table row with no _account_link call site is a problem the CLI
    exits nonzero on — the provenance half of the contract (a fixture
    tree has none of the real accounting seams)."""
    root = make_pkg(tmp_path, {
        "parallel/handoff.py": """
            def hop(x):
                return x
        """,
    })
    r = _run_cli("--root", root, "--comms")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no _account_link call site" in r.stdout
