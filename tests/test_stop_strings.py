"""Textual stop sequences ("stop": [...]): OpenAI-style truncation on the
solo, batched, and continuous paths — with EARLY slot termination in
continuous mode (the fleet stops decoding for a request whose stop string
already fired)."""

import threading
import time

import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine


@pytest.fixture(scope="module")
def eng():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64)))


def _pick_stop(engine, prompt, n=12):
    """Find a substring the model actually generates, to use as a stop."""
    full = engine.generate(prompt, max_tokens=n, greedy=True, chat=False)
    text = full["response"]
    assert len(text) >= 3, text
    mid = len(text) // 2
    return full, text[mid : mid + 2], text[:mid].find(text[mid : mid + 2])


def test_solo_stop_truncates(eng):
    full, stop_s, earlier = _pick_stop(eng, "stop solo prompt")
    r = eng.generate(
        "stop solo prompt", max_tokens=12, greedy=True, chat=False,
        stop=[stop_s],
    )
    assert r["status"] == "success"
    assert r["stopped"] is True
    assert stop_s not in r["response"]
    assert full["response"].startswith(r["response"])


def test_batched_stop_truncates(eng):
    full, stop_s, _ = _pick_stop(eng, "stop batch prompt")
    r = eng.generate_batch(
        ["stop batch prompt", "other prompt"], max_tokens=12, greedy=True,
        chat=False, stop=[stop_s],
    )
    assert r["status"] == "success"
    row = r["results"][0]
    assert row.get("stopped") is True
    assert stop_s not in row["response"]


def test_continuous_stop_frees_slot_early(eng):
    """A stop hit kills the slot at the chunk boundary: the request
    finishes well before its token budget and the fleet keeps serving."""
    full, stop_s, _ = _pick_stop(eng, "stop cont prompt")
    cont = ContinuousEngine(eng, n_slots=1, chunk_steps=2)
    try:
        r = cont.submit(
            "stop cont prompt", max_tokens=64, greedy=True, chat=False,
            stop=[stop_s],
        )
        assert r["status"] == "success", r
        assert r["stopped"] is True
        assert stop_s not in r["response"]
        # early termination: far fewer tokens than the 64 budget
        assert r["tokens_generated"] < 40
        r2 = cont.submit("after stop", max_tokens=3, greedy=True, chat=False)
        assert r2["status"] == "success"
    finally:
        cont.close()


def test_stream_never_crosses_stop(eng):
    full, stop_s, _ = _pick_stop(eng, "stop stream prompt")
    cont = ContinuousEngine(eng, n_slots=1, chunk_steps=2)
    try:
        events = list(
            cont.stream(
                "stop stream prompt", max_tokens=32, greedy=True, chat=False,
                stop=[stop_s],
            )
        )
        final = events[-1]
        assert final["status"] == "success" and final.get("stopped") is True
        joined = "".join(e["delta"] for e in events[:-1])
        assert joined == final["response"]
        assert stop_s not in joined
    finally:
        cont.close()


def test_no_stop_unchanged(eng):
    a = eng.generate("plain", max_tokens=6, greedy=True, chat=False)
    b = eng.generate("plain", max_tokens=6, greedy=True, chat=False, stop=[])
    assert a["response"] == b["response"]
    assert "stopped" not in b


def test_solo_early_stop_bounds_device_steps(eng):
    """Round-2 review weak #4: a stop hit at ~token 5 must not decode the
    full budget. The chunked path caps consumed steps at the next
    DECODE_BUCKETS[0] boundary, far below a large max_tokens."""
    from distributed_llm_inference_tpu.engine.engine import DECODE_BUCKETS

    full, stop_s, _ = _pick_stop(eng, "count my steps")
    calls = []
    real_decode = eng.backend.decode

    def counting_decode(first, cache, start_pos, limit, *a, **kw):
        calls.append(int(limit))
        return real_decode(first, cache, start_pos, limit, *a, **kw)

    eng.backend.decode = counting_decode
    try:
        r = eng.generate(
            "count my steps", max_tokens=400, greedy=True, chat=False,
            stop=[stop_s],
        )
    finally:
        eng.backend.decode = real_decode
    assert r["status"] == "success" and r["stopped"] is True
    consumed = sum(calls)
    # the stop fires within the first chunk or two; 400-token budget unused
    assert consumed <= 2 * DECODE_BUCKETS[0], (calls, r["response"])
    assert full["response"].startswith(r["response"])


def test_solo_stop_chunked_matches_single_call_greedy(eng):
    """Greedy chunked decode is bit-identical to the single-call path, so
    a stop that never fires yields the same text as no stop at all."""
    full = eng.generate("never stops here", max_tokens=10, greedy=True,
                        chat=False)
    r = eng.generate("never stops here", max_tokens=10, greedy=True,
                     chat=False, stop=["@@NO-SUCH@@"])
    assert r["response"] == full["response"]
    assert "stopped" not in r
