"""int8 KV-cache quantization (ops/kv_quant.py) tests.

The bar: kv_quant="int8" halves the cache's HBM bytes and stays a pure
cache-strategy swap — same engine surface, same request semantics; the
numerics are LOSSY (unlike the paged pool's bit-exactness) but bounded,
so logits stay close and the continuous fleet remains exactly
self-consistent with the solo quantized path (both write the same
quantized values).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inference_tpu import EngineConfig, get_model_config
from distributed_llm_inference_tpu.engine.continuous import ContinuousEngine
from distributed_llm_inference_tpu.engine.engine import InferenceEngine
from distributed_llm_inference_tpu.models import llama
from distributed_llm_inference_tpu.ops import kv_quant as KQ

PROMPTS = [
    "the quick brown fox",
    "jumps over a lazy dog",
    "hello world",
]


@pytest.fixture(scope="module")
def raw_engine():
    cfg = get_model_config("test-llama-tiny")
    return InferenceEngine(
        cfg, engine_cfg=EngineConfig(prefill_buckets=(32, 64))
    )


@pytest.fixture(scope="module")
def q_engine(raw_engine):
    cfg = raw_engine.cfg.replace(kv_quant="int8")
    return InferenceEngine(
        cfg, params=raw_engine.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )


@pytest.fixture(scope="module")
def dense_q_fleet_text(q_engine):
    """One dense int8-fleet baseline (greedy, max_tokens=10) shared by
    every parity test — the compile and generations are paid once."""
    cont = ContinuousEngine(q_engine, n_slots=2, chunk_steps=4,
                            slot_max_seq=96)
    try:
        return [
            cont.submit(p, greedy=True, chat=False, max_tokens=10)["response"]
            for p in PROMPTS
        ]
    finally:
        cont.close()


def test_quantize_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16), jnp.float32)
    q, s = KQ.quantize_chunk(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 5, 3)
    back = q.astype(jnp.float32) * s[..., None]
    # symmetric rounding error <= scale/2 = absmax/254 per element
    bound = np.asarray(jnp.max(jnp.abs(x), axis=-1) / 254.0)[..., None]
    assert np.all(np.abs(np.asarray(back - x)) <= bound + 1e-7)
    # all-zero rows quantize to exactly zero (scale floor, no NaN)
    qz, sz = KQ.quantize_chunk(jnp.zeros((1, 2, 2, 8)))
    assert np.all(np.asarray(qz) == 0) and np.all(np.isfinite(np.asarray(sz)))


def test_cache_memory_halved():
    cfg = get_model_config("test-llama-tiny", dtype="bfloat16")
    raw = llama.init_kv_cache(cfg, 4, max_seq=128)
    qcfg = cfg.replace(kv_quant="int8")
    quant = llama.init_kv_cache(qcfg, 4, max_seq=128)
    raw_b = sum(x.nbytes for x in jax.tree_util.tree_leaves(raw))
    q_b = sum(x.nbytes for x in jax.tree_util.tree_leaves(quant))
    # int8 data is half the bf16 bytes; the fp32 scales add 4 bytes per
    # Dh int8 bytes -> exact ratio 0.5 + 2/Dh (6% overhead at Dh=64,
    # 12.5% at this test model's Dh=16)
    assert q_b == raw_b * (0.5 + 2.0 / cfg.head_dim)
    assert isinstance(quant["k"], KQ.KVQuant)


def test_gated_write_is_noop():
    leaf = KQ.KVQuant(
        jnp.ones((1, 2, 8, 4), jnp.int8), jnp.ones((1, 2, 8), jnp.float32)
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 2, 4))
    out = KQ.update_cache(leaf, x, jnp.int32(3), gate=jnp.bool_(False))
    np.testing.assert_array_equal(np.asarray(out.q), np.asarray(leaf.q))
    np.testing.assert_array_equal(np.asarray(out.s), np.asarray(leaf.s))
    out2 = KQ.update_cache(leaf, x, jnp.int32(3), gate=jnp.bool_(True))
    assert not np.array_equal(np.asarray(out2.q), np.asarray(leaf.q))


@pytest.mark.slow
def test_solo_logits_close_and_generation_runs(raw_engine, q_engine):
    """Quantization error is bounded: greedy generation completes and the
    scored logprobs of the SAME continuation stay close to the raw
    engine's (scoring runs teacher-forced through the quantized cache)."""
    out_r = raw_engine.generate(
        PROMPTS[0], greedy=True, chat=False, max_tokens=8
    )
    out_q = q_engine.generate(
        PROMPTS[0], greedy=True, chat=False, max_tokens=8
    )
    assert out_q["status"] == "success"
    assert out_q["tokens_generated"] == out_r["tokens_generated"]
    s_r = raw_engine.score(PROMPTS[0] + " " + out_r["response"])
    s_q = q_engine.score(PROMPTS[0] + " " + out_r["response"])
    lp_r = np.asarray(s_r["token_logprobs"][1:], np.float64)
    lp_q = np.asarray(s_q["token_logprobs"][1:], np.float64)
    np.testing.assert_allclose(lp_q, lp_r, atol=0.15)


@pytest.mark.slow
def test_continuous_matches_solo_quantized(q_engine):
    """The quantized fleet is exactly self-consistent with the solo
    quantized path (same values written, same attention) — the dense
    fleet's parity property, unchanged by the cache strategy."""
    want = [
        q_engine.generate(p, greedy=True, chat=False, max_tokens=10)
        for p in PROMPTS
    ]
    cont = ContinuousEngine(q_engine, n_slots=2, chunk_steps=4,
                            slot_max_seq=96)
    try:
        got = [
            cont.submit(p, greedy=True, chat=False, max_tokens=10)
            for p in PROMPTS
        ]
    finally:
        cont.close()
    for w, g in zip(want, got):
        assert g["status"] == "success"
        assert g["response"] == w["response"]


@pytest.mark.slow
def test_kv_quant_rejects_illegal_combos(raw_engine):
    cfg = get_model_config("test-llama-tiny")
    with pytest.raises(ValueError, match="kv_quant"):
        cfg.replace(kv_quant="fp8")
    # gpt2 + kv_quant COMPOSES since round 5 (the shared attn_hook seam
    # covers both families) — the replace must succeed
    assert get_model_config(
        "test-gpt2-tiny"
    ).replace(kv_quant="int8").kv_quant == "int8"
    # kv_quant + pallas COMPOSES now (the flash kernel dequantizes int8
    # in its tile prologue) — the replace must succeed
    assert cfg.replace(kv_quant="int8", attn_impl="pallas").attn_impl == "pallas"
    # (kv_quant now composes with every topology, sp included — the ring
    # hooks quantize on write; see test_sp_ring_kv_quant_matches_solo)



@pytest.mark.slow
def test_pp_mesh_kv_quant_matches_single_device(raw_engine, eight_devices):
    """The pp pipeline serves kv_quant="int8" with the same greedy text as
    the single-device quantized engine (quantization is per-layer local,
    so stage placement cannot change the written values) — the
    one-topology-full-surface property extended to the cache strategy."""
    from distributed_llm_inference_tpu.parallel.mesh import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    qcfg = raw_engine.cfg.replace(kv_quant="int8")
    solo = InferenceEngine(
        qcfg, params=raw_engine.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    pp = create_engine(
        qcfg, mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=raw_engine.backend.params,
    )
    for prompt in PROMPTS[:2]:
        w = solo.generate(prompt, greedy=True, chat=False, max_tokens=10)
        g = pp.generate(prompt, greedy=True, chat=False, max_tokens=10)
        assert g["status"] == "success"
        assert g["response"] == w["response"]


@pytest.mark.slow
def test_kv_quant_1f1b_fleet_matches_single_device(raw_engine, eight_devices):
    """kv_quant composes with the microbatched 1F1B schedule now (round-3
    review #5b): _stage_apply slices the KVQuant leaves per microbatch and
    the cache specs distribute per leaf — a greedy int8 fleet on the
    zero-bubble schedule emits the same tokens as the single-device int8
    engine, row for row."""
    from distributed_llm_inference_tpu.parallel.mesh import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    qcfg = raw_engine.cfg.replace(kv_quant="int8")
    pp = create_engine(
        qcfg, mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=raw_engine.backend.params,
    )
    f1b = create_engine(
        qcfg, mesh_cfg=MeshConfig(pp=2), microbatches=2,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=raw_engine.backend.params,
    )
    assert f1b.backend.name == "pipeline-1f1b"
    kw = dict(greedy=True, chat=False, max_tokens=8)
    want = pp.generate_batch(PROMPTS[:4], **kw)
    got = f1b.generate_batch(PROMPTS[:4], **kw)
    assert got["status"] == want["status"] == "success"
    assert (
        [r["response"] for r in got["results"]]
        == [r["response"] for r in want["results"]]
    )


@pytest.mark.slow
def test_prefix_cache_hit_on_quantized_cache(raw_engine):
    """The prefix KV cache composes with kv_quant: snapshots slice the
    int8 data AND the scales (same seq axis), and a hit reproduces the
    cold quantized output exactly."""
    qcfg = raw_engine.cfg.replace(kv_quant="int8")
    eng = InferenceEngine(
        qcfg, params=raw_engine.backend.params,
        engine_cfg=EngineConfig(
            prefill_buckets=(32, 64), prefix_cache_entries=2,
            prefix_chunk=16,
        ),
    )
    # ~60 byte-tokens: fits the tiny model's 128-slot cache with headroom
    prompt = " ".join(f"w{i}" for i in range(18))
    cold = eng.generate(prompt, greedy=True, chat=False, max_tokens=8)
    assert cold["status"] == "success"
    hot = eng.generate(prompt, greedy=True, chat=False, max_tokens=8)
    assert hot["response"] == cold["response"]
    st = eng._prefix.stats()
    assert st["hits"] >= 1


@pytest.mark.slow
def test_paged_pool_composes_with_kv_quant(q_engine, dense_q_fleet_text):
    """Both HBM levers together: an int8 BLOCK POOL serves the same
    greedy text as the dense int8 fleet (identical quantized writes, so
    the parity is exact), and pool accounting still balances."""
    paged = ContinuousEngine(
        q_engine, n_slots=2, chunk_steps=4, slot_max_seq=96,
        kv_pool_blocks=16, kv_block_size=16,
    )
    try:
        got = [
            paged.submit(p, greedy=True, chat=False, max_tokens=10)
            for p in PROMPTS
        ]
        stats = paged.stats()
    finally:
        paged.close()
    for w, g in zip(dense_q_fleet_text, got):
        assert g["status"] == "success"
        assert g["response"] == w
    assert stats["paged"]["free_blocks"] == 15


@pytest.mark.slow
def test_pp_continuous_fleet_with_kv_quant(raw_engine, q_engine,
                                           dense_q_fleet_text,
                                           eight_devices):
    """Continuous batching on a pp mesh with an int8 cache: the fleet's
    shard_map programs take the quantized leaves through the per-leaf
    cache specs, and the served text matches the single-chip quantized
    fleet exactly."""
    from distributed_llm_inference_tpu.parallel.mesh import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    qcfg = q_engine.cfg
    pp = create_engine(
        qcfg, mesh_cfg=MeshConfig(pp=2),
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=raw_engine.backend.params,
    )
    cont_p = ContinuousEngine(pp, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        got = [
            cont_p.submit(p, greedy=True, chat=False, max_tokens=10)
            for p in PROMPTS
        ]
    finally:
        cont_p.close()
    for w, g in zip(dense_q_fleet_text, got):
        assert g["status"] == "success"
        assert g["response"] == w


def test_flash_kernel_dequantizes_int8_cache():
    """Kernel-level (round-3 review #5a): flash_attend over KVQuant leaves
    == attend over the dequantized cache — the dequant happens in the
    kernel's tile prologue, bit-comparable to the XLA dequant path at
    fp32 tolerance."""
    from distributed_llm_inference_tpu.ops.attention import attend
    from distributed_llm_inference_tpu.ops.flash_attention import flash_attend
    from distributed_llm_inference_tpu.ops.kv_quant import (
        dequantize, quantize_chunk, KVQuant,
    )
    from distributed_llm_inference_tpu.ops.attention import causal_mask

    B, T, H, KV, Dh, S, pos = 2, 5, 4, 2, 16, 32, 7
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    raw_k = jax.random.normal(ks[1], (B, KV, S, Dh), jnp.float32)
    raw_v = jax.random.normal(ks[2], (B, KV, S, Dh), jnp.float32)
    qk, sk = quantize_chunk(raw_k.transpose(0, 2, 1, 3))
    qv, sv = quantize_chunk(raw_v.transpose(0, 2, 1, 3))
    ck = KVQuant(qk.transpose(0, 2, 1, 3), sk.transpose(0, 2, 1))
    cv = KVQuant(qv.transpose(0, 2, 1, 3), sv.transpose(0, 2, 1))
    got = flash_attend(q, ck, cv, jnp.int32(pos), interpret=True)
    mask = causal_mask(jnp.int32(pos), T, S)
    want = attend(q, dequantize(ck), dequantize(cv), mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_pallas_prefill_with_kv_quant_token_parity(raw_engine):
    """Engine-level: attn_impl='pallas' + kv_quant='int8' serves the SAME
    greedy tokens as the XLA int8 path (the T>1 prefill chunks run the
    dequantizing flash kernel; T=1 decode keeps the XLA einsum)."""
    base = raw_engine.cfg.replace(kv_quant="int8")
    eng_x = InferenceEngine(
        base, params=raw_engine.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    eng_p = InferenceEngine(
        base.replace(attn_impl="pallas"), params=raw_engine.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    for prompt in PROMPTS[:2]:
        w = eng_x.generate(prompt, greedy=True, chat=False, max_tokens=8)
        g = eng_p.generate(prompt, greedy=True, chat=False, max_tokens=8)
        assert w["status"] == g["status"] == "success"
        assert g["response"] == w["response"]


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.slow
def test_sp_ring_kv_quant_matches_solo(raw_engine, eight_devices, strategy):
    """kv_quant composes with context parallelism now (the last kv_quant
    exclusion): the ring prefill stores quantized chunks and attends the
    dequantized round-trip — the SAME values the solo int8 path attends —
    and cp decode merges dequantized local partials. Greedy tokens match
    the solo int8 engine exactly on the test model."""
    from distributed_llm_inference_tpu.parallel.mesh import MeshConfig
    from distributed_llm_inference_tpu.runtime import create_engine

    qcfg = raw_engine.cfg.replace(kv_quant="int8")
    solo = InferenceEngine(
        qcfg, params=raw_engine.backend.params,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
    )
    sp = create_engine(
        qcfg, mesh_cfg=MeshConfig(sp=2), sp_strategy=strategy,
        engine_cfg=EngineConfig(prefill_buckets=(32, 64)),
        params=raw_engine.backend.params,
    )
    assert sp.backend.name == "context-parallel"
    for prompt in PROMPTS[:2]:
        w = solo.generate(prompt, greedy=True, chat=False, max_tokens=10)
        g = sp.generate(prompt, greedy=True, chat=False, max_tokens=10)
        assert g["status"] == "success"
        assert g["response"] == w["response"]


@pytest.mark.slow
def test_gpt2_kv_quant_decode_close_to_raw_cache():
    """Round-5: gpt2 rides the int8 KV cache through the SHARED attn_hook
    seam (config.py no longer gates kv_quant to llama). Numeric pin for
    the family-specific shapes (MHA group=1, both the solo kv_update path
    and the fleet kv_update_slots path): teacher-forced forward over a
    quantized cache stays close to the raw cache, and greedy decode + the
    continuous fleet both serve."""
    from distributed_llm_inference_tpu.engine.engine import InferenceEngine
    from distributed_llm_inference_tpu.models import api as M

    cfg = get_model_config("test-gpt2-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(3))
    qcfg = cfg.replace(kv_quant="int8")
    tokens = jnp.asarray([[5, 9, 13, 17, 21, 25]], jnp.int32)
    cache_r = M.init_kv_cache(cfg, 1, max_seq=32)
    cache_q = M.init_kv_cache(qcfg, 1, max_seq=32)
    assert isinstance(cache_q["k"], KQ.KVQuant)
    lr, _ = M.forward(cfg, params, tokens, cache_r, jnp.int32(0))
    lq, _ = M.forward(qcfg, params, tokens, cache_q, jnp.int32(0))
    pr = np.asarray(jax.nn.log_softmax(lr[0, -1]), np.float64)
    pq = np.asarray(jax.nn.log_softmax(lq[0, -1]), np.float64)
    np.testing.assert_allclose(pq, pr, atol=0.15)

    raw = InferenceEngine(cfg, params=params)
    quant = InferenceEngine(qcfg, params=params)
    out_r = raw.generate("a quick check", greedy=True, chat=False, max_tokens=8)
    out_q = quant.generate("a quick check", greedy=True, chat=False, max_tokens=8)
    assert out_q["status"] == "success"
    assert out_q["tokens_generated"] == out_r["tokens_generated"]
    # fleet path (kv_update_slots through the shared hook)
    cont = ContinuousEngine(quant, n_slots=2, chunk_steps=4, slot_max_seq=96)
    try:
        got = cont.submit("a quick check", greedy=True, chat=False, max_tokens=8)
    finally:
        cont.close()
    assert got["status"] == "success"
    assert got["response"] == out_q["response"]
