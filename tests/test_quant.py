"""Weight-only int8 quantization (ops/quant.py).

Beyond-parity TPU feature: batch-1 decode is HBM-bound, so int8 weights
halve bytes/token. Correctness bar: exact algebra (scaled int matmul ==
matmul of dequantized weights), bounded reconstruction error, and an
end-to-end engine run whose outputs stay close to full precision.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inference_tpu import EngineConfig, MeshConfig, create_engine
from distributed_llm_inference_tpu.engine import generate as G
from distributed_llm_inference_tpu.models import api as M
from distributed_llm_inference_tpu.models.registry import get_model_config
from distributed_llm_inference_tpu.ops.quant import (
    QTensor, dequantize_tensor, matmul, quantize_params, quantize_tensor,
)


def test_reconstruction_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    t = quantize_tensor(w)
    assert t.q.dtype == jnp.int8 and t.s.shape == (48,)
    back = dequantize_tensor(t)
    # round-to-nearest: |err| <= scale/2 per element
    bound = np.asarray(t.s) / 2 + 1e-7
    assert np.all(np.abs(np.asarray(back - w)) <= bound[None, :])


def test_matmul_matches_dequantized_reference():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((3, 16, 24)), jnp.float32)  # stacked
    x = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    t = quantize_tensor(w)
    got = matmul(x, QTensor(t.q[0], t.s[0]))
    want = x @ dequantize_tensor(QTensor(t.q[0], t.s[0]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_quantize_params_structure_and_scan():
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params)
    assert isinstance(qp["layers"]["wq"], QTensor)
    assert isinstance(qp["lm_head"], QTensor)
    assert not isinstance(qp["embed"], QTensor)  # gather path stays dense
    assert not isinstance(qp["layers"]["attn_norm"], QTensor)
    # idempotent
    qp2 = quantize_params(cfg, qp)
    assert qp2["layers"]["wq"] is qp["layers"]["wq"]

    # QTensor leaves slice correctly through the stacked-layer scan
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    tokens = jnp.asarray([[5, 9, 13]], jnp.int32)
    logits, _ = M.forward(cfg, qp, tokens, cache, jnp.int32(0))
    assert logits.shape == (1, 3, cfg.vocab_size)


@pytest.mark.slow
def test_quantized_logits_close_to_full_precision():
    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params)
    tokens = jnp.asarray([[5, 9, 13, 2, 7, 11]], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    full, _ = M.forward(cfg, params, tokens, cache, jnp.int32(0))
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    quant, _ = M.forward(cfg, qp, tokens, cache, jnp.int32(0))
    # int8 weight-only on a 4-layer model: logits track closely
    err = np.abs(np.asarray(full - quant))
    scale = np.abs(np.asarray(full)).max()
    assert err.max() / scale < 0.05, err.max() / scale


@pytest.mark.slow
def test_engine_end_to_end_with_quant():
    cfg = get_model_config("test-llama-tiny", quant="int8")
    engine = create_engine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = engine.generate("hello quant", max_tokens=5, greedy=True, chat=False)
    assert r["status"] == "success", r
    assert r["tokens_generated"] >= 1


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(dp=1, pp=2, tp=1), MeshConfig(dp=1, pp=2, tp=2)],
    ids=["pp2", "pp2tp2"],
)
@pytest.mark.slow
def test_quant_pipeline_matches_quant_single_device(mesh_cfg, eight_devices):
    """SPMD + quant: an int8 pp (x tp) mesh decodes bit-exactly what the
    int8 single-device backend decodes (same quantized weights; the
    collectives add nothing)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params)

    ids = [5, 9, 13, 21, 8]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, qp, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, qp, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    mesh = build_mesh(mesh_cfg, eight_devices)
    pb = PipelineBackend(cfg, qp, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
    assert int(n_p[0]) == int(n_s[0])
    # the int8 weight bytes (not a dequantized copy) are what sharded
    q = pb.layers["wq"].q
    assert q.dtype == jnp.int8
    assert q.sharding.shard_shape(q.shape)[0] == q.shape[0] // 2


@pytest.mark.parametrize("pp", [2, 3])  # 3: uneven split + zero-pad + quant
@pytest.mark.slow
def test_quant_engine_on_pipeline_mesh(pp, eight_devices):
    cfg = get_model_config("test-llama-tiny", quant="int8")
    engine = create_engine(
        cfg, mesh_cfg=MeshConfig(dp=1, pp=pp, tp=1),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = engine.generate("quant on a mesh", max_tokens=4, greedy=True, chat=False)
    assert r["status"] == "success", r


@pytest.mark.slow  # re-tiered round 5 (fast-tier budget)
@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_quant_gpt2_close_to_full_precision(mode):
    """Round-5: weight-only quantization covers gpt2 (projections route
    through the quant-aware mm; ops/quant._QUANT_KEYS carries the family's
    key set). Greedy decode through the quantized engine succeeds and the
    quantized logits stay close to full precision."""
    import jax.numpy as jnp

    from distributed_llm_inference_tpu.models import api as M
    from distributed_llm_inference_tpu.ops.quant import quantize_params

    cfg = get_model_config("test-gpt2-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params, mode=mode)
    tokens = jnp.asarray([[5, 9, 13, 17]], jnp.int32)
    cache_f = M.init_kv_cache(cfg, 1, max_seq=16)
    cache_q = M.init_kv_cache(cfg, 1, max_seq=16)
    lf, _ = M.forward(cfg, params, tokens, cache_f, jnp.int32(0))
    lq, _ = M.forward(cfg, qp, tokens, cache_q, jnp.int32(0))
    f = np.asarray(lf[0, -1]).astype(np.float64)
    qv = np.asarray(lq[0, -1]).astype(np.float64)
    cos = (f @ qv) / (np.linalg.norm(f) * np.linalg.norm(qv) + 1e-12)
    # int4 is the lossier scheme (packed nibbles, group scales) and the
    # random-init tiny model has near-noise logits, so its floor is looser
    assert cos > (0.98 if mode == "int8" else 0.93), (mode, cos)

    eng = create_engine(
        cfg.replace(quant=mode),
        engine_cfg=EngineConfig(prefill_buckets=(32,)),
    )
    r = eng.generate("a quick check", max_tokens=4, greedy=True, chat=False)
    assert r["status"] == "success"


# -- int4 (packed nibbles, group-wise scales) -------------------------------


def test_int4_pack_roundtrip():
    """Packing then unpacking recovers the exact int4 code values."""
    from distributed_llm_inference_tpu.ops.quant import (
        Q4Tensor, _unpack_int4, dequantize_tensor4, quantize_tensor4,
    )

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 24)), jnp.float32)
    t = quantize_tensor4(w, group=16)
    assert t.q.dtype == jnp.int8
    assert t.q.shape == (4, 8, 24)  # [G=64/16, g/2, out]
    assert t.s.shape == (4, 24)
    codes = np.asarray(_unpack_int4(t.q))
    assert codes.min() >= -7 and codes.max() <= 7
    # reconstruction: |err| <= scale/2 per element within each group
    back = np.asarray(dequantize_tensor4(t)).reshape(4, 16, 24)
    want = np.asarray(w).reshape(4, 16, 24)
    bound = np.asarray(t.s)[:, None, :] / 2 + 1e-7
    assert np.all(np.abs(back - want) <= bound)


def test_int4_matmul_matches_dequantized_reference():
    from distributed_llm_inference_tpu.ops.quant import (
        dequantize_tensor4, matmul, quantize_tensor4,
    )

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((32, 24)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 32)), jnp.float32)
    t = quantize_tensor4(w, group=8)
    got = matmul(x, t)
    want = x @ dequantize_tensor4(t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_int4_odd_group_falls_back_to_single_group():
    from distributed_llm_inference_tpu.ops.quant import quantize_tensor4

    w = jnp.ones((20, 8), jnp.float32)  # 20 % 64 != 0 -> one group of 20
    t = quantize_tensor4(w, group=64)
    assert t.g == 20 and t.q.shape == (1, 10, 8)


@pytest.mark.slow
def test_int4_params_forward_close_to_full_precision():
    from distributed_llm_inference_tpu.ops.quant import Q4Tensor

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params, mode="int4")
    assert isinstance(qp["layers"]["wq"], Q4Tensor)
    assert isinstance(qp["lm_head"], Q4Tensor)
    tokens = jnp.asarray([[5, 9, 13, 2, 7, 11]], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    full, _ = M.forward(cfg, params, tokens, cache, jnp.int32(0))
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    quant, _ = M.forward(cfg, qp, tokens, cache, jnp.int32(0))
    # group-wise int4 on RANDOM gaussian weights is quantization's worst
    # case (no outlier structure; a tiny random model's logits are near-
    # chaotic in its weights — measured ~0.23-0.35 rel err across group
    # sizes 8-64 here, where real checkpoints track far tighter). The
    # exactness of the int4 algebra itself is pinned by the
    # pack-roundtrip and matmul-vs-dequantized tests above; this test
    # only guards against gross wiring bugs (wrong scales, nibble-order
    # swaps blow the error to O(1) x logit scale).
    err = np.abs(np.asarray(full - quant))
    scale = np.abs(np.asarray(full)).max()
    assert err.max() / scale < 0.5, err.max() / scale


@pytest.mark.slow
def test_int4_engine_end_to_end():
    cfg = get_model_config("test-llama-tiny", quant="int4")
    engine = create_engine(cfg, engine_cfg=EngineConfig(prefill_buckets=(32,)))
    r = engine.generate("hello int4", max_tokens=5, greedy=True, chat=False)
    assert r["status"] == "success", r
    assert r["tokens_generated"] >= 1


@pytest.mark.slow
def test_int4_pipeline_matches_int4_single_device(eight_devices):
    """int4 on a pp=2 x tp=2 mesh decodes bit-exactly what int4 on one
    device decodes (Q4Tensor leaves shard: groups over tp-in, out over
    tp-out, layers over pp; vocab padding handles the packed head)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-llama-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    # group=16: dim 64 -> 4 groups, divisible by tp=2 (row shards move to
    # the group axis; real-model dims give dozens of groups at default 64)
    qp = quantize_params(cfg, params, mode="int4", group=16)

    ids = [5, 9, 13, 21, 8]
    bucket, steps = 16, 6
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, qp, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, qp, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=2), eight_devices)
    pb = PipelineBackend(cfg, qp, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    assert int(f_p[0]) == int(f_s[0])
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))


@pytest.mark.slow
def test_int4_pallas_kernel_matches_reference():
    """The Pallas VMEM-unpack kernel (decode hot path on TPU; interpret
    mode here) computes exactly x @ dequant(w) for kernel-eligible
    shapes, including R=1 (decode) and R=8 (slot fleet)."""
    from distributed_llm_inference_tpu.ops.quant import (
        dequantize_tensor4, q4_matmul_rows, quantize_tensor4,
    )

    rng = np.random.default_rng(7)
    w = jnp.asarray(rng.standard_normal((256, 384)), jnp.float32)
    t = quantize_tensor4(w, group=64)
    for R in (1, 3, 8):
        x = jnp.asarray(rng.standard_normal((R, 256)), jnp.float32)
        got = q4_matmul_rows(x, t, interpret=True)
        want = x @ dequantize_tensor4(t)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


# -- int8 MoE expert banks --------------------------------------------------


@pytest.mark.slow
def test_int8_moe_expert_banks_quantize_and_track():
    """MoE models quantize their expert banks too (per-(expert,
    out-channel) scales riding the moe_ffn einsums); logits stay close
    and the structure is QTensor end to end."""
    cfg = get_model_config("test-moe-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params, mode="int8")
    assert isinstance(qp["layers"]["w_gate"], QTensor)
    assert qp["layers"]["w_gate"].q.shape == (4, 4, 64, 96)
    assert qp["layers"]["w_gate"].s.shape == (4, 4, 96)
    assert not isinstance(qp["layers"]["w_router"], QTensor)  # tiny; dense

    tokens = jnp.asarray([[5, 9, 13, 2, 7]], jnp.int32)
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    full, _ = M.forward(cfg, params, tokens, cache, jnp.int32(0))
    cache = M.init_kv_cache(cfg, 1, max_seq=32)
    quant, _ = M.forward(cfg, qp, tokens, cache, jnp.int32(0))
    # random-weight MoE amplifies quantization error (an expert's shifted
    # output feeds a near-uniform random router downstream); the exact
    # algebra is pinned by the einsum-vs-dequant check below
    err = np.abs(np.asarray(full - quant))
    scale = np.abs(np.asarray(full)).max()
    assert err.max() / scale < 0.2, err.max() / scale
    # exactness of the scaled einsum itself (no quantization error in
    # the seam): expert_einsum(q) == einsum(dequant(q))
    from distributed_llm_inference_tpu.ops.quant import (
        dequantize_tensor, expert_einsum,
    )

    w = qp["layers"]["w_gate"]
    h = jnp.asarray(np.random.default_rng(1).standard_normal((1, 3, 64)),
                    jnp.float32)
    got = expert_einsum("btd,edf->btef", h, QTensor(w.q[0], w.s[0]))
    want = jnp.einsum(
        "btd,edf->btef", h, dequantize_tensor(QTensor(w.q[0], w.s[0]))
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_int8_moe_pipeline_ep_matches_single_device(eight_devices):
    """Quantized expert banks shard over pp x ep bit-exactly (QTensor
    scale specs follow the 4-D bank layout)."""
    from distributed_llm_inference_tpu.parallel.mesh import build_mesh
    from distributed_llm_inference_tpu.parallel.pipeline import PipelineBackend

    cfg = get_model_config("test-moe-tiny")
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_params(cfg, params, mode="int8")

    ids = [5, 9, 13, 21, 8]
    bucket, steps = 16, 5
    tokens = jnp.asarray([ids + [cfg.pad_token_id] * (bucket - len(ids))], jnp.int32)
    plen = jnp.int32(len(ids))
    sampling = G.default_sampling(greedy=True)
    kp, kd = jax.random.split(jax.random.PRNGKey(3))

    cache_s = M.init_kv_cache(cfg, 1, max_seq=64)
    f_s, logits_s, cache_s = G.prefill(cfg, qp, tokens, plen, cache_s, kp, sampling)
    out_s, n_s, _ = G.decode(
        cfg, qp, f_s, cache_s, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )

    mesh = build_mesh(MeshConfig(pp=2, ep=2), eight_devices)
    pb = PipelineBackend(cfg, qp, mesh)
    cache_p = pb.init_cache(1, 64)
    f_p, logits_p, cache_p = pb.prefill(tokens, plen, cache_p, kp, sampling)
    out_p, n_p, _ = pb.decode(
        f_p, cache_p, plen, jnp.int32(steps), kd, sampling, max_steps=steps
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_s), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_s))
